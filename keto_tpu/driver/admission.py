"""Adaptive admission control: an AIMD window over the batch check lane.

The batcher's queue bound is a *memory* defense, not a *latency* defense:
a queue sized for burst absorption (8×batch_size tuples) holds seconds of
backlog before the hard 429, and every queued batch tuple is latency the
device has already promised to somebody. This controller closes the loop
the way TCP does — additive increase, multiplicative decrease — keyed off
two live signals:

- the **slice service-time histogram** the stream width controller
  already records (``x/telemetry.DurationStats`` on the engine): p99 of
  the slices landed since the last tick. A slow device (thermal, fault
  delay, degraded CPU fallback) shows up here first.
- the **estimated queue delay**: batch-lane backlog divided by the
  batcher's observed dispatch throughput (EWMA over recent rounds). A
  *fast* device behind 3× offered load never shows slow slices — the
  damage is all queueing — so slice times alone would admit forever.

When either estimate exceeds the latency budget (default 4× the
``serve.stream_slice_target_ms`` the width controller steers toward),
the admitted batch-lane window shrinks multiplicatively and excess load
is shed at the door with 429 + ``Retry-After`` *before* it queues;
when healthy, the window recovers additively. The interactive lane is
never admission-limited — protecting its p99 is the whole point.

``retry_after_s`` grows with consecutive overloaded ticks (1→2→4→8 s),
so shed clients decongest roughly in proportion to how far gone the
server is, and SDK retry budgets (keto_tpu/httpclient.py) honor it.
"""

from __future__ import annotations

import threading
import time
from typing import Optional


class AdmissionController:
    """AIMD concurrency limiter for the batch check lane.

    ``stats`` is anything with ``tail(n) -> (observations_ms, count)``
    (``x/telemetry.DurationStats``); None disables the slice-time signal
    and leaves only the queue-delay estimate. ``tick`` is rate-limited to
    ``interval_s`` internally, so callers invoke it on every enqueue and
    dispatch round without cost concerns."""

    def __init__(
        self,
        stats=None,
        target_ms: float = 40.0,
        budget_ms: Optional[float] = None,
        min_window: int = 64,
        max_window: int = 32768,
        decrease: float = 0.5,
        increase: Optional[int] = None,
        interval_s: float = 0.25,
        time_fn=time.monotonic,
    ):
        self._stats = stats
        self.budget_ms = float(budget_ms) if budget_ms else 4.0 * float(target_ms)
        self.min_window = max(1, int(min_window))
        self.max_window = max(self.min_window, int(max_window))
        self._decrease = float(decrease)
        self._increase = int(increase) if increase else max(16, self.max_window // 64)
        self._interval_s = float(interval_s)
        self._time = time_fn
        self._lock = threading.Lock()  # guards: window, _last_tick, _seen, _rate, _consec_over, last_p99_ms, last_queue_delay_ms, decreases, increases
        #: admitted batch-lane window (tuples queued); starts open — the
        #: first overloaded tick shrinks it, idle ticks recover it
        self.window = self.max_window
        self._last_tick = -1e18
        self._seen = 0  # stats count high-water mark at the last tick
        self._rate: Optional[float] = None  # EWMA dispatch tuples/s
        self._consec_over = 0
        #: introspection counters (scraped via /metrics)
        self.last_p99_ms = 0.0
        self.last_queue_delay_ms = 0.0
        self.decreases = 0
        self.increases = 0

    # -- signals --------------------------------------------------------------

    def observe_round(self, n_tuples: int, wall_s: float) -> None:
        """The batcher reports every dispatch round (tuples served, wall
        seconds) — the throughput estimate the queue-delay signal needs."""
        if wall_s <= 0 or n_tuples <= 0:
            return
        rate = n_tuples / wall_s
        with self._lock:
            self._rate = rate if self._rate is None else 0.8 * self._rate + 0.2 * rate

    def tick(self, backlog: int = 0, now: Optional[float] = None) -> None:
        """One AIMD evaluation, rate-limited to ``interval_s``.
        ``backlog`` is the batch lane's queued tuple count."""
        now = self._time() if now is None else now
        with self._lock:
            if now - self._last_tick < self._interval_s:
                return
            self._last_tick = now

            # only slices landed since the last tick count: a quiet
            # period must not keep re-judging stale history
            p99: Optional[float] = None
            if self._stats is not None:
                _, count = self._stats.tail(0)
                delta = count - self._seen
                if delta > 0:
                    samples, _ = self._stats.tail(min(4096, delta))
                    self._seen = count
                    if samples:
                        vals = sorted(samples)
                        p99 = vals[min(len(vals) - 1, int(len(vals) * 0.99))]
                        self.last_p99_ms = p99

            queue_delay_ms: Optional[float] = None
            if self._rate:
                queue_delay_ms = backlog / self._rate * 1e3
                self.last_queue_delay_ms = queue_delay_ms

            overloaded = (p99 is not None and p99 > self.budget_ms) or (
                queue_delay_ms is not None and queue_delay_ms > self.budget_ms
            )
            if p99 is None and queue_delay_ms is None and backlog > self.window:
                # stalled device: backlog grows but nothing lands to
                # measure — treat silence plus a deep queue as overload
                overloaded = True

            if overloaded:
                self.window = max(self.min_window, int(self.window * self._decrease))
                self.decreases += 1
                self._consec_over += 1
            else:
                self.window = min(self.max_window, self.window + self._increase)
                self.increases += 1
                self._consec_over = 0

    # -- decisions ------------------------------------------------------------

    def retry_after_s(self) -> float:
        """Backoff advice for a shed request: doubles with consecutive
        overloaded ticks, capped at 8 s."""
        with self._lock:
            return float(min(8, 1 << min(self._consec_over, 3)))

    @property
    def overloaded(self) -> bool:
        with self._lock:
            return self._consec_over > 0

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "window": self.window,
                "budget_ms": round(self.budget_ms, 3),
                "last_p99_ms": round(self.last_p99_ms, 3),
                "last_queue_delay_ms": round(self.last_queue_delay_ms, 3),
                "rate_tuples_per_s": round(self._rate, 1) if self._rate else None,
                "increases": self.increases,
                "decreases": self.decreases,
                "overloaded": self._consec_over > 0,
            }
