"""HBM budget governor: OOM-safe device-state lifecycle.

The engine keeps four device-resident state families — bucket matrices
("snapshot"), the delta-overlay ELL ("overlay"), the 2-hop label arrays
("labels"), and the warm-compiled width ladder ("warmup") — and before
this module nothing accounted for or bounded them: a graph that outgrew
the chip surfaced as an unhandled XLA ``RESOURCE_EXHAUSTED`` mid-refresh,
the one failure family the supervised-maintenance / degraded-mode /
crash-safety work never covered.

``HbmGovernor`` closes that hole with three mechanisms:

1. **A ledger.** Every device allocation site registers its tagged size
   (``register``/``add``/``release``), so ``resident_bytes()`` is an
   honest account of what the engine has placed on device, scraped as
   ``keto_hbm_resident_bytes{tag=...}``. The budget comes from
   ``serve.hbm_budget_bytes`` (0 = auto: ``jax.Device.memory_stats()``
   ``bytes_limit`` minus headroom, with a conservative fallback when the
   backend exposes no stats — e.g. CPU).

2. **Plan-before-upload with a graceful eviction ladder.** Refresh,
   compaction, and label builds call ``plan(nbytes)`` BEFORE uploading
   (old + new state are co-resident during a snapshot swap, so the plan
   is against live residency, not a clean slate). When the plan does not
   fit, the governor walks a deterministic ladder of engine-supplied
   rungs instead of dying — drop the label arrays (coverage loss only:
   the router falls back to BFS), trim the warm compile-width ladder,
   shrink the overlay edge budget to force compaction — and only when
   every rung is spent does ``plan`` return False, which the engine turns
   into "refuse the refresh, serve stale, DEGRADED(memory_pressure)".
   Pressure clearing walks back UP the ladder (``maybe_restore``).

3. **Real-OOM containment.** ``is_resource_exhausted`` classifies an
   exception as device-memory exhaustion (XLA RESOURCE_EXHAUSTED, or the
   injected ``device-alloc`` ``oom`` fault from keto_tpu/x/faults.py);
   the engine's allocation seams evict one rung and retry once, then
   escalate through the existing bit-identical CPU fallback rather than
   crashing.

Lockstep meshes never evict asymmetrically: ladder decisions derive only
from replicated state (configured budget, planned sizes — identical on
every host by the lockstep contract), and the *reactive* paths that could
diverge (auto budget from per-host ``memory_stats``, OOM-triggered
eviction) are disabled in ``deterministic`` mode — multi-controller
engines construct the governor that way and keep their existing
fail-loudly behavior on device errors.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Optional

_log = logging.getLogger("keto_tpu.hbm")

#: fraction of the device's reported bytes_limit held back from the auto
#: budget (XLA needs workspace the ledger cannot see: program temporaries,
#: transfer staging, compiled executables)
DEFAULT_HEADROOM_FRAC = 0.08

#: auto-budget fallback when the backend exposes no memory stats (CPU
#: backend, very old runtimes) — conservative, and deterministic across
#: hosts, which is why lockstep meshes pin it
FALLBACK_BUDGET_BYTES = 4 << 30

#: restore a rung only while resident + planned stays under this fraction
#: of the budget — hysteresis so the ladder doesn't oscillate at the edge
RESTORE_FRAC = 0.7

#: the canonical ledger tags, in scrape order ("build" is the snapshot
#: pipeline's transient device footprint — the GovernedSorter's sort
#: workspace (keto_tpu/graph/device_build.py) and the label build's
#: frontier/cover matrices (keto_tpu/graph/label_build.py), registered
#: around the dispatches and released before the result installs;
#: "staging" is the
#: persistent entry-staging pool behind the donated dispatch buffers,
#: keto_tpu/check/tpu_engine.py _StagingPool — reconciled against the
#: pool's own accounting at every scrape)
TAGS = ("snapshot", "overlay", "labels", "reverse", "warmup", "build",
        "staging")

#: the eviction ladder rung names, in descent order (the final "refuse
#: the refresh" step is not a rung — it is plan() returning False).
#: "staging" goes first: dropping the entry-staging pool reverts to
#: per-slice allocation + device_put — pure churn cost, never coverage.
#: "reverse" drops the list layouts' device arrays — reverse queries
#: fall back to the CPU-reference lister bit-identically.
#: "tenant-lru" (appended by the registry in multi-tenant mode, AFTER
#: the engine's own rungs) evicts the coldest idle tenant's whole engine
#: — never the tenant currently dispatching — and its state faults back
#: in through the segmented snapcache on next touch
RUNGS = ("staging", "labels", "reverse", "warm-ladder", "overlay-budget",
         "tenant-lru")


def device_budget_bytes(
    headroom_frac: float = DEFAULT_HEADROOM_FRAC, deterministic: bool = False
) -> int:
    """The auto budget: the first local device's ``memory_stats()``
    ``bytes_limit`` minus headroom, or ``FALLBACK_BUDGET_BYTES`` when the
    backend exposes no stats. ``deterministic`` (lockstep meshes) skips
    the per-host probe entirely — hosts could report different limits,
    and ladder decisions must derive from replicated state only."""
    if deterministic:
        return FALLBACK_BUDGET_BYTES
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats() or {}
        limit = int(stats.get("bytes_limit") or 0)
        if limit > 0:
            return max(1, int(limit * (1.0 - headroom_frac)))
    except Exception:
        _log.info(
            "device memory stats unavailable; auto budget falls back to "
            "%d bytes", FALLBACK_BUDGET_BYTES, exc_info=True,
        )
    return FALLBACK_BUDGET_BYTES


def device_measured_bytes() -> Optional[int]:
    """Actual device-memory occupancy (``bytes_in_use``) when the backend
    reports it, else None — bench reports this next to its host-side
    estimate."""
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats() or {}
        v = stats.get("bytes_in_use")
        return int(v) if v is not None else None
    except Exception:
        return None


def is_resource_exhausted(exc: BaseException) -> bool:
    """Classify ``exc`` as device-memory exhaustion. Matches the XLA
    runtime's RESOURCE_EXHAUSTED surface (jaxlib raises XlaRuntimeError
    with the status name in the message), allocator out-of-memory texts,
    and the injected ``device-alloc`` oom fault (keto_tpu/x/faults.py) —
    NEVER plain Python MemoryError, which is a host failure the ladder
    cannot help."""
    from keto_tpu.x import faults

    if isinstance(exc, faults.OomInjected):
        return True
    msg = str(exc)
    return (
        "RESOURCE_EXHAUSTED" in msg
        or "Resource exhausted" in msg
        or ("out of memory" in msg.lower() and "XlaRuntimeError" in type(exc).__name__)
    )


class MemoryPressure(RuntimeError):
    """A planned allocation was refused with every rung spent — the
    engine serves stale and reports DEGRADED(memory_pressure)."""


class _Rung:
    __slots__ = ("name", "evict", "restore", "evicted")

    def __init__(self, name: str, evict: Callable[[], int], restore: Callable[[], None]):
        self.name = name
        self.evict = evict  # returns estimated bytes freed (logging only)
        self.restore = restore
        self.evicted = False


class HbmGovernor:
    """Ledger + budget + eviction-ladder policy (see module docstring).

    Thread-safe; rung callables run under the governor's re-entrant lock
    and may call back into ``release``/``register``. The engine owns the
    rung semantics — the governor only owns the order and the account."""

    def __init__(
        self,
        budget_bytes: int = 0,
        *,
        stats=None,
        deterministic: bool = False,
        headroom_frac: float = DEFAULT_HEADROOM_FRAC,
    ):
        self._lock = threading.RLock()
        self._ledger: dict[str, int] = {}
        # per-shard breakdown of tags the sharded engine mode registers
        # (keto_tpu/parallel/sharded.py): tag → [bytes per shard]. Tags
        # with no breakdown (replicated/transient state) spread evenly
        # over the shards in the per-shard view.
        self._n_shards = 1
        self._shard_ledger: dict[str, list] = {}
        self._rungs: list[_Rung] = []
        self._depth = 0  # rungs currently evicted (prefix of _rungs)
        self._stats = stats  # MaintenanceStats or None
        self.deterministic = bool(deterministic)
        self.configured_budget = int(budget_bytes)
        self.budget_bytes = (
            int(budget_bytes)
            if budget_bytes > 0
            else device_budget_bytes(headroom_frac, deterministic=deterministic)
        )
        self.evictions_by_rung: dict[str, int] = {r: 0 for r in RUNGS}
        self.restores = 0
        self.refusals = 0
        self.forced_allocs = 0  # over-budget allocations allowed (cold boot)
        self.oom_events = 0
        self.oom_recoveries = 0
        self._gauge("hbm_budget_bytes", self.budget_bytes)
        self._gauge("hbm_resident_bytes", 0)
        self._gauge("hbm_rung", 0)

    # -- stats plumbing ------------------------------------------------------

    def _gauge(self, key: str, value) -> None:
        if self._stats is not None:
            self._stats.set_gauge(key, value)

    def _incr(self, key: str) -> None:
        if self._stats is not None:
            self._stats.incr(key)

    def _publish_locked(self) -> None:
        self._gauge("hbm_resident_bytes", sum(self._ledger.values()))
        self._gauge("hbm_rung", self._depth)

    # -- ledger --------------------------------------------------------------

    def register(self, tag: str, nbytes: int) -> None:
        """Record ``tag``'s device residency as exactly ``nbytes``
        (replacing any prior figure — a snapshot swap re-registers its
        family once the old arrays are unreferenced)."""
        with self._lock:
            self._ledger[tag] = max(0, int(nbytes))
            self._publish_locked()

    def add(self, tag: str, nbytes: int) -> None:
        """Additive registration (the warm ladder accumulates per width)."""
        with self._lock:
            self._ledger[tag] = self._ledger.get(tag, 0) + max(0, int(nbytes))
            self._publish_locked()

    def release(self, tag: str) -> int:
        """Drop ``tag`` from the ledger; returns the bytes released."""
        with self._lock:
            freed = self._ledger.pop(tag, 0)
            self._shard_ledger.pop(tag, None)
            self._publish_locked()
            return freed

    # -- per-shard ledger (sharded serving) ----------------------------------

    def set_shard_count(self, n: int) -> None:
        """Declare the graph-axis shard count the per-shard ledger and
        per-shard budget slices divide by. Set once by the sharded
        engine at construction."""
        with self._lock:
            self._n_shards = max(1, int(n))
            self._shard_ledger = {}

    def register_shards(self, tag: str, per_shard) -> None:
        """Record ``tag``'s per-shard owned bytes (the unpadded rows each
        shard actually holds). The global figure for ``tag`` is still
        whatever ``register`` recorded — padding makes the two differ;
        the per-shard view is the honest hot-shard account."""
        with self._lock:
            vals = [max(0, int(v)) for v in per_shard]
            if len(vals) < self._n_shards:
                vals += [0] * (self._n_shards - len(vals))
            self._shard_ledger[tag] = vals[: self._n_shards]

    def shard_resident_bytes(self) -> list:
        """Per-shard resident bytes: tracked tags contribute their owned
        slice, untracked tags spread evenly (replicated / transient
        state is on every shard's devices)."""
        with self._lock:
            return self._shard_resident_locked()

    def _shard_resident_locked(self) -> list:
        n = self._n_shards
        out = [0] * n
        for tag, total in self._ledger.items():
            per = self._shard_ledger.get(tag)
            if per is None:
                for s in range(n):
                    out[s] += total // n
            else:
                for s in range(n):
                    out[s] += per[s]
        return out

    def resident_bytes(self) -> int:
        with self._lock:
            return sum(self._ledger.values())

    def ledger(self) -> dict[str, int]:
        with self._lock:
            return dict(self._ledger)

    def set_budget_bytes(self, nbytes: int) -> None:
        """Operator/test seam: re-pin the budget at runtime (pressure
        rehearsal, live retuning). Restores are NOT applied here — the
        next successful plan walks back up the ladder."""
        with self._lock:
            self.budget_bytes = max(1, int(nbytes))
            self._gauge("hbm_budget_bytes", self.budget_bytes)

    # -- the ladder ----------------------------------------------------------

    def attach_rungs(self, rungs) -> None:
        """``rungs`` is an ordered list of ``(name, evict_fn, restore_fn)``
        — descent order. Attached once by the engine at construction."""
        with self._lock:
            self._rungs = [_Rung(n, e, r) for n, e, r in rungs]
            self._depth = 0

    def append_rung(
        self, name: str, evict: Callable[[], int], restore: Callable[[], None]
    ) -> None:
        """Append one rung BELOW the engine's ladder (``attach_rungs``
        replaces the whole ladder, and the engine attaches its rungs at
        construction — this is the seam for rungs owned by someone else,
        e.g. the registry's cross-tenant ``tenant-lru`` rung). Appended
        rungs run under the same lock discipline and are accounted in
        ``evictions_by_rung`` like any other. Idempotent per name."""
        with self._lock:
            if any(r.name == name for r in self._rungs):
                return
            self._rungs.append(_Rung(name, evict, restore))

    @property
    def rung_depth(self) -> int:
        """How many rungs are currently evicted (0 = full service)."""
        with self._lock:
            return self._depth

    def fits(self, nbytes: int) -> bool:
        with self._lock:
            return sum(self._ledger.values()) + max(0, int(nbytes)) <= self.budget_bytes

    def _evict_next_locked(self, reason: str) -> Optional[str]:
        if self._depth >= len(self._rungs):
            return None
        rung = self._rungs[self._depth]
        self._depth += 1
        rung.evicted = True
        try:
            freed = int(rung.evict() or 0)
        except Exception:
            _log.warning("eviction rung %r failed; continuing down the ladder",
                         rung.name, exc_info=True)
            freed = 0
        self.evictions_by_rung[rung.name] = self.evictions_by_rung.get(rung.name, 0) + 1
        self._incr("hbm_evictions")
        self._publish_locked()
        _log.warning(
            "HBM pressure (%s): evicted rung %r (~%d bytes freed; rung %d/%d, "
            "resident %d / budget %d)",
            reason, rung.name, freed, self._depth, len(self._rungs),
            sum(self._ledger.values()), self.budget_bytes,
        )
        return rung.name

    def evict_one(self, reason: str = "") -> Optional[str]:
        """Descend one rung (the real-OOM containment path). Returns the
        rung name, or None when the ladder is spent. Deterministic mode
        (lockstep meshes) never evicts reactively — per-host OOM timing
        is not replicated state."""
        if self.deterministic:
            return None
        with self._lock:
            return self._evict_next_locked(reason or "oom")

    def plan(
        self,
        nbytes: int,
        *,
        what: str = "",
        evict: bool = True,
        per_shard=None,
    ) -> bool:
        """Will ``nbytes`` more fit? Walks the eviction ladder (in order,
        at most once per rung) until it does; returns False only with
        every rung spent and the plan still over budget — the caller
        refuses the work (or, for optional work like warming one more
        width, simply skips it with ``evict=False``).

        ``per_shard`` (sharded serving) additionally holds each shard's
        incoming bytes against that shard's slice of the budget — the
        HOTTEST shard is the binding constraint, and any rung the walk
        evicts is MESH-WIDE (one ladder for every shard), so a single
        over-full shard can never silently diverge the ladder."""
        need = max(0, int(nbytes))

        def over_locked() -> bool:
            if sum(self._ledger.values()) + need > self.budget_bytes:
                return True
            if per_shard is not None and self._n_shards > 1:
                shard_budget = self.budget_bytes // self._n_shards
                resident = self._shard_resident_locked()
                for s in range(self._n_shards):
                    add = int(per_shard[s]) if s < len(per_shard) else 0
                    if resident[s] + add > shard_budget:
                        return True
            return False

        with self._lock:
            while over_locked():
                if not evict or self._evict_next_locked(f"planning {what or 'allocation'}") is None:
                    return False
            return True

    def note_refused(self) -> None:
        """Count an actual refusal (the engine declined a refresh and is
        serving stale) — distinct from a failed plan the caller then
        force-allows (cold boot) or simply skips (optional warmup)."""
        with self._lock:
            self.refusals += 1
        self._incr("hbm_refusals")

    def note_forced(self, what: str, nbytes: int) -> None:
        """Account an allocation that proceeded over budget (cold boot:
        there is no stale snapshot to serve instead)."""
        with self._lock:
            self.forced_allocs += 1
        self._incr("hbm_forced_allocs")
        _log.warning(
            "HBM budget exceeded but no stale state to serve: allowing %s "
            "(%d bytes) over the %d-byte budget", what, nbytes, self.budget_bytes,
        )

    def maybe_restore(self, planned: int = 0) -> int:
        """Walk back UP the ladder while there is clear headroom
        (resident + planned under RESTORE_FRAC of budget). Called after a
        successful refresh; returns the number of rungs restored."""
        restored = 0
        with self._lock:
            while self._depth > 0:
                if sum(self._ledger.values()) + max(0, int(planned)) > (
                    RESTORE_FRAC * self.budget_bytes
                ):
                    break
                rung = self._rungs[self._depth - 1]
                try:
                    rung.restore()
                except Exception:
                    _log.warning("restore of rung %r failed; staying evicted",
                                 rung.name, exc_info=True)
                    break
                rung.evicted = False
                self._depth -= 1
                restored += 1
                self.restores += 1
                self._incr("hbm_restores")
                _log.info("HBM pressure cleared: restored rung %r (rung %d/%d)",
                          rung.name, self._depth, len(self._rungs))
            if restored:
                self._publish_locked()
        return restored

    # -- OOM accounting ------------------------------------------------------

    #: optional anomaly hook (the flight recorder's OOM trigger seam);
    #: invoked outside the governor lock, exceptions contained
    on_oom: Optional[Callable[[str], None]] = None

    def note_oom(self, what: str = "") -> None:
        with self._lock:
            self.oom_events += 1
        self._incr("oom_events")
        _log.warning("device RESOURCE_EXHAUSTED at %s", what or "unknown site")
        cb = self.on_oom
        if cb is not None:
            try:
                cb(what)
            except Exception:
                _log.warning("on_oom hook failed", exc_info=True)

    def note_oom_recovered(self) -> None:
        with self._lock:
            self.oom_recoveries += 1
        self._incr("oom_recoveries")

    # -- introspection -------------------------------------------------------

    def snapshot(self) -> dict:
        """Operator/metrics view: budget, ledger, ladder position, and
        the counters — ``keto_hbm_*`` / ``keto_oom_*`` read this."""
        with self._lock:
            return {
                "budget_bytes": self.budget_bytes,
                "configured_budget_bytes": self.configured_budget,
                "resident_bytes": sum(self._ledger.values()),
                "ledger": dict(self._ledger),
                "shards": (
                    self._shard_resident_locked() if self._n_shards > 1 else []
                ),
                "shard_count": self._n_shards,
                "rung": self._depth,
                "rungs": [r.name for r in self._rungs],
                "evicted": [r.name for r in self._rungs if r.evicted],
                "evictions_by_rung": dict(self.evictions_by_rung),
                "restores": self.restores,
                "refusals": self.refusals,
                "forced_allocs": self.forced_allocs,
                "oom_events": self.oom_events,
                "oom_recoveries": self.oom_recoveries,
            }
