"""Dependency-injection registry.

Mirrors the reference's ``driver.Registry`` contract and its lazily
constructed singletons (reference internal/driver/registry.go:26-58,
registry_default.go:158-170): config in, everything else memoized on first
access. ``permission_engine()`` is the seam where the TPU check engine plugs
in instead of the recursive one (reference registry_default.go:158-163 — the
spot the survey marks as "where a TPU CheckEngine plugs in").
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Optional

from keto_tpu import namespace as namespace_pkg
from keto_tpu.check.engine import CheckEngine
from keto_tpu.config.provider import Config
from keto_tpu.driver.batch import CheckBatcher
from keto_tpu.expand.engine import ExpandEngine
from keto_tpu.persistence.memory import MemoryPersister
from keto_tpu.version import __version__ as VERSION
from keto_tpu.x.logging import new_logger


class Registry:
    def __init__(self, config: Config, network_id: str = "default"):
        self._config = config
        self._network_id = network_id
        self._lock = threading.RLock()  # guards: _singletons, _promoted
        self._singletons: dict[str, Any] = {}
        # fleet promotion flag: a process booted as serve.role=replica
        # that won the lease election serves as a primary from then on —
        # is_replica() consults this at call time, so the write path,
        # group-commit construction and REST refusals all flip without
        # a rebuild (keto_tpu/fleet/controller.py)
        self._promoted = False
        # engines see namespace hot-reloads through this indirection
        config.on_namespace_change(self._on_namespace_change)

    def _memo(self, key: str, build: Callable[[], Any]) -> Any:
        got = self._singletons.get(key)
        if got is None:
            with self._lock:
                got = self._singletons.get(key)
                if got is None:
                    got = build()
                    self._singletons[key] = got
        return got

    def _on_namespace_change(self) -> None:
        # nothing to invalidate: stores/engines resolve the namespace
        # manager through the callable below on every use
        pass

    def peek(self, key: str):
        """An already-built singleton, or None — shutdown paths use this
        to avoid constructing a dependency just to tear it down."""
        return self._singletons.get(key)

    # -- leaf dependencies ---------------------------------------------------

    def config(self) -> Config:
        return self._config

    def logger(self):
        return self._memo(
            "logger",
            lambda: new_logger(
                self._config.get("log.level", "info"), self._config.get("log.format", "text")
            ),
        )

    def namespace_manager(self) -> namespace_pkg.Manager:
        return self._config.namespace_manager()

    def namespaces_source(self) -> Callable[[], namespace_pkg.Manager]:
        return self._config.namespace_manager

    # -- storage -------------------------------------------------------------

    def is_replica(self) -> bool:
        """True when this process serves as a read replica
        (``serve.role: replica``): no SQL access, state fed by the
        primary's Watch changefeed (keto_tpu/replica/). A replica the
        fleet controller promoted reads False from then on — every
        write-path branch consults this at call time."""
        if self._promoted:
            return False
        return str(self._config.get("serve.role", "primary")) == "replica"

    def _build_direct_store(self):
        """A tuple store with direct SQL (or in-process memory) access,
        built from the configured dsn — the primary's store, and the
        store a promoted replica installs over the durable-watermark
        handoff (promote_to_primary)."""
        dsn = self._config.dsn
        if dsn == "memory":
            store = MemoryPersister(
                self.namespaces_source(), network_id=self._network_id
            )
        elif dsn.startswith("sqlite://"):
            from keto_tpu.persistence.sqlite import SQLitePersister

            store = SQLitePersister(
                dsn, self.namespaces_source(), network_id=self._network_id
            )
        elif dsn.startswith(("postgres://", "postgresql://", "cockroach://")):
            from keto_tpu.persistence.postgres import PostgresPersister

            store = PostgresPersister(
                dsn, self.namespaces_source(), network_id=self._network_id
            )
        else:
            raise ValueError(f"unsupported dsn {dsn!r}")
        # idempotency keys dedup write retries for this long before GC
        store.idempotency_ttl_s = float(
            self._config.get("serve.idempotency_ttl_s", 86400.0)
        )
        # time-based GC of the durable change logs feeding /watch and
        # the delta path (serve.watch_log_retention_s; 0 disables)
        store.watch_log_retention_s = float(
            self._config.get("serve.watch_log_retention_s", 3600.0)
        )
        # one piggybacked watch-GC pass prunes at most this many rows
        # (a group commit must never stall behind an unbounded sweep)
        store.watch_gc_max_rows = int(
            self._config.get("serve.watch_gc_max_rows", 10000)
        )
        return store

    def relation_tuple_manager(self):
        def build():
            if self.is_replica():
                # replicas hold NO SQL access: the store is a local
                # materialization of the primary's commit log, installed
                # by the replica controller (dsn is ignored by design)
                from keto_tpu.replica.store import ReplicaStore

                store = ReplicaStore(
                    self.namespaces_source(), network_id=self._network_id
                )
                store.idempotency_ttl_s = float(
                    self._config.get("serve.idempotency_ttl_s", 86400.0)
                )
                # a replica's own logs feed chained watchers and its
                # engine's delta path: same retention hygiene as primary
                store.watch_log_retention_s = float(
                    self._config.get("serve.watch_log_retention_s", 3600.0)
                )
                store.watch_gc_max_rows = int(
                    self._config.get("serve.watch_gc_max_rows", 10000)
                )
                return store
            return self._build_direct_store()

        return self._memo("manager", build)

    def write_coordinator(self):
        """The group-commit coordinator
        (keto_tpu/driver/group_commit.py): batches concurrent write
        transactions into one durable ``transact_many`` group. ``None``
        on replicas (read-only) and when
        ``serve.group_commit_enabled: false`` — callers fall back to
        per-commit ``transact_relation_tuples``."""
        if self.is_replica():
            return None
        if not bool(self._config.get("serve.group_commit_enabled", True)):
            return None

        def build():
            from keto_tpu.driver.group_commit import GroupCommitCoordinator

            co = GroupCommitCoordinator(
                self.relation_tuple_manager(),
                max_writers=int(
                    self._config.get("serve.group_commit_max_writers", 128)
                ),
                window_ms=float(
                    self._config.get("serve.group_commit_window_ms", 2.0)
                ),
                max_pending=int(
                    self._config.get("serve.group_commit_max_pending", 4096)
                ),
                wait_histogram=self.metrics().histogram(
                    "keto_group_commit_wait_seconds",
                    "Time a writer spent queued in the group-commit "
                    "coordinator before its group's durable transaction "
                    "started (the coalescing cost the "
                    "serve.group_commit_window_ms knob trades against "
                    "fsyncs).",
                    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
                             0.05, 0.1, 0.25, 1.0),
                ),
                batch_histogram=self.metrics().histogram(
                    "keto_group_commit_batch_size",
                    "Writers coalesced per durable group transaction "
                    "(1 = no batching benefit; the ceiling is "
                    "serve.group_commit_max_writers).",
                    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256),
                ),
            )
            co.start()
            return co

        return self._memo("group_commit", build)

    def transact_writes(self):
        """The write-path entry point the serving layers call: a
        ``(insert, delete, idempotency_key=None) -> TransactResult``
        callable routed through the group-commit coordinator when one is
        enabled, else straight to the store's solo transact. Per-writer
        results (snaptoken, replay flag) are identical either way."""
        co = self.write_coordinator()
        if co is not None:
            def route(insert, delete, idempotency_key=None):
                return co.transact(
                    insert, delete, idempotency_key=idempotency_key
                )

            return route
        store = self.relation_tuple_manager()

        def solo(insert, delete, idempotency_key=None):
            return store.transact_relation_tuples(
                insert, delete, idempotency_key=idempotency_key
            )

        return solo

    def replica_controller(self):
        """The replica lifecycle owner (keto_tpu/replica/controller.py):
        bootstrap from the primary's /snapshot/export, the supervised
        Watch feed with its durable applied-watermark, the 412 read gate,
        and the Watch-invalidated check cache. ``None`` on a primary —
        serving layers branch on that."""
        if not self.is_replica():
            return None

        def build():
            from keto_tpu.replica.controller import ReplicaController

            engine = self.permission_engine()
            return ReplicaController(
                self.relation_tuple_manager(),
                self.permission_engine,
                str(self._config.get("serve.primary_url", "")),
                # replication-aware tracing: applies join the writer's
                # trace, and the commit→visible delay histogram carries
                # the writer's trace id as its exemplar
                tracer=self.tracer(),
                apply_delay_histogram=self.metrics().histogram(
                    "keto_replication_apply_delay_seconds",
                    "Replica mode: wall time from the primary's commit to "
                    "the change being visible through this replica's 412 "
                    "gate (cross-clock; slowest sample carries the "
                    "writer's trace_id exemplar).",
                    buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                             0.5, 1.0, 2.5, 5.0, 15.0, 60.0),
                ),
                replica_dir=str(self._config.get("serve.replica_dir", "") or ""),
                snapshot_cache_dir=str(
                    self._config.get("serve.snapshot_cache_dir", "") or ""
                ),
                staleness_wait_ms=float(
                    self._config.get("serve.staleness_wait_ms", 200.0)
                ),
                staleness_budget_s=float(
                    self._config.get("serve.replica_staleness_budget_s", 30.0)
                ),
                checkcache_entries=int(
                    self._config.get("serve.checkcache_entries", 65536)
                ),
                probe_s=max(
                    0.25,
                    float(self._config.get("serve.watch_poll_ms", 100.0)) / 1e3,
                ),
                stats=getattr(engine, "maintenance", None),
            )

        return self._memo("replica", build)

    # -- fleet control plane (keto_tpu/fleet/) -------------------------------

    def fleet_enabled(self) -> bool:
        return bool(self._config.get("serve.fleet_enabled", False))

    def _fleet_lease_store(self):
        """The store the lease election runs through. Replicas hold no
        tuple-store SQL access by design, so the lease channel is a
        DEDICATED persister built from the dsn — the one SQL surface a
        replica touches pre-promotion. Primaries with a memory dsn share
        the tuple store itself (same in-process state)."""

        def build():
            if self._config.dsn == "memory" and not self.is_replica():
                return self.relation_tuple_manager()
            return self._build_direct_store()

        return self._memo("fleet_lease_store", build)

    def fleet_controller(self):
        """The lease-election / membership / promotion loop
        (keto_tpu/fleet/controller.py), or None without
        ``serve.fleet_enabled``. Started by the daemon after the serving
        components exist."""
        if not self.fleet_enabled():
            return None

        def build():
            import os
            import socket

            from keto_tpu.fleet.controller import FleetController

            node_id = str(self._config.get("serve.fleet_node_id", "") or "")
            if not node_id:
                node_id = f"{socket.gethostname()}-{os.getpid()}"
            role = "replica" if self.is_replica() else "primary"

            def watermark_fn():
                rep = self.peek("replica")
                if rep is not None and self.is_replica():
                    return int(rep.watermark)
                store = self.peek("manager")
                try:
                    return int(store.watermark()) if store is not None else 0
                except Exception:
                    return 0

            def lag_fn():
                rep = self.peek("replica")
                if rep is not None and self.is_replica():
                    try:
                        return float(rep.lag_s())
                    except Exception:
                        return 0.0
                return 0.0

            def fence_fn(epoch):
                # primaries fence their own store on (re)acquire; a
                # promoted replica's new store was already fenced inside
                # promote_to_primary before this runs
                store = self.peek("manager")
                if store is not None and hasattr(store, "fence_epoch"):
                    store.fence_epoch = int(epoch)

            return FleetController(
                self._fleet_lease_store(),
                node_id,
                advertise_url=str(
                    self._config.get("serve.fleet_advertise_url", "") or ""
                ),
                role=role,
                lease_ttl_s=float(
                    self._config.get("serve.fleet_lease_ttl_s", 2.0)
                ),
                heartbeat_s=float(
                    self._config.get("serve.fleet_heartbeat_s", 0.5)
                ),
                promotion_grace_s=float(
                    self._config.get("serve.fleet_promotion_grace_s", 0.5)
                ),
                lag_budget_s=float(
                    self._config.get("serve.replica_staleness_budget_s", 30.0)
                ),
                watermark_fn=watermark_fn,
                lag_fn=lag_fn,
                on_promote=self.promote_to_primary,
                fence_fn=fence_fn,
                stats=getattr(self.peek("permission_engine"), "maintenance", None),
            )

        return self._memo("fleet", build)

    def promote_to_primary(self, epoch: int) -> None:
        """The durable-watermark handoff: called by the fleet controller
        when this replica wins the lease at ``epoch``. The replica's
        applied watermark IS a store watermark over the same tuple
        history, so the device snapshot stays valid — only the backing
        store swaps:

        1. build a direct SQL store from the dsn, fenced at the won
           epoch BEFORE any write can route through it,
        2. install it as the ``manager`` singleton and into the engine
           (``set_store`` — no snapshot rebuild; the next maintenance
           pass catches up via the delta path),
        3. retire the replication feed (the primary it followed is
           dead) and detach it from health derivation,
        4. flip ``_promoted`` so is_replica() — and with it the write
           coordinator, REST/gRPC write refusals, and the 412 gate
           branch — reads primary from then on.

        Idempotent: the controller's install-retry path (crash between
        winning and installing) re-runs this at the same epoch."""
        with self._lock:
            if self._promoted:
                store = self._singletons.get("manager")
                if store is not None and hasattr(store, "fence_epoch"):
                    store.fence_epoch = int(epoch)
                return
            new_store = self._build_direct_store()
            new_store.fence_epoch = int(epoch)
            old_store = self._singletons.get("manager")
            self._singletons["manager"] = new_store
            self._promoted = True
        self.logger().warning(
            "fleet promotion: serving as primary at epoch %d "
            "(store handoff at watermark %s)",
            int(epoch), new_store.watermark(),
        )
        engine = self.peek("permission_engine")
        if engine is not None and hasattr(engine, "set_store"):
            engine.set_store(new_store)
        # the replication feed followed a primary that no longer owns
        # the lease: stop it without blocking the promotion path (its
        # threads are daemons; a hung HTTP read dies with them)
        rep = None
        with self._lock:
            rep = self._singletons.pop("replica", None)
        if rep is not None:
            try:
                rep.stop(timeout=0.5)
            except Exception:
                self.logger().warning(
                    "replica controller stop failed during promotion",
                    exc_info=True,
                )
        monitor = self.peek("health_monitor")
        if monitor is not None:
            monitor.set_replica(None)
        # the watch hub polled the old replica store, which stops
        # advancing now: close it so chained watchers reconnect and the
        # next subscriber gets a hub over the new store
        hub = None
        with self._lock:
            hub = self._singletons.pop("watch_hub", None)
        if hub is not None:
            try:
                hub.close()
            except Exception:
                self.logger().warning(
                    "watch hub close failed during promotion", exc_info=True
                )
        if old_store is not None and old_store is not new_store:
            closer = getattr(old_store, "close", None)
            if closer is not None:
                try:
                    closer()
                except Exception:
                    self.logger().warning(
                        "old replica store close failed during promotion",
                        exc_info=True,
                    )

    def reshard_coordinator(self):
        """The live shard split/merge coordinator
        (keto_tpu/fleet/reshard.py): builds a complete engine at the
        target graph-mesh width while the current engine keeps serving,
        then installs it atomically under the registry lock."""

        def build():
            from keto_tpu.fleet.reshard import ReshardCoordinator

            def current():
                # shard_count is a property on the TPU engine (0 = not
                # sharded) and absent on the oracle fallback
                eng = self.peek("permission_engine")
                val = getattr(eng, "shard_count", None)
                if callable(val):
                    val = val()
                try:
                    return max(1, int(val)) if val is not None else 1
                except (TypeError, ValueError):
                    return 1

            def build_new(target):
                eng = self._build_permission_engine(
                    mesh_graph_override=(None if target <= 1 else target)
                )
                # warm the snapshot BEFORE install so the handoff swaps
                # one serving engine for another, not for a cold build
                if hasattr(eng, "snapshot"):
                    eng.snapshot()
                return eng

            return ReshardCoordinator(
                build_new, self._install_resharded_engine, current_fn=current
            )

        return self._memo("reshard", build)

    def _install_resharded_engine(self, new_engine, target: int) -> None:
        """Swap the serving engine for the resharded one. In-flight
        rounds finish on the old engine (the batcher reads its engine
        attribute per dispatch); the old engine closes only after the
        batcher drains, off this thread."""
        with self._lock:
            old = self._singletons.get("permission_engine")
            self._singletons["permission_engine"] = new_engine
            # lazily rebuilt over the new engine on next use
            self._singletons.pop("expand_engine", None)
            self._singletons.pop("list_engine", None)
        batcher = self.peek("check_batcher")
        if batcher is not None and hasattr(batcher, "set_engine"):
            batcher.set_engine(new_engine)
        monitor = self.peek("health_monitor")
        if monitor is not None and hasattr(monitor, "set_engine"):
            monitor.set_engine(new_engine)
        if old is not None and old is not new_engine and hasattr(old, "close"):
            def close_old():
                try:
                    if batcher is not None and hasattr(batcher, "drain"):
                        batcher.drain(30.0)
                    old.close()
                except Exception:
                    self.logger().warning(
                        "old engine close failed after reshard", exc_info=True
                    )

            threading.Thread(
                target=close_old, name="reshard-engine-close", daemon=True
            ).start()

    def autoscaler(self):
        """The SLO-burn autoscale loop (keto_tpu/fleet/autoscale.py), or
        None without ``serve.fleet_autoscale_enabled``. Advisory unless
        a spawner is attached (the daemon wires one when launched with a
        replica argv template; tests attach their own)."""
        if not bool(self._config.get("serve.fleet_autoscale_enabled", False)):
            return None

        def build():
            from keto_tpu.fleet.autoscale import Autoscaler

            def signals():
                # one broken component must not blind the others: each
                # signal reads under its own guard, logging the failure
                # (a stuck-at-default signal biases decisions, silently)
                out = {
                    "availability_burn_rate": 0.0,
                    "latency_burn_rate": 0.0,
                    "queue_depth_ratio": 0.0,
                    "hbm_rung": 0,
                    "replica_lag_s": 0.0,
                }
                slo = self.peek("slo")
                if slo is not None:
                    try:
                        rep = slo.to_json()
                        burns = [
                            float(w.get("availability_burn_rate", 0) or 0)
                            for w in rep.get("windows", [])
                        ]
                        lat = [
                            float(w.get("latency_burn_rate", 0) or 0)
                            for w in rep.get("windows", [])
                        ]
                        if burns:
                            out["availability_burn_rate"] = max(burns)
                        if lat:
                            out["latency_burn_rate"] = max(lat)
                    except Exception:
                        self.logger().warning(
                            "autoscale burn-rate signal read failed",
                            exc_info=True,
                        )
                b = self.peek("check_batcher")
                if b is not None:
                    depth = float(getattr(b, "queue_depth", 0) or 0)
                    cap = float(getattr(b, "max_pending", 0) or 0)
                    if cap > 0:
                        out["queue_depth_ratio"] = depth / cap
                gov = getattr(self.peek("permission_engine"), "hbm", None)
                if gov is not None:
                    try:
                        out["hbm_rung"] = int(gov.snapshot().get("rung", 0) or 0)
                    except Exception:
                        self.logger().warning(
                            "autoscale hbm-rung signal read failed",
                            exc_info=True,
                        )
                rep = self.peek("replica")
                if rep is not None:
                    try:
                        out["replica_lag_s"] = float(rep.lag_s())
                    except Exception:
                        self.logger().warning(
                            "autoscale replica-lag signal read failed",
                            exc_info=True,
                        )
                return out

            return Autoscaler(
                signals,
                min_replicas=int(
                    self._config.get("serve.fleet_min_replicas", 0)
                ),
                max_replicas=int(
                    self._config.get("serve.fleet_max_replicas", 4)
                ),
                sustain_s=float(
                    self._config.get("serve.fleet_scale_sustain_s", 5.0)
                ),
                cooldown_s=float(
                    self._config.get("serve.fleet_scale_cooldown_s", 30.0)
                ),
            )

        return self._memo("autoscaler", build)

    # -- engines -------------------------------------------------------------

    def permission_engine(self):
        """The check engine: TPU snapshot engine when the store supports it
        and config allows, else the recursive oracle."""
        return self._memo("permission_engine", self._build_permission_engine)

    def _build_permission_engine(self, mesh_graph_override: Optional[int] = None):
        """Construct a check engine from config. ``mesh_graph_override``
        replaces ``serve.mesh_graph`` — the live-reshard seam
        (keto_tpu/fleet/reshard.py): the coordinator builds a complete
        engine at the target shard count while the current one keeps
        serving, then installs it via _install_resharded_engine."""

        def build():
            backend = self._config.get("engine.backend", "auto")
            store = self.relation_tuple_manager()
            if backend != "oracle" and hasattr(store, "snapshot_rows"):
                # persistent XLA compilation cache: compiled kernel
                # geometries survive restarts, so the boot warmup
                # (Daemon._warm_snapshot → engine.warm_compile) hits disk
                # instead of recompiling the whole width ladder
                cc_dir = str(self._config.get("serve.compile_cache_dir", "") or "")
                if cc_dir:
                    try:
                        import jax

                        jax.config.update("jax_compilation_cache_dir", cc_dir)
                        jax.config.update(
                            "jax_persistent_cache_min_compile_time_secs", 0.0
                        )
                    except Exception:
                        self.logger().warning(
                            "persistent compilation cache unavailable; "
                            "continuing without it", exc_info=True,
                        )
                from keto_tpu.check.tpu_engine import TpuCheckEngine

                # multi-chip serving (keto_tpu/parallel/sharded.py): a
                # (graph, data) mesh over the local devices; graph > 1
                # partitions the CSR/bitmap/label rows into row-range
                # shards served by the explicit shard_map program
                # (serve.mesh_sharded=false keeps the legacy GSPMD path)
                mesh = None
                mesh_sharded = False
                mesh_graph = int(self._config.get("serve.mesh_graph", 1))
                if mesh_graph_override is not None:
                    mesh_graph = int(mesh_graph_override)
                mesh_data = int(self._config.get("serve.mesh_data", 0))
                if mesh_graph > 1 or mesh_data > 1:
                    from keto_tpu.parallel import make_mesh

                    mesh = make_mesh(
                        graph=max(1, mesh_graph),
                        data=mesh_data if mesh_data > 0 else None,
                    )
                    mesh_sharded = bool(
                        self._config.get("serve.mesh_sharded", True)
                    )
                engine = TpuCheckEngine(
                    store,
                    self.namespaces_source(),
                    mesh=mesh,
                    shard_rows=mesh is not None,
                    sharded=mesh_sharded,
                    it_cap=int(self._config.get("engine.it_cap", 4096)),
                    peel_seed_cap=float(self._config.get("engine.peel_seed_cap", 4.0)),
                    sync_rebuild_budget_s=float(
                        self._config.get("engine.sync_rebuild_budget_s", 0.25)
                    ),
                    stream_slice_target_ms=float(
                        self._config.get("serve.stream_slice_target_ms", 40.0)
                    ),
                    overlay_edge_budget=int(
                        self._config.get("serve.overlay_edge_budget", 4096)
                    ),
                    fold_segment_edges=int(
                        self._config.get("serve.fold_segment_edges", 2048)
                    ),
                    snapshot_cache_dir=(
                        str(self._config.get("serve.snapshot_cache_dir", "") or "")
                        or None
                    ),
                    degraded_probe_s=float(
                        self._config.get("serve.degraded_probe_s", 5.0)
                    ),
                    labels_enabled=bool(
                        self._config.get("serve.labels_enabled", True)
                    ),
                    labels_max_width=int(
                        self._config.get("serve.labels_max_width", 64)
                    ),
                    labels_landmarks=int(
                        self._config.get("serve.labels_landmarks", 0)
                    ),
                    labels_device_build=bool(
                        self._config.get("serve.labels_device_build", True)
                    ),
                    labels_min_gain=float(
                        self._config.get("serve.labels_min_gain", 0.0)
                    ),
                    labels_batch=int(
                        self._config.get("serve.labels_batch", 64)
                    ),
                    labels_device_min_edges=int(
                        self._config.get("serve.labels_device_min_edges", 65536)
                    ),
                    hbm_budget_bytes=int(
                        self._config.get("serve.hbm_budget_bytes", 0)
                    ),
                    audit_sample_rate=float(
                        self._config.get("serve.audit_sample_rate", 0.0)
                    ),
                    device_build_enabled=bool(
                        self._config.get("serve.device_build_enabled", True)
                    ),
                    build_chunk_rows=int(
                        self._config.get("serve.build_chunk_rows", 262144)
                    ),
                    native_pack_enabled=bool(
                        self._config.get("serve.native_pack_enabled", True)
                    ),
                    staging_enabled=bool(
                        self._config.get("serve.staging_enabled", True)
                    ),
                    stream_tail_ratio=float(
                        self._config.get("serve.stream_tail_ratio", 5.0)
                    ),
                )
                # mirror per-slice service times into /metrics — the same
                # numbers the adaptive width controller steers by
                engine.stream_slice_stats.attach_histogram(
                    self.metrics().histogram(
                        "keto_engine_stream_slice_duration_seconds",
                        "Per-slice device service time of the streaming "
                        "check pipeline (what StreamSliceController steers by).",
                    )
                )
                # mirror build-pipeline phase durations the same way —
                # the phases bench grades are the phases operators scrape
                engine.build_progress.attach_histogram(
                    self.metrics().histogram(
                        "keto_build_phase_duration_seconds",
                        "Wall time per streaming-build pipeline phase "
                        "(scan / intern / device_build / labels / "
                        "cache_save), one histogram series per phase.",
                        ("phase",),
                        buckets=(0.01, 0.05, 0.25, 1.0, 5.0, 15.0, 60.0,
                                 300.0, 1200.0),
                    )
                )
                return engine
            return CheckEngine(store)

        return build()

    def expand_depth(self, requested: int) -> int:
        """Clamp a request's max-depth to the configured global cap
        (``limit.max_read_depth``): a request asking for 0 — or more than
        the cap — gets the cap."""
        cap = int(self._config.get("limit.max_read_depth", 5))
        return cap if requested <= 0 or requested > cap else requested

    def expand_engine(self):
        """The expand engine: snapshot-backed (sharing the TPU check
        engine's device snapshots and freshness semantics) when the check
        engine is the TPU one, else the Manager-backed recursion."""

        def build():
            check = self.permission_engine()
            if hasattr(check, "snapshot"):
                from keto_tpu.expand.tpu_engine import SnapshotExpandEngine

                return SnapshotExpandEngine(check, self.namespaces_source())
            return ExpandEngine(self.relation_tuple_manager())

        return self._memo("expand_engine", build)

    def explain_enabled(self) -> bool:
        return bool(self._config.get("serve.explain_enabled", True))

    def decision_log(self):
        """The durable decision-audit log (keto_tpu/explain/decision_log.py),
        or None when ``serve.decision_log_dir`` is unset — the hot path's
        entire cost in that case is this None check."""
        d = str(self._config.get("serve.decision_log_dir", "") or "")
        if not d:
            return None

        def build():
            from keto_tpu.explain.decision_log import DecisionLog

            return DecisionLog(
                d,
                sample=float(self._config.get("serve.decision_log_sample", 0.0)),
                segment_bytes=int(
                    self._config.get("serve.decision_log_segment_bytes", 1 << 20)
                ),
                retention=int(self._config.get("serve.decision_log_retention", 8)),
            )

        return self._memo("decision_log", build)

    def explain_engine(self):
        """The decision-provenance engine (keto_tpu/explain): decides
        through the serving check engine (so the reported route is the one
        that actually answered), back-traces the witness against the
        Manager, verifies it edge-by-edge, and records to the decision
        log. Verify failures — each one a bug in the producing route —
        fire the flight recorder with the failing witness attached."""

        def build():
            from keto_tpu.explain.engine import ExplainEngine

            def on_verify_failure(note):
                fr = self.flight_recorder()
                if fr is not None:
                    fr.trigger("witness-verify-failure", detail=note.get("tuple", ""))

            return ExplainEngine(
                self.permission_engine(),
                self.relation_tuple_manager(),
                decision_log=self.decision_log(),
                on_verify_failure=on_verify_failure,
            )

        return self._memo("explain_engine", build)

    def list_engine(self):
        """The reverse-query engine (keto_tpu/list/): snapshot-backed
        (sharing the TPU check engine's device snapshots, transposed
        layouts, and snaptoken semantics) when the check engine is the
        TPU one, else the Manager-backed oracle."""

        def build():
            check = self.permission_engine()
            if hasattr(check, "snapshot"):
                from keto_tpu.list.tpu_engine import SnapshotListEngine

                return SnapshotListEngine(
                    check,
                    self.namespaces_source(),
                    cache_entries=int(
                        self._config.get("serve.list_cache_entries", 64)
                    ),
                )
            from keto_tpu.list.engine import ListEngine

            return ListEngine(self.relation_tuple_manager())

        return self._memo("list_engine", build)

    def watch_hub(self):
        """The Watch changefeed hub (keto_tpu/list/watch.py) over the
        tuple store's durable change log."""
        from keto_tpu.list.watch import WatchHub

        return self._memo(
            "watch_hub",
            lambda: WatchHub(
                self.relation_tuple_manager(),
                poll_s=float(self._config.get("serve.watch_poll_ms", 100.0)) / 1e3,
                max_streams=int(self._config.get("serve.watch_max_streams", 64)),
            ),
        )

    def check_batcher(self) -> CheckBatcher:
        def build():
            engine = self.permission_engine()
            batch_size = int(self._config.get("engine.batch_size", 4096))
            max_pending = 8 * batch_size
            # adaptive admission control: AIMD over the batch lane, keyed
            # off the SAME slice service-time stats the stream width
            # controller steers by, plus the batcher's own queue-delay
            # estimate (keto_tpu/driver/admission.py)
            admission = None
            if bool(self._config.get("serve.admission_enabled", True)):
                from keto_tpu.driver.admission import AdmissionController

                budget = float(
                    self._config.get("serve.admission_latency_budget_ms", 0.0)
                )
                admission = AdmissionController(
                    stats=getattr(engine, "stream_slice_stats", None),
                    target_ms=float(
                        self._config.get("serve.stream_slice_target_ms", 40.0)
                    ),
                    budget_ms=budget or None,
                    min_window=int(
                        self._config.get("serve.admission_min_window", 64)
                    ),
                    max_window=max_pending,
                )
            b = CheckBatcher(
                engine,
                batch_size=batch_size,
                window_ms=float(self._config.get("engine.batch_window_ms", 1.0)),
                max_pending=max_pending,
                # serving processes shed on a full queue (429 /
                # RESOURCE_EXHAUSTED) instead of letting callers block
                # into their own timeouts — backpressure with an answer
                shed_on_full=bool(self._config.get("serve.shed_on_full", True)),
                interactive_max_tuples=int(
                    self._config.get("serve.interactive_max_tuples", 16)
                ),
                batch_sub_slice=int(self._config.get("serve.batch_sub_slice", 1024)),
                admission=admission,
                # every shed response names its tenant (X-Keto-Tenant) —
                # requests without the header belong to the default tenant
                tenant="default",
            )
            pool = self.peek("tenants")
            if pool is not None:
                b.on_shed = pool.note_shed
            b.start()
            return b

        return self._memo("check_batcher", build)

    # -- multi-tenant serving (keto_tpu/driver/tenants.py) --------------------

    def tenant_pool(self):
        """The keyed TenantPool behind the ``X-Keto-Tenant`` header:
        per-tenant engine/batcher/admission/watch contexts, the
        cross-tenant residency ledger with its tenant-LRU, and the
        per-tenant shed-spike anomaly tracker. Built lazily on the first
        non-default tenant request — a process that never sees the header
        never constructs it."""

        def build():
            from keto_tpu.driver.tenants import TenantPool

            pool = TenantPool(
                self,
                max_resident=int(
                    self._config.get("serve.tenant_max_resident", 8)
                ),
                quota_share=float(
                    self._config.get("serve.tenant_quota_share", 0.25)
                ),
                backend=str(
                    self._config.get("serve.tenant_backend", "oracle")
                ),
                shed_spike=int(
                    self._config.get("serve.tenant_shed_spike", 50)
                ),
            )
            # cross-tenant residency arbitration: the default engine's
            # governor gets a tenant-LRU rung BELOW its own ladder, so
            # real device pressure reclaims cold tenants' engines whole
            # (they fault back in via the segmented snapcache)
            gov = getattr(self.permission_engine(), "hbm", None)
            if gov is not None:
                gov.append_rung("tenant-lru", pool.evict_coldest, lambda: None)
            fr = self.flight_recorder()
            if fr is not None:
                # a per-tenant shed-rate spike is an anomaly in its own
                # right: capture the bundle while the storm is visible
                pool.set_shed_trigger(
                    lambda tenant, detail: fr.trigger(
                        "tenant-shed-spike", detail, defer_s=0.2
                    )
                )
            batcher = self.peek("check_batcher")
            if batcher is not None:
                # the default tenant's sheds feed the same spike tracker
                batcher.on_shed = pool.note_shed
            return pool

        return self._memo("tenants", build)

    def build_tenant_engine(self, store, tenant: str):
        """Engine factory for TenantPool fault-ins. ``serve.tenant_backend``:
        ``oracle`` (default) serves the tenant from the recursive CPU
        reference engine — zero device footprint, bit-identical answers
        by construction, the right shape for thousands of mostly-cold
        tenants; ``device`` builds a full TpuCheckEngine over the
        tenant's store view with a per-tenant snapcache directory (the
        sub-500ms cold fault-in path); ``auto`` follows the default
        engine's kind."""
        backend = str(self._config.get("serve.tenant_backend", "oracle"))
        if backend == "auto":
            backend = (
                "device"
                if hasattr(self.peek("permission_engine"), "snapshot")
                else "oracle"
            )
        if backend == "device" and hasattr(store, "snapshot_rows"):
            import os

            from keto_tpu.check.tpu_engine import TpuCheckEngine

            cache_root = str(
                self._config.get("serve.snapshot_cache_dir", "") or ""
            )
            return TpuCheckEngine(
                store,
                self.namespaces_source(),
                sync_rebuild_budget_s=float(
                    self._config.get("engine.sync_rebuild_budget_s", 0.25)
                ),
                overlay_edge_budget=int(
                    self._config.get("serve.overlay_edge_budget", 4096)
                ),
                # each tenant caches its snapshots under its own subdir:
                # eviction closes the engine, the on-disk segments stay,
                # and the next touch faults in from cache, not a rebuild
                snapshot_cache_dir=(
                    os.path.join(cache_root, "tenants", tenant)
                    if cache_root
                    else None
                ),
                labels_enabled=bool(
                    self._config.get("serve.labels_enabled", True)
                ),
                # per-tenant governor budget (0 = auto). The POOL bounds
                # how many such engines exist at once; this bounds each.
                hbm_budget_bytes=int(
                    self._config.get("serve.tenant_hbm_budget_bytes", 0)
                ),
                audit_sample_rate=float(
                    self._config.get("serve.audit_sample_rate", 0.0)
                ),
            )
        return CheckEngine(store)

    def build_tenant_batcher(self, engine, tenant: str) -> CheckBatcher:
        """Per-tenant CheckBatcher + AIMD admission — the quota/fairness
        half of noisy-neighbor isolation. Each tenant's queue bound is
        ``serve.tenant_quota_share`` of the global bound, and its
        admission controller tracks ITS consecutive overloaded ticks, so
        Retry-After scales per tenant (no cross-tenant backoff bleed)."""
        batch_size = int(self._config.get("engine.batch_size", 4096))
        share = min(
            1.0,
            max(0.01, float(self._config.get("serve.tenant_quota_share", 0.25))),
        )
        max_pending = max(64, int(8 * batch_size * share))
        admission = None
        if bool(self._config.get("serve.admission_enabled", True)):
            from keto_tpu.driver.admission import AdmissionController

            budget = float(
                self._config.get("serve.admission_latency_budget_ms", 0.0)
            )
            admission = AdmissionController(
                stats=None,  # tenant rounds are timed by observe_round
                target_ms=float(
                    self._config.get("serve.stream_slice_target_ms", 40.0)
                ),
                budget_ms=budget or None,
                min_window=int(
                    self._config.get("serve.admission_min_window", 64)
                ),
                max_window=max_pending,
            )
        b = CheckBatcher(
            engine,
            batch_size=batch_size,
            window_ms=float(self._config.get("engine.batch_window_ms", 1.0)),
            max_pending=max_pending,
            shed_on_full=bool(self._config.get("serve.shed_on_full", True)),
            interactive_max_tuples=int(
                self._config.get("serve.interactive_max_tuples", 16)
            ),
            batch_sub_slice=int(self._config.get("serve.batch_sub_slice", 1024)),
            admission=admission,
            tenant=tenant,
        )
        b.on_shed = self.tenant_pool().note_shed
        b.start()
        return b

    def health_monitor(self):
        """The serving health state machine (keto_tpu/driver/health.py):
        REST ``/health/ready``, gRPC ``grpc.health.v1``, and operator
        introspection all read the same derived state."""
        from keto_tpu.driver.health import HealthMonitor

        return self._memo(
            "health_monitor",
            lambda: HealthMonitor(
                self.permission_engine(),
                staleness_budget_s=float(
                    self._config.get("serve.staleness_budget_s", 60.0)
                ),
                # replica mode: feed lag / primary loss past the budget
                # reports DEGRADED(replication_lag); pre-bootstrap reads
                # as STARTING (keto_tpu/replica/controller.py)
                replica=self.replica_controller(),
            ),
        )

    # -- request timelines / flight recorder / SLOs ---------------------------

    def timeline_recorder(self):
        """The per-request timeline recorder (keto_tpu/x/timeline.py):
        REST/gRPC begin+finish timelines around every non-health
        request, the batcher/engine stamp stages through the context
        variable, ``GET /debug/requests`` reads the ring. Disabled
        (``serve.timeline_enabled: false``) it hands out None timelines
        and every stamp site short-circuits."""

        def build():
            from keto_tpu.x.timeline import TimelineRecorder

            rec = TimelineRecorder(
                capacity=int(self._config.get("serve.timeline_ring", 512)),
                enabled=bool(self._config.get("serve.timeline_enabled", True)),
            )
            rec.set_tracer(self.tracer())
            rec.attach_stage_histogram(
                self.metrics().histogram(
                    "keto_timeline_stage_duration_seconds",
                    "Per-request time attributed to each pipeline stage "
                    "(admit/pack/dispatch/device/land/deliver, from the "
                    "request timelines); slowest sample per stage carries "
                    "a trace_id exemplar.",
                    ("stage",),
                )
            )
            return rec

        return self._memo("timeline", build)

    def slo_engine(self):
        """The SLO engine (keto_tpu/x/slo.py): availability + latency
        burn rates over the live request counters, multi-window, served
        at ``GET /slo`` and scraped as ``keto_slo_*``."""

        def build():
            from keto_tpu.x.slo import SloEngine

            return SloEngine(
                self.metrics(),
                availability_objective=float(
                    self._config.get("serve.slo_availability_objective", 0.999)
                ),
                latency_objective_ms=float(
                    self._config.get("serve.slo_latency_objective_ms", 250.0)
                ),
                latency_objective_ratio=float(
                    self._config.get("serve.slo_latency_objective_ratio", 0.99)
                ),
            )

        return self._memo("slo", build)

    def flight_recorder(self):
        """The anomaly flight recorder (keto_tpu/x/flightrec.py), or
        None without ``serve.debug_bundle_dir``. ``wire_flight_recorder``
        attaches its triggers to the live components."""
        bundle_dir = str(self._config.get("serve.debug_bundle_dir", "") or "")
        if not bundle_dir:
            return None

        def build():
            from keto_tpu.x.flightrec import FlightRecorder

            return FlightRecorder(
                bundle_dir,
                collect=self._flightrec_collect,
                max_bundles=int(self._config.get("serve.debug_bundle_max", 8)),
                min_interval_s=float(
                    self._config.get("serve.debug_bundle_min_interval_s", 30.0)
                ),
                max_bytes=int(
                    self._config.get("serve.debug_bundle_max_bytes", 4 << 20)
                ),
                version=VERSION,
            )

        return self._memo("flightrec", build)

    def wire_flight_recorder(self) -> None:
        """Attach the flight recorder's anomaly triggers: health
        transitions into DEGRADED/NOT_SERVING (which also covers audit
        mismatches — they surface as a DEGRADED transition), contained
        device OOMs, and lock-watchdog trips. Called by the daemon after
        the serving components exist; a no-op without a bundle dir."""
        fr = self.flight_recorder()
        if fr is None:
            return
        from keto_tpu.driver.health import HealthState

        def on_transition(state, reason):
            if state in (HealthState.DEGRADED, HealthState.NOT_SERVING):
                fr.trigger(f"health-{state.value}", reason)

        self.health_monitor().add_listener(on_transition)
        gov = getattr(self.permission_engine(), "hbm", None)
        if gov is not None:
            # OOMs are detected MID-request: defer briefly so the
            # triggering request's finished timeline is in the bundle
            gov.on_oom = lambda what: fr.trigger("oom", what, defer_s=0.3)
        from keto_tpu.x import lockwatch

        if lockwatch.installed():
            lockwatch.add_trip_listener(
                lambda trip: fr.trigger("watchdog", str(trip.get("lock_site", "")))
            )

    def _flightrec_collect(self) -> dict:
        """The flight recorder's bundle sections, every one gathered
        under its own guard so a broken component cannot suppress the
        evidence from the rest."""
        sections: dict = {}

        def sec(name, fn):
            try:
                sections[name] = fn()
            except Exception as e:
                sections[name] = {"error": repr(e)}

        rec = self.peek("timeline")
        if rec is not None:
            sec("timelines", lambda: rec.snapshot(recent=100, slowest=20))
        monitor = self.peek("health_monitor")
        if monitor is not None:
            sec("health", monitor.snapshot)
        gov = getattr(self.peek("permission_engine"), "hbm", None)
        if gov is not None:
            sec("hbm", gov.snapshot)
        batcher = self.peek("check_batcher")
        if batcher is not None:
            def batcher_state():
                adm = batcher.admission
                return {
                    "queue_depth": batcher.queue_depth,
                    "lane_depths": batcher.lane_depths,
                    "inflight": batcher.inflight,
                    "shed_count": batcher.shed_count,
                    "shed_by_lane": dict(batcher.shed_by_lane),
                    "admission_shed_count": batcher.admission_shed_count,
                    "deadline_drop_count": batcher.deadline_drop_count,
                    "admission": None if adm is None else {
                        "window": getattr(adm, "window", None),
                        "budget_ms": getattr(adm, "budget_ms", None),
                        "last_p99_ms": getattr(adm, "last_p99_ms", None),
                    },
                }

            sec("batcher", batcher_state)
        m = self.peek("metrics")
        if m is not None:
            sec("metrics", m.render)
        from keto_tpu.x import lockwatch

        if lockwatch.installed():
            sec("lockwatch", lockwatch.report)
        hub = self.peek("watch_hub")
        if hub is not None:
            sec("watch", hub.snapshot)
        rep = self.peek("replica")
        if rep is not None:
            sec("replica", rep.snapshot)
        slo = self.peek("slo")
        if slo is not None:
            sec("slo", slo.to_json)
        pool = self.peek("tenants")
        if pool is not None:
            # noisy-neighbor forensics: per-tenant residency, shed
            # totals, spike counts, and degradation reasons — who was
            # storming and who paid, at the moment of anomaly
            sec("tenants", pool.snapshot)
        ex = self.peek("explain_engine")
        if ex is not None and ex.recent_failures:
            # witnesses that failed edge-by-edge verification — each one
            # is a bug in the producing route; the failing path is the
            # evidence triage starts from
            sec("explain", lambda: {
                "verify_failures": ex.verify_failures,
                "recent": list(ex.recent_failures),
            })
        eng = self.peek("permission_engine")
        divs = getattr(eng, "audit_divergences", None)
        if divs:
            # shadow-parity divergences WITH both witnesses (device
            # route's vs the CPU oracle's) — triage starts from the
            # disagreeing edge, not a bare mismatch counter
            sec("audit_divergences", lambda: list(divs))
        sections["config"] = {
            "role": str(self._config.get("serve.role", "primary")),
            "version": VERSION,
        }
        return sections

    # -- observability -------------------------------------------------------

    def metrics(self):
        """The process-wide MetricsRegistry (keto_tpu/x/metrics.py),
        bridged from every existing stat sink: REST/gRPC layers record
        their request counters/histograms directly, while the batcher,
        engine maintenance, health machine, tracer, and persister are
        read through scrape-time callbacks — their hot paths never learn
        about Prometheus. ``metrics.enabled: false`` swaps in the no-op
        registry (and /metrics answers 404)."""

        def build():
            from keto_tpu.x.metrics import MetricsRegistry, NullMetricsRegistry

            if not bool(self._config.get("metrics.enabled", True)):
                return NullMetricsRegistry()
            m = MetricsRegistry()
            m.gauge(
                "keto_build_info",
                "Always 1; the version label identifies the running build.",
                ("version",),
            ).set((VERSION,), 1)
            # engine slice service times: the SAME numbers the adaptive
            # stream-width controller steers by, mirrored from the
            # engine's DurationStats (attached in permission_engine())
            m.histogram(
                "keto_engine_stream_slice_duration_seconds",
                "Per-slice device service time of the streaming check "
                "pipeline (what StreamSliceController steers by).",
            )
            # streaming-build pipeline phases (declared eagerly so a
            # scrape before the first build exposes the family; the
            # engine attaches the same instrument in permission_engine())
            m.histogram(
                "keto_build_phase_duration_seconds",
                "Wall time per streaming-build pipeline phase "
                "(scan / intern / device_build / labels / "
                "cache_save), one histogram series per phase.",
                ("phase",),
                buckets=(0.01, 0.05, 0.25, 1.0, 5.0, 15.0, 60.0,
                         300.0, 1200.0),
            )
            # request-timeline stage durations (the recorder attaches
            # the same instrument in timeline_recorder()) and the
            # replica-side replication delay — declared eagerly so every
            # role's scrape exposes the documented family set
            m.histogram(
                "keto_timeline_stage_duration_seconds",
                "Per-request time attributed to each pipeline stage "
                "(admit/pack/dispatch/device/land/deliver, from the "
                "request timelines); slowest sample per stage carries "
                "a trace_id exemplar.",
                ("stage",),
            )
            m.histogram(
                "keto_replication_apply_delay_seconds",
                "Replica mode: wall time from the primary's commit to "
                "the change being visible through this replica's 412 "
                "gate (cross-clock; slowest sample carries the "
                "writer's trace_id exemplar).",
                buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                         0.5, 1.0, 2.5, 5.0, 15.0, 60.0),
            )
            # request families are declared eagerly (the serving layers
            # re-declare idempotently) so a scrape before first traffic
            # already exposes the full documented family set
            from keto_tpu.servers.grpc_api import _expand_metrics, _request_metrics

            _request_metrics(m)
            _expand_metrics(m)
            self._register_metric_bridges(m)
            return m

        return self._memo("metrics", build)

    def _register_metric_bridges(self, m) -> None:
        """Scrape-time callbacks over already-built components. They read
        through ``peek`` so a scrape never constructs (or starts) a
        component as a side effect; families report zeros until the
        component exists."""

        def batcher_attr(attr):
            def read():
                b = self.peek("check_batcher")
                yield (), float(getattr(b, attr, 0) if b is not None else 0)

            return read

        m.register_callback(
            "keto_check_queue_depth", "gauge",
            "Coalescing check batcher: requests queued, not yet packed.",
            batcher_attr("queue_depth"),
        )
        m.register_callback(
            "keto_check_inflight", "gauge",
            "Accepted check requests whose futures have not resolved.",
            batcher_attr("inflight"),
        )
        m.register_callback(
            "keto_check_shed_total", "counter",
            "Check requests refused at the door with 429/RESOURCE_EXHAUSTED "
            "(queue at capacity).",
            batcher_attr("shed_count"),
        )
        m.register_callback(
            "keto_check_deadline_drops_total", "counter",
            "Check requests dropped before dispatch because their deadline "
            "expired (504/DEADLINE_EXCEEDED).",
            batcher_attr("deadline_drop_count"),
        )

        from keto_tpu.driver.batch import LANES

        def lane_map(attr):
            def read():
                b = self.peek("check_batcher")
                vals = getattr(b, attr, {}) if b is not None else {}
                return [((lane,), float(vals.get(lane, 0))) for lane in LANES]

            return read

        m.register_callback(
            "keto_lane_queue_depth", "gauge",
            "Priority lanes: tuples queued per lane, not yet packed into a "
            "dispatch round.",
            lane_map("lane_depths"), ("lane",),
        )
        m.register_callback(
            "keto_lane_shed_total", "counter",
            "Requests refused at the door per lane (queue full or over the "
            "admission window), 429/RESOURCE_EXHAUSTED + Retry-After.",
            lane_map("shed_by_lane"), ("lane",),
        )
        m.register_callback(
            "keto_admission_shed_total", "counter",
            "Batch-lane requests shed by the AIMD admission window "
            "specifically (subset of keto_check_shed_total).",
            batcher_attr("admission_shed_count"),
        )

        def admission_attr(attr, scale=1.0):
            def read():
                b = self.peek("check_batcher")
                a = getattr(b, "admission", None) if b is not None else None
                v = getattr(a, attr, 0) if a is not None else 0
                yield (), float(v or 0) * scale

            return read

        m.register_callback(
            "keto_admission_window", "gauge",
            "AIMD admission control: currently admitted batch-lane window "
            "(queued tuples); shrinks multiplicatively past the latency "
            "budget, recovers additively.",
            admission_attr("window"),
        )
        m.register_callback(
            "keto_admission_latency_budget_seconds", "gauge",
            "The latency budget the admission controller sheds against "
            "(serve.admission_latency_budget_ms, default 4x the slice "
            "target).",
            admission_attr("budget_ms", 1e-3),
        )
        m.register_callback(
            "keto_admission_observed_p99_seconds", "gauge",
            "Slice service-time p99 the admission controller last judged "
            "(same DurationStats the stream width controller steers by).",
            admission_attr("last_p99_ms", 1e-3),
        )

        def maintenance_raw():
            engine = self.peek("permission_engine")
            stats = getattr(engine, "maintenance", None)
            if stats is None:
                return {}, {}, {}
            return stats.raw()

        def maintenance_events():
            counters, _, _ = maintenance_raw()
            return [((k,), float(v)) for k, v in counters.items()] or [(("none",), 0.0)]

        m.register_callback(
            "keto_maintenance_events_total", "counter",
            "Snapshot maintenance events (delta applies, compactions, "
            "rebuilds, cache saves/loads, failures), by event.",
            maintenance_events, ("event",),
        )

        def maintenance_durations(field, scale):
            def read():
                _, _, durations = maintenance_raw()
                return [
                    ((op,), float(d[field]) * scale) for op, d in durations.items()
                ] or [(("none",), 0.0)]

            return read

        m.register_callback(
            "keto_maintenance_duration_seconds_total", "counter",
            "Cumulative wall time spent in maintenance operations "
            "(compaction, rebuild, cache save/reload), by op.",
            maintenance_durations("total_ms", 1e-3), ("op",),
        )
        m.register_callback(
            "keto_maintenance_runs_total", "counter",
            "Completed maintenance operations, by op.",
            maintenance_durations("count", 1.0), ("op",),
        )

        def label_paths():
            counters, _, _ = maintenance_raw()
            return [
                (("label",), float(counters.get("label_checks", 0))),
                (("fallback",), float(counters.get("label_fallbacks", 0))),
            ]

        m.register_callback(
            "keto_label_checks_total", "counter",
            "Check queries answered by the 2-hop label fast path (path="
            "label) vs routed to the BFS kernel while labels were live "
            "(path=fallback: wildcards, coverage gaps, self-queries).",
            label_paths, ("path",),
        )

        def label_coverage():
            _, gauges, _ = maintenance_raw()
            v = gauges.get("label_coverage", 0.0)
            yield (), float(v) if isinstance(v, (int, float)) else 0.0

        m.register_callback(
            "keto_label_coverage_ratio", "gauge",
            "Fraction of interior rows the 2-hop label index can certify "
            "on both sides (processed landmark, untruncated labels) — "
            "label build/patch/invalidation events ride "
            "keto_maintenance_events_total.",
            label_coverage,
        )

        def label_truncations():
            counters, _, _ = maintenance_raw()
            return [
                (("cap",), float(counters.get("label_build_truncated_cap", 0))),
                (
                    ("min_gain",),
                    float(counters.get("label_build_truncated_min_gain", 0)),
                ),
            ]

        m.register_callback(
            "keto_label_build_truncated_total", "counter",
            "Label builds that stopped before processing every interior "
            "landmark, by reason (cap: the host path's 131072 landmark "
            "safety cap; min_gain: the device build's "
            "serve.labels_min_gain early exit). Each one logs the "
            "achieved coverage ratio; uncovered deep checks fall back to "
            "the BFS kernel bit-identically, paying the depth tax the "
            "labels exist to remove.",
            label_truncations, ("reason",),
        )

        def label_patch_aborts():
            counters, _, _ = maintenance_raw()
            yield (), float(counters.get("label_patch_aborts", 0))

        m.register_callback(
            "keto_label_patch_aborts_total", "counter",
            "Incremental label patches (compaction folding overlay "
            "inserts into the index) abandoned on the visit budget — "
            "each abort schedules a full device rebuild in the same "
            "supervised maintenance pass (rides "
            "keto_maintenance_events_total as label_rebuilds). A rising "
            "rate means overlay inserts land in dense regions; raise the "
            "budget or compact more often.",
            label_patch_aborts,
        )

        # streaming slice scheduler: per-route landing counts, the
        # observed tail ratio the service-time controller guards, and
        # which pack path (native C++ vs numpy) built each chunk
        STREAM_ROUTES = ("label", "hybrid", "bfs", "host", "cpu")

        def route_slices():
            engine = self.peek("permission_engine")
            fn = getattr(engine, "route_slice_counts", None)
            counts = fn() if fn is not None else {}
            return [((r,), float(counts.get(r, 0))) for r in STREAM_ROUTES]

        m.register_callback(
            "keto_stream_route_slices_total", "counter",
            "Streaming check slices landed, by answering route: label "
            "(intersection kernel only), hybrid (label + BFS sub-batch), "
            "bfs, host (no device work), cpu (degraded fallback).",
            route_slices, ("route",),
        )

        def stream_tail_ratio():
            engine = self.peek("permission_engine")
            stats = getattr(engine, "stream_slice_stats", None)
            snap = stats.snapshot() if stats is not None else None
            if not snap or not snap.get("p50_ms"):
                yield (), 0.0
            else:
                yield (), float(snap["p99_ms"]) / float(snap["p50_ms"])

        m.register_callback(
            "keto_stream_tail_ratio", "gauge",
            "Observed per-slice service-time p99/p50 ratio over the "
            "engine's sliding window — the number the slice controller's "
            "tail guard (serve.stream_tail_ratio) steers and the "
            "tail-smoke CI gate asserts.",
            stream_tail_ratio,
        )

        def native_pack_paths():
            from keto_tpu.check.native_pack import COUNTERS

            return [((p,), float(COUNTERS.get(p, 0))) for p in ("native", "numpy")]

        m.register_callback(
            "keto_native_pack_chunks_total", "counter",
            "Check chunks packed per host-walk path: native (GIL-released "
            "C++ walk, native/pack.cpp) vs numpy (library absent/disabled, "
            "or the snapshot carries host-visible overlay state).",
            native_pack_paths, ("path",),
        )

        # streaming snapshot build (keto_tpu/graph/stream_build.py): the
        # live pipeline phase plus cumulative ingest counters, read from
        # the engine's BuildProgress at scrape time — a multi-minute
        # STARTING boot is visibly alive on /metrics too
        from keto_tpu.graph.stream_build import PHASES as BUILD_PHASES

        def build_progress():
            engine = self.peek("permission_engine")
            return getattr(engine, "build_progress", None)

        def build_phase():
            bp = build_progress()
            current = bp.current_phase if bp is not None else "idle"
            return [
                ((p,), 1.0 if p == current else 0.0)
                for p in ("idle",) + BUILD_PHASES
            ]

        m.register_callback(
            "keto_build_phase", "gauge",
            "Streaming-build pipeline phase, one-hot over idle/scan/"
            "intern/device_build/labels/cache_save — nonzero off idle "
            "means a snapshot build is in flight.",
            build_phase, ("phase",),
        )

        def build_attr(attr):
            def read():
                bp = build_progress()
                yield (), float(getattr(bp, attr, 0) if bp is not None else 0)

            return read

        m.register_callback(
            "keto_build_rows_ingested_total", "counter",
            "Store rows scanned+interned by snapshot builds since boot "
            "(cumulative across rebuilds; rate it to watch a cold start "
            "make progress).",
            build_attr("rows_ingested"),
        )
        m.register_callback(
            "keto_build_edges_ingested_total", "counter",
            "Graph edges laid out by snapshot builds since boot "
            "(cumulative across rebuilds).",
            build_attr("edges_ingested"),
        )

        def overlay_gauge(key):
            def read():
                _, gauges, _ = maintenance_raw()
                v = gauges.get(key, 0)
                yield (), float(v) if isinstance(v, (int, float)) else 0.0

            return read

        m.register_callback(
            "keto_overlay_edges", "gauge",
            "Delta-overlay occupancy: pending edges + tombstones not yet "
            "folded into the base layout.",
            overlay_gauge("overlay_edges"),
        )
        m.register_callback(
            "keto_overlay_budget", "gauge",
            "serve.overlay_edge_budget: occupancy past this triggers "
            "compaction.",
            overlay_gauge("overlay_budget"),
        )

        # HBM budget governor (keto_tpu/driver/hbm.py): the ledger, the
        # eviction ladder, and OOM containment — read at scrape time from
        # the governor's own snapshot so the exposed totals reconcile
        # with the ledger by construction
        from keto_tpu.driver.hbm import RUNGS as HBM_RUNGS
        from keto_tpu.driver.hbm import TAGS as HBM_TAGS

        def hbm_snapshot():
            engine = self.peek("permission_engine")
            gov = getattr(engine, "hbm", None)
            return gov.snapshot() if gov is not None else {}

        def hbm_ledger():
            led = hbm_snapshot().get("ledger", {})
            out = [((t,), float(led.get(t, 0))) for t in HBM_TAGS]
            out += [
                ((t,), float(v)) for t, v in sorted(led.items())
                if t not in HBM_TAGS
            ]
            return out

        m.register_callback(
            "keto_hbm_resident_bytes", "gauge",
            "Device bytes resident per governor ledger tag (snapshot "
            "buckets, overlay ELL, label arrays, warm-ladder workspace); "
            "the series sums to the governor's total ledger.",
            hbm_ledger, ("tag",),
        )

        def hbm_scalar(key):
            def read():
                yield (), float(hbm_snapshot().get(key, 0) or 0)

            return read

        m.register_callback(
            "keto_hbm_budget_bytes", "gauge",
            "The enforced device-memory budget: serve.hbm_budget_bytes, "
            "or the auto value (device bytes_limit minus headroom, with "
            "a conservative fallback when the backend has no stats).",
            hbm_scalar("budget_bytes"),
        )
        m.register_callback(
            "keto_hbm_eviction_rung", "gauge",
            "Current eviction-ladder depth: 0 = full service, then "
            "staging pool dropped -> labels dropped -> reverse layouts "
            "dropped -> warm ladder trimmed -> overlay budget shrunk; "
            "refresh refusals ride keto_hbm_refusals_total.",
            hbm_scalar("rung"),
        )

        def hbm_evictions():
            by = hbm_snapshot().get("evictions_by_rung", {})
            return [((r,), float(by.get(r, 0))) for r in HBM_RUNGS]

        m.register_callback(
            "keto_hbm_evictions_total", "counter",
            "Eviction-ladder descents, by rung (labels / warm-ladder / "
            "overlay-budget) — planned pressure and real-OOM containment "
            "both count here.",
            hbm_evictions, ("rung",),
        )
        m.register_callback(
            "keto_hbm_refusals_total", "counter",
            "Snapshot refreshes refused because the plan stayed over "
            "budget with every eviction rung spent — the engine serves "
            "stale and reports DEGRADED(memory_pressure).",
            hbm_scalar("refusals"),
        )

        def warm_skipped():
            _, gauges, _ = maintenance_raw()
            v = gauges.get("warm_widths_skipped", 0)
            yield (), float(v) if isinstance(v, (int, float)) else 0.0

        m.register_callback(
            "keto_hbm_warm_widths_skipped", "gauge",
            "Slice widths the boot warmup skipped because their "
            "compiled-buffer footprint would breach the HBM budget "
            "(warming never evicts; it just stops lower on the ladder).",
            warm_skipped,
        )
        m.register_callback(
            "keto_oom_events_total", "counter",
            "Device allocations/compiled calls that raised a classified "
            "RESOURCE_EXHAUSTED (real XLA or the injected device-alloc "
            "oom fault).",
            hbm_scalar("oom_events"),
        )
        m.register_callback(
            "keto_oom_recoveries_total", "counter",
            "OOMs contained by evicting one ladder rung and retrying "
            "once successfully (the remainder escalate to the CPU "
            "fallback or a supervised refresh retry — never a crash).",
            hbm_scalar("oom_recoveries"),
        )

        # sharded serving (keto_tpu/parallel/sharded.py): the per-shard
        # residency ledger and the halo-exchange / frontier counters the
        # shard_map kernel's stats words feed
        def shard_hbm():
            snap = hbm_snapshot()
            shards = snap.get("shards") or []
            return [
                ((str(s),), float(v)) for s, v in enumerate(shards)
            ] or [(("0",), 0.0)]

        m.register_callback(
            "keto_shard_hbm_resident_bytes", "gauge",
            "Per-shard device bytes resident under the governor's "
            "per-shard ledger (owned bucket/overlay/label rows; "
            "replicated state spreads evenly) — the hottest shard is "
            "the binding constraint of every mesh-wide plan.",
            shard_hbm, ("shard",),
        )

        # multi-tenant serving (keto_tpu/driver/tenants.py): pool-level
        # residency/ledger plus per-tenant traffic and degradation —
        # peek-only like every bridge; the labeled families always emit
        # a default-tenant row so the exposed family set (and its
        # observability.md contract) is stable before the first tenant
        def tenant_pool_peek():
            return self.peek("tenants")

        def tenant_pool_count(method):
            def read():
                p = tenant_pool_peek()
                yield (), float(getattr(p, method)() if p is not None else 0)

            return read

        m.register_callback(
            "keto_tenant_known", "gauge",
            "Tenants this process has served since boot (resident or "
            "evicted); the default tenant is not counted.",
            tenant_pool_count("known_count"),
        )
        m.register_callback(
            "keto_tenant_resident", "gauge",
            "Tenants whose engines are currently materialized (bounded "
            "by serve.tenant_max_resident via the tenant-LRU).",
            tenant_pool_count("resident_count"),
        )

        def tenant_pool_attr(attr):
            def read():
                p = tenant_pool_peek()
                yield (), float(getattr(p, attr, 0) if p is not None else 0)

            return read

        m.register_callback(
            "keto_tenant_evictions_total", "counter",
            "Whole-tenant engine evictions (tenant-LRU capacity + the "
            "governor's tenant-lru HBM rung); state faults back in via "
            "the per-tenant snapcache on next touch.",
            tenant_pool_attr("evictions"),
        )
        m.register_callback(
            "keto_tenant_faultins_total", "counter",
            "Tenant engine fault-ins (first touch + every re-build after "
            "an eviction).",
            tenant_pool_attr("faultins"),
        )
        m.register_callback(
            "keto_tenant_shed_spikes_total", "counter",
            "Per-tenant shed-rate spikes detected (>= "
            "serve.tenant_shed_spike sheds inside the tracking window) — "
            "each one also triggers a flight-recorder bundle.",
            tenant_pool_attr("spike_triggers"),
        )

        def tenant_rows(per_ctx):
            def read():
                p = tenant_pool_peek()
                rows = (
                    [((c.name,), per_ctx(c)) for c in p.tenants()]
                    if p is not None
                    else []
                )
                return rows or [(("default",), 0.0)]

            return read

        m.register_callback(
            "keto_tenant_checks_total", "counter",
            "Check tuples dispatched per tenant (the default tenant's "
            "traffic rides the global keto_check_* families).",
            tenant_rows(lambda c: float(c.checks_total)), ("tenant",),
        )

        def tenant_shed():
            p = tenant_pool_peek()
            totals = dict(p.shed_totals) if p is not None else {}
            rows = [((t,), float(v)) for t, v in sorted(totals.items())]
            return rows or [(("default",), 0.0)]

        m.register_callback(
            "keto_tenant_shed_total", "counter",
            "Requests shed per tenant (429 + Retry-After + "
            "X-Keto-Tenant): one tenant's storm sheds under ITS quota "
            "while every other tenant's lanes stay open.",
            tenant_shed, ("tenant",),
        )
        m.register_callback(
            "keto_tenant_resident_bytes", "gauge",
            "Device-ledger bytes per resident tenant engine (0 while "
            "cold/oracle-backed); sums with keto_hbm_resident_bytes to "
            "the whole process's residency account.",
            tenant_rows(lambda c: float(c.resident_bytes())), ("tenant",),
        )

        def tenant_degraded():
            p = tenant_pool_peek()
            if p is None:
                return [(("default",), 0.0)]
            bad = p.degraded()
            rows = [
                ((c.name,), 1.0 if c.name in bad else 0.0)
                for c in p.tenants()
            ]
            return rows or [(("default",), 0.0)]

        m.register_callback(
            "keto_tenant_degraded", "gauge",
            "1 for tenants currently carrying a DEGRADED(tenant=...) "
            "reason (device fallback, memory pressure, audit mismatch) — "
            "per-tenant only, never the global health machine.",
            tenant_degraded, ("tenant",),
        )

        def maint_counter(key):
            def read():
                counters, _, _ = maintenance_raw()
                yield (), float(counters.get(key, 0))

            return read

        m.register_callback(
            "keto_shard_halo_rounds_total", "counter",
            "Halo-exchange rounds executed by the sharded BFS kernel: "
            "one all-gather of every shard's frontier bitmap slab over "
            "the graph axis per real BFS hop.",
            maint_counter("shard_halo_rounds"),
        )
        m.register_callback(
            "keto_shard_halo_bytes_total", "counter",
            "Frontier-slab bytes received per device across all halo "
            "rounds ((shards-1) x slab bytes per round) — the "
            "interconnect cost of cross-shard reachability.",
            maint_counter("shard_halo_bytes"),
        )
        m.register_callback(
            "keto_shard_frontier_bits_total", "counter",
            "Set bits in the fixpoint frontier bitmaps summed over "
            "shards and dispatches — the reachability work the mesh "
            "actually performed.",
            maint_counter("shard_frontier_bits"),
        )

        # sampled shadow-parity auditor (serve.audit_sample_rate)
        def audit_counter(key):
            def read():
                counters, _, _ = maintenance_raw()
                yield (), float(counters.get(key, 0))

            return read

        m.register_callback(
            "keto_audit_checks_total", "counter",
            "Live check decisions re-verified against the CPU reference "
            "oracle by the background shadow-parity auditor.",
            audit_counter("audit_checks"),
        )
        m.register_callback(
            "keto_audit_mismatches_total", "counter",
            "Audited decisions that DIVERGED from the CPU oracle — any "
            "nonzero value flips health to DEGRADED (continuous proof "
            "that eviction rungs never change answers).",
            audit_counter("audit_mismatches"),
        )

        # decision provenance (keto_tpu/explain): explain requests by the
        # route that decided them, witnesses that failed edge-by-edge
        # verification (each one a bug), and the durable decision log's
        # append totals
        def explain_requests():
            ex = self.peek("explain_engine")
            totals = getattr(ex, "requests_by_route", {}) if ex is not None else {}
            out = [((r,), float(v)) for r, v in sorted(totals.items())]
            return out or [(("bfs",), 0.0)]

        m.register_callback(
            "keto_explain_requests_total", "counter",
            "Check-explain requests served, by the route that decided "
            "them (label / hybrid / bfs / host / cpu — the stream's own "
            "route label, not a re-derivation).",
            explain_requests, ("route",),
        )

        def explain_verify_failures():
            ex = self.peek("explain_engine")
            yield (), float(getattr(ex, "verify_failures", 0) if ex is not None else 0)

        m.register_callback(
            "keto_witness_verify_failures_total", "counter",
            "Witnesses that FAILED edge-by-edge verification against the "
            "Manager before return — each one is a bug in the producing "
            "route; the response fell back to the CPU oracle's witness "
            "and the flight recorder captured the failing path.",
            explain_verify_failures,
        )

        def decision_log_attr(attr):
            def read():
                dl = self.peek("decision_log")
                yield (), float(getattr(dl, attr, 0) if dl is not None else 0)

            return read

        m.register_callback(
            "keto_decision_log_records_total", "counter",
            "Records appended to the durable decision-audit log (sampled "
            "hot-path checks plus every explain request), all tenants.",
            decision_log_attr("records_total"),
        )
        m.register_callback(
            "keto_decision_log_bytes_total", "counter",
            "Bytes appended to the decision-audit log across active and "
            "sealed segments, all tenants.",
            decision_log_attr("bytes_total"),
        )

        # reverse-query subsystem (keto_tpu/list/): request counters per
        # answering path, and the watch hub's stream/event counters
        def list_requests():
            eng = self.peek("list_engine")
            totals = getattr(eng, "requests_total", {}) if eng is not None else {}
            out = [
                ((op, path), float(v)) for (op, path), v in sorted(totals.items())
            ]
            return out or [(("objects", "device"), 0.0)]

        m.register_callback(
            "keto_list_requests_total", "counter",
            "Reverse-query requests served, by op (objects/subjects) and "
            "answering path (device BFS, host = CPU-reference lister, "
            "oracle = Manager-backed wildcard/pattern fallback, empty = "
            "unresolvable query).",
            list_requests, ("op", "path"),
        )

        def list_device_errors():
            eng = self.peek("list_engine")
            yield (), float(getattr(eng, "device_errors", 0) if eng is not None else 0)

        m.register_callback(
            "keto_list_device_errors_total", "counter",
            "Device list-BFS failures that fell back to the "
            "CPU-reference lister (answers unchanged).",
            list_device_errors,
        )

        def watch_stat(key):
            def read():
                hub = self.peek("watch_hub")
                snap = hub.snapshot() if hub is not None else {}
                yield (), float(snap.get(key, 0))

            return read

        m.register_callback(
            "keto_watch_streams", "gauge",
            "Watch changefeed streams currently open (REST chunked + "
            "gRPC server-stream), bounded by serve.watch_max_streams.",
            watch_stat("active_streams"),
        )
        m.register_callback(
            "keto_watch_events_total", "counter",
            "Tuple-change events delivered to watch subscribers (inserts "
            "+ deletes, across all streams).",
            watch_stat("events_total"),
        )
        m.register_callback(
            "keto_watch_expired_total", "counter",
            "Watch resumes refused because the snaptoken predates the "
            "retained change log (410 Gone / OUT_OF_RANGE).",
            watch_stat("expired_total"),
        )

        # replica tier (keto_tpu/replica/): replication lag, feed apply
        # and bootstrap counters, and the Watch-invalidated check cache —
        # read from the controller's snapshot at scrape time; a primary
        # (peek returns None) exposes the families at zero so one scrape
        # config and one dashboard cover both roles
        def replica_snapshot():
            rep = self.peek("replica")
            return rep.snapshot() if rep is not None else {}

        def replica_stat(key):
            def read():
                yield (), float(replica_snapshot().get(key, 0) or 0)

            return read

        m.register_callback(
            "keto_replica_lag_seconds", "gauge",
            "Replica mode: seconds since this replica last confirmed it "
            "was caught up with the primary (feed lagging or primary "
            "unreachable — handled the same); past "
            "serve.replica_staleness_budget_s health reports "
            "DEGRADED(replication_lag). 0 on a primary.",
            replica_stat("lag_s"),
        )
        m.register_callback(
            "keto_replica_applied_commits_total", "counter",
            "Watch commit groups this replica applied at their primary "
            "snaptoken through the delta-overlay path (exactly-once: "
            "re-delivered groups are skipped by the watermark guard).",
            replica_stat("applied_commits"),
        )
        m.register_callback(
            "keto_replica_bootstraps_total", "counter",
            "Full-state installs from the primary's /snapshot/export: "
            "the cold start plus every watch-horizon-loss recovery "
            "(410-triggered automatic re-bootstrap, never silent "
            "divergence).",
            replica_stat("bootstraps"),
        )

        def checkcache_stat(key):
            def read():
                cc = replica_snapshot().get("checkcache") or {}
                yield (), float(cc.get(key, 0) or 0)

            return read

        m.register_callback(
            "keto_checkcache_hits_total", "counter",
            "Replica check-cache hits: decisions served from a (tuple, "
            "snaptoken-window) entry still valid for the requested "
            "freshness.",
            checkcache_stat("hits"),
        )
        m.register_callback(
            "keto_checkcache_misses_total", "counter",
            "Replica check-cache misses (no entry, window closed by an "
            "applied delta, or requested snaptoken above the window).",
            checkcache_stat("misses"),
        )
        m.register_callback(
            "keto_checkcache_invalidations_total", "counter",
            "Check-cache entries whose windows were closed by applied "
            "Watch deltas (global invalidation: reachability is "
            "transitive, so any delta may flip any decision).",
            checkcache_stat("invalidations"),
        )

        def health_states():
            from keto_tpu.driver.health import HealthState

            monitor = self.peek("health_monitor")
            current = monitor.status()[0] if monitor is not None else None
            return [((s.value,), 1.0 if s is current else 0.0) for s in HealthState]

        m.register_callback(
            "keto_health_state", "gauge",
            "Serving health state machine, one-hot over "
            "starting/serving/degraded/not_serving.",
            health_states, ("state",),
        )

        def health_transitions():
            monitor = self.peek("health_monitor")
            yield (), float(monitor.transitions if monitor is not None else 0)

        m.register_callback(
            "keto_health_transitions_total", "counter",
            "Health state transitions since boot.",
            health_transitions,
        )

        # request timelines (keto_tpu/x/timeline.py) + flight recorder
        # (keto_tpu/x/flightrec.py) + SLO engine (keto_tpu/x/slo.py) —
        # peek-only like every other bridge; the daemon primes the SLO
        # engine at boot so scrapes see live burn rates
        def timeline_finished():
            rec = self.peek("timeline")
            by = getattr(rec, "finished_by_surface", {}) if rec is not None else {}
            return [
                ((s,), float(by.get(s, 0))) for s in ("http", "grpc")
            ] + [
                ((s,), float(v)) for s, v in sorted(by.items())
                if s not in ("http", "grpc")
            ]

        m.register_callback(
            "keto_timeline_finished_total", "counter",
            "Request timelines recorded (ring + top-K slowest, queryable "
            "at GET /debug/requests), by serving surface.",
            timeline_finished, ("surface",),
        )

        def flightrec_snapshot():
            fr = self.peek("flightrec")
            return fr.snapshot() if fr is not None else {}

        def flightrec_bundles():
            by = flightrec_snapshot().get("bundles_by_reason", {})
            return [
                ((r,), float(v)) for r, v in sorted(by.items())
            ] or [(("none",), 0.0)]

        m.register_callback(
            "keto_flightrec_bundles_total", "counter",
            "Flight-recorder debug bundles written to "
            "serve.debug_bundle_dir, by trigger reason (health-degraded/"
            "health-not_serving/oom/drain/watchdog).",
            flightrec_bundles, ("reason",),
        )

        def flightrec_suppressed():
            yield (), float(flightrec_snapshot().get("suppressed", 0) or 0)

        m.register_callback(
            "keto_flightrec_suppressed_total", "counter",
            "Flight-recorder triggers refused by the rate limit "
            "(serve.debug_bundle_min_interval_s) — a flapping anomaly "
            "cannot fill the disk.",
            flightrec_suppressed,
        )

        def slo_field(field):
            def read():
                slo = self.peek("slo")
                return slo.metric_rows(field) if slo is not None else []

            return read

        m.register_callback(
            "keto_slo_availability_ratio", "gauge",
            "Fraction of REST+gRPC requests without a server-side "
            "failure (5xx / INTERNAL-class codes) over each trailing "
            "window; 1.0 on an idle window.",
            slo_field("availability_ratio"), ("window",),
        )
        m.register_callback(
            "keto_slo_availability_burn_rate", "gauge",
            "Error-budget burn rate of the availability objective per "
            "window: 1.0 spends the budget exactly at the objective "
            "horizon, >1 is an alertable burn.",
            slo_field("availability_burn_rate"), ("window",),
        )
        m.register_callback(
            "keto_slo_latency_ratio", "gauge",
            "Fraction of REST requests answered within the latency "
            "objective threshold (bucket-quantized), per window.",
            slo_field("latency_ratio"), ("window",),
        )
        m.register_callback(
            "keto_slo_latency_burn_rate", "gauge",
            "Error-budget burn rate of the latency objective per "
            "window (same semantics as the availability burn rate).",
            slo_field("latency_burn_rate"), ("window",),
        )

        def slo_objectives():
            slo = self.peek("slo")
            return slo.objective_rows() if slo is not None else []

        m.register_callback(
            "keto_slo_objective", "gauge",
            "The configured objectives the burn rates are judged "
            "against (availability ratio, latency good-ratio, latency "
            "threshold seconds).",
            slo_objectives, ("objective",),
        )

        def tracer_attr(attr):
            def read():
                t = self.peek("tracer")
                yield (), float(getattr(t, attr, 0) if t is not None else 0)

            return read

        m.register_callback(
            "keto_tracer_spans_exported_total", "counter",
            "Spans handed to the configured trace exporter.",
            tracer_attr("spans_exported"),
        )
        m.register_callback(
            "keto_tracer_spans_dropped_total", "counter",
            "Spans lost (full export queue, collector down, dead file).",
            tracer_attr("spans_dropped"),
        )

        def store_attr(attr):
            def read():
                s = self.peek("manager")
                yield (), float(getattr(s, attr, 0) if s is not None else 0)

            return read

        m.register_callback(
            "keto_persistence_reconnect_retries_total", "counter",
            "Store operations re-run after a dialect-recognized connection "
            "loss (reads always; writes only when idempotency-keyed).",
            store_attr("reconnect_retries"),
        )
        m.register_callback(
            "keto_idempotent_replays_total", "counter",
            "Keyed write retries answered from the dedup table instead of "
            "re-applying.",
            store_attr("idempotent_replays"),
        )

        # group-commit write path (keto_tpu/driver/group_commit.py):
        # flush counter bridged from the coordinator; wait/batch-size
        # histograms are recorded directly by the coordinator (attached
        # in write_coordinator()). Declared eagerly so scrapes expose
        # the documented family before the first write.
        m.histogram(
            "keto_group_commit_wait_seconds",
            "Time a writer spent queued in the group-commit "
            "coordinator before its group's durable transaction "
            "started (the coalescing cost the "
            "serve.group_commit_window_ms knob trades against "
            "fsyncs).",
            buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
                     0.05, 0.1, 0.25, 1.0),
        )
        m.histogram(
            "keto_group_commit_batch_size",
            "Writers coalesced per durable group transaction "
            "(1 = no batching benefit; the ceiling is "
            "serve.group_commit_max_writers).",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256),
        )

        def group_commit_attr(attr):
            def read():
                co = self.peek("group_commit")
                yield (), float(getattr(co, attr, 0) if co is not None else 0)

            return read

        m.register_callback(
            "keto_group_commit_flush_total", "counter",
            "Durable group transactions committed by the write "
            "coordinator (each covers keto_group_commit_batch_size "
            "writers with one BEGIN/COMMIT).",
            group_commit_attr("flush_total"),
        )
        m.register_callback(
            "keto_group_commit_errors_total", "counter",
            "Group transactions that failed (every writer in the group "
            "observed the same error and retries individually).",
            group_commit_attr("flush_errors"),
        )

        # device-resident overlay apply + log-structured fold
        # (keto_tpu/check/tpu_engine.py): bridged from the engine's
        # maintenance stats like the other maintenance families
        m.register_callback(
            "keto_overlay_device_applies_total", "counter",
            "Delta-overlay installs applied directly to the resident "
            "device ELL via scatter patches (no full host re-pack + "
            "re-upload; the complement re-packs, e.g. on capacity "
            "growth).",
            maint_counter("overlay_device_applies"),
        )
        m.register_callback(
            "keto_fold_runs_total", "counter",
            "Background log-structured fold passes: oldest overlay "
            "segments folded into the base snapshot while new writes "
            "keep landing in the newest (the replacement for the "
            "stop-the-world compaction cliff).",
            maint_counter("fold_runs"),
        )

        def fold_duration():
            _, _, durations = maintenance_raw()
            d = durations.get("fold")
            yield (), float(d["total_ms"]) * 1e-3 if d else 0.0

        m.register_callback(
            "keto_fold_duration_seconds_total", "counter",
            "Cumulative wall time spent in background fold passes "
            "(rate against keto_fold_runs_total for the mean fold "
            "cost; folds run off the serving path).",
            fold_duration,
        )

        # fleet control plane (keto_tpu/fleet/): lease epoch, promotion
        # and membership state, live-reshard state machine, and the
        # lag-aware routing weights — peek-only like every other bridge
        def fleet_snapshot():
            f = self.peek("fleet")
            return f.snapshot() if f is not None else {}

        def fleet_epoch():
            yield (), float(fleet_snapshot().get("epoch", 0) or 0)

        m.register_callback(
            "keto_fleet_epoch", "gauge",
            "The fence epoch this node last observed on the fleet lease "
            "(monotone across promotions; a primary's writes carry it, "
            "a deposed primary's writes 409 against a newer one).",
            fleet_epoch,
        )

        def fleet_promotions():
            by = fleet_snapshot().get("promotions_by_reason", {})
            return [
                ((r,), float(v)) for r, v in sorted(by.items())
            ] or [(("none",), 0.0)]

        m.register_callback(
            "keto_fleet_promotions_total", "counter",
            "Times this node installed itself as primary, by reason "
            "(lease-expired: won the election after primary death; "
            "install-retry: re-ran a promotion that crashed between "
            "winning the lease and finishing the install).",
            fleet_promotions, ("reason",),
        )

        def fleet_replicas():
            states: dict[str, int] = {}
            for mem in fleet_snapshot().get("members", []):
                role = str(mem.get("role", "unknown") or "unknown")
                states[role] = states.get(role, 0) + 1
            return [
                ((s,), float(v)) for s, v in sorted(states.items())
            ] or [(("none",), 0.0)]

        m.register_callback(
            "keto_fleet_replicas", "gauge",
            "Live fleet members by advertised role (primary / replica / "
            "deposed), from the heartbeat membership table — stale "
            "members age out of the count.",
            fleet_replicas, ("state",),
        )

        def reshard_state():
            r = self.peek("reshard")
            yield (), float(r.state_code() if r is not None else 0)

        m.register_callback(
            "keto_reshard_state", "gauge",
            "Live-reshard state machine: 0 idle, 1 preparing (target "
            "engine building while the current one serves), 2 handoff "
            "(atomic install), 3 failed (old geometry kept serving).",
            reshard_state,
        )

        def fleet_route_weights():
            w = fleet_snapshot().get("route_weights", {})
            return [
                ((str(nid),), float(v)) for nid, v in sorted(w.items())
            ] or [(("none",), 0.0)]

        m.register_callback(
            "keto_route_weight", "gauge",
            "Lag-aware routing weight per fleet replica (0 = drained: "
            "lag at/over the staleness budget; otherwise lag headroom "
            "over the latency EWMA) — what SDK read routing steers by.",
            fleet_route_weights, ("replica",),
        )

    def tracer(self):
        from keto_tpu.x.tracing import DEFAULT_OTLP_ENDPOINT, Tracer

        return self._memo(
            "tracer",
            lambda: Tracer(
                self._config.get("tracing.provider", ""),
                self.logger(),
                otlp_file=self._config.get("tracing.otlp.file", ""),
                otlp_endpoint=self._config.get(
                    "tracing.otlp.endpoint", DEFAULT_OTLP_ENDPOINT
                ),
            ),
        )

    def telemetry(self):
        from keto_tpu.x.telemetry import Telemetry

        return self._memo(
            "telemetry", lambda: Telemetry(bool(self._config.get("telemetry.enabled", False)))
        )

    # -- info ----------------------------------------------------------------

    def version(self) -> str:
        return VERSION

    def close(self) -> None:
        # the fleet loops go first: they must not renew (or contend for)
        # the lease, heartbeat membership, spawn replicas, or trigger a
        # promotion while the components under them tear down
        scaler = self._singletons.get("autoscaler")
        if scaler is not None:
            scaler.stop()
        fleet = self._singletons.get("fleet")
        if fleet is not None:
            fleet.stop()
        rep = self._singletons.get("replica")
        if rep is not None:
            rep.stop()
        hub = self._singletons.get("watch_hub")
        if hub is not None:
            hub.close()
        # tenant contexts own batchers/engines/hubs of their own: stop
        # them before the default batcher so no tenant dispatch lands on
        # components mid-teardown
        pool = self._singletons.get("tenants")
        if pool is not None:
            pool.close()
        batcher = self._singletons.get("check_batcher")
        if batcher:
            batcher.stop()
        # the write coordinator must stop before the store closes: a
        # group mid-commit against a closed connection would fail every
        # writer in it
        co = self._singletons.get("group_commit")
        if co is not None:
            co.stop()
        engine = self._singletons.get("permission_engine")
        if engine is not None and hasattr(engine, "close"):
            engine.close()
        tracer = self._singletons.get("tracer")
        if tracer is not None:
            tracer.close()
        store = self._singletons.get("manager")
        if store is not None and hasattr(store, "close"):
            store.close()
        lease_store = self._singletons.get("fleet_lease_store")
        if (
            lease_store is not None
            and lease_store is not store
            and hasattr(lease_store, "close")
        ):
            lease_store.close()
        self._config.close()
