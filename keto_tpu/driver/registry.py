"""Dependency-injection registry.

Mirrors the reference's ``driver.Registry`` contract and its lazily
constructed singletons (reference internal/driver/registry.go:26-58,
registry_default.go:158-170): config in, everything else memoized on first
access. ``permission_engine()`` is the seam where the TPU check engine plugs
in instead of the recursive one (reference registry_default.go:158-163 — the
spot the survey marks as "where a TPU CheckEngine plugs in").
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Optional

from keto_tpu import namespace as namespace_pkg
from keto_tpu.check.engine import CheckEngine
from keto_tpu.config.provider import Config
from keto_tpu.driver.batch import CheckBatcher
from keto_tpu.expand.engine import ExpandEngine
from keto_tpu.persistence.memory import MemoryPersister
from keto_tpu.version import __version__ as VERSION
from keto_tpu.x.logging import new_logger


class Registry:
    def __init__(self, config: Config, network_id: str = "default"):
        self._config = config
        self._network_id = network_id
        self._lock = threading.RLock()
        self._singletons: dict[str, Any] = {}
        # engines see namespace hot-reloads through this indirection
        config.on_namespace_change(self._on_namespace_change)

    def _memo(self, key: str, build: Callable[[], Any]) -> Any:
        got = self._singletons.get(key)
        if got is None:
            with self._lock:
                got = self._singletons.get(key)
                if got is None:
                    got = build()
                    self._singletons[key] = got
        return got

    def _on_namespace_change(self) -> None:
        # nothing to invalidate: stores/engines resolve the namespace
        # manager through the callable below on every use
        pass

    def peek(self, key: str):
        """An already-built singleton, or None — shutdown paths use this
        to avoid constructing a dependency just to tear it down."""
        return self._singletons.get(key)

    # -- leaf dependencies ---------------------------------------------------

    def config(self) -> Config:
        return self._config

    def logger(self):
        return self._memo(
            "logger",
            lambda: new_logger(
                self._config.get("log.level", "info"), self._config.get("log.format", "text")
            ),
        )

    def namespace_manager(self) -> namespace_pkg.Manager:
        return self._config.namespace_manager()

    def namespaces_source(self) -> Callable[[], namespace_pkg.Manager]:
        return self._config.namespace_manager

    # -- storage -------------------------------------------------------------

    def relation_tuple_manager(self):
        def build():
            dsn = self._config.dsn
            if dsn == "memory":
                store = MemoryPersister(
                    self.namespaces_source(), network_id=self._network_id
                )
            elif dsn.startswith("sqlite://"):
                from keto_tpu.persistence.sqlite import SQLitePersister

                store = SQLitePersister(
                    dsn, self.namespaces_source(), network_id=self._network_id
                )
            elif dsn.startswith(("postgres://", "postgresql://", "cockroach://")):
                from keto_tpu.persistence.postgres import PostgresPersister

                store = PostgresPersister(
                    dsn, self.namespaces_source(), network_id=self._network_id
                )
            else:
                raise ValueError(f"unsupported dsn {dsn!r}")
            # idempotency keys dedup write retries for this long before GC
            store.idempotency_ttl_s = float(
                self._config.get("serve.idempotency_ttl_s", 86400.0)
            )
            return store

        return self._memo("manager", build)

    # -- engines -------------------------------------------------------------

    def permission_engine(self):
        """The check engine: TPU snapshot engine when the store supports it
        and config allows, else the recursive oracle."""

        def build():
            backend = self._config.get("engine.backend", "auto")
            store = self.relation_tuple_manager()
            if backend != "oracle" and hasattr(store, "snapshot_rows"):
                from keto_tpu.check.tpu_engine import TpuCheckEngine

                return TpuCheckEngine(
                    store,
                    self.namespaces_source(),
                    it_cap=int(self._config.get("engine.it_cap", 4096)),
                    peel_seed_cap=float(self._config.get("engine.peel_seed_cap", 4.0)),
                    sync_rebuild_budget_s=float(
                        self._config.get("engine.sync_rebuild_budget_s", 0.25)
                    ),
                    stream_slice_target_ms=float(
                        self._config.get("serve.stream_slice_target_ms", 40.0)
                    ),
                    overlay_edge_budget=int(
                        self._config.get("serve.overlay_edge_budget", 4096)
                    ),
                    snapshot_cache_dir=(
                        str(self._config.get("serve.snapshot_cache_dir", "") or "")
                        or None
                    ),
                    degraded_probe_s=float(
                        self._config.get("serve.degraded_probe_s", 5.0)
                    ),
                )
            return CheckEngine(store)

        return self._memo("permission_engine", build)

    def expand_depth(self, requested: int) -> int:
        """Clamp a request's max-depth to the configured global cap
        (``limit.max_read_depth``): a request asking for 0 — or more than
        the cap — gets the cap."""
        cap = int(self._config.get("limit.max_read_depth", 5))
        return cap if requested <= 0 or requested > cap else requested

    def expand_engine(self):
        """The expand engine: snapshot-backed (sharing the TPU check
        engine's device snapshots and freshness semantics) when the check
        engine is the TPU one, else the Manager-backed recursion."""

        def build():
            check = self.permission_engine()
            if hasattr(check, "snapshot"):
                from keto_tpu.expand.tpu_engine import SnapshotExpandEngine

                return SnapshotExpandEngine(check, self.namespaces_source())
            return ExpandEngine(self.relation_tuple_manager())

        return self._memo("expand_engine", build)

    def check_batcher(self) -> CheckBatcher:
        def build():
            b = CheckBatcher(
                self.permission_engine(),
                batch_size=int(self._config.get("engine.batch_size", 4096)),
                window_ms=float(self._config.get("engine.batch_window_ms", 1.0)),
                # serving processes shed on a full queue (429 /
                # RESOURCE_EXHAUSTED) instead of letting callers block
                # into their own timeouts — backpressure with an answer
                shed_on_full=bool(self._config.get("serve.shed_on_full", True)),
            )
            b.start()
            return b

        return self._memo("check_batcher", build)

    def health_monitor(self):
        """The serving health state machine (keto_tpu/driver/health.py):
        REST ``/health/ready``, gRPC ``grpc.health.v1``, and operator
        introspection all read the same derived state."""
        from keto_tpu.driver.health import HealthMonitor

        return self._memo(
            "health_monitor",
            lambda: HealthMonitor(
                self.permission_engine(),
                staleness_budget_s=float(
                    self._config.get("serve.staleness_budget_s", 60.0)
                ),
            ),
        )

    # -- observability -------------------------------------------------------

    def tracer(self):
        from keto_tpu.x.tracing import DEFAULT_OTLP_ENDPOINT, Tracer

        return self._memo(
            "tracer",
            lambda: Tracer(
                self._config.get("tracing.provider", ""),
                self.logger(),
                otlp_file=self._config.get("tracing.otlp.file", ""),
                otlp_endpoint=self._config.get(
                    "tracing.otlp.endpoint", DEFAULT_OTLP_ENDPOINT
                ),
            ),
        )

    def telemetry(self):
        from keto_tpu.x.telemetry import Telemetry

        return self._memo(
            "telemetry", lambda: Telemetry(bool(self._config.get("telemetry.enabled", False)))
        )

    # -- info ----------------------------------------------------------------

    def version(self) -> str:
        return VERSION

    def close(self) -> None:
        batcher = self._singletons.get("check_batcher")
        if batcher:
            batcher.stop()
        engine = self._singletons.get("permission_engine")
        if engine is not None and hasattr(engine, "close"):
            engine.close()
        tracer = self._singletons.get("tracer")
        if tracer is not None:
            tracer.close()
        store = self._singletons.get("manager")
        if store is not None and hasattr(store, "close"):
            store.close()
        self._config.close()
