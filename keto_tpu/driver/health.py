"""Health / readiness state machine for the serving core.

The reference answers ``/health/ready`` and ``grpc.health.v1`` statically
(reference internal/driver/registry_default.go:97-111) — fine for a
stateless SQL frontend, wrong for this architecture: the TPU engine's
correctness depends on *background maintenance* (snapshot refresh,
compaction, cache saves) and a *device path* that can fail independently
of the process being up. A dead refresh thread here used to mean serving
permissions frozen at some past watermark forever, with every health
surface still green.

This module derives one externally visible state from the engine's live
health inputs (``TpuCheckEngine.health()``):

::

                      first snapshot          device path failing
        STARTING ───────────────────▶ SERVING ◀─────────────────▶ DEGRADED
                                        ▲  │ staleness > budget,          │
                                        │  │ or maintenance dead          │
                         refresh caught │  ▼                              │
                         up / thread ok └─ NOT_SERVING ◀──────────────────┘
                                                         (degraded AND stale)

- **STARTING** — no snapshot yet and nothing has failed. *Ready*: a cold
  engine builds its snapshot inline on first check, so refusing traffic
  would only delay the build.
- **SERVING** — snapshot within the staleness budget, maintenance alive.
- **DEGRADED** — the device path is failing and checks are served by the
  CPU reference engine (bit-identical decisions, reference throughput).
  Still *ready* — answers remain correct.
- **NOT_SERVING** — answers can no longer be trusted fresh: the snapshot
  is further behind the store than ``serve.staleness_budget_s`` allows,
  or the maintenance supervisor thread itself died. REST ``/health/ready``
  returns 503 + reason, gRPC health returns ``NOT_SERVING`` (and
  streaming ``Watch`` emits the transition).

The state is *derived on read* (staleness is a function of wall time, so
an event-push design would need a timer wheel to notice "nothing
happened for too long"); ``watch()`` polls cheaply and yields only
transitions. ``set_override`` is the operator drain seam.
"""

from __future__ import annotations

import enum
import logging
import threading
import time
from collections import deque
from typing import Callable, Optional

_log = logging.getLogger("keto_tpu.health")


class HealthState(enum.Enum):
    STARTING = "starting"
    SERVING = "serving"
    DEGRADED = "degraded"
    NOT_SERVING = "not_serving"


#: states in which the server should accept traffic
READY_STATES = (HealthState.STARTING, HealthState.SERVING, HealthState.DEGRADED)


class HealthMonitor:
    """Derives the serving state from an engine's ``health()`` inputs.

    ``engine`` may be any check engine: one without a ``health()`` method
    (the recursive oracle — no snapshot, no staleness concept) is always
    SERVING. Transitions are logged, counted into the engine's
    MaintenanceStats when present (``health_transitions`` counter +
    ``health_state`` gauge), and broadcast to ``watch()`` streams."""

    def __init__(
        self,
        engine=None,
        *,
        staleness_budget_s: float = 60.0,
        replica=None,
    ):
        """``replica`` is a ReplicaController (keto_tpu/replica/) on a
        read replica, else None: pre-bootstrap reads as STARTING, and
        feed lag (or primary loss — indistinguishable) past the
        controller's staleness budget reads as DEGRADED(replication_lag).
        The replica keeps serving at its watermark throughout."""
        self._engine = engine
        self._replica = replica
        self._budget = float(staleness_budget_s)
        self._lock = threading.Lock()  # guards: _last_state, _last_reason, _override, _transitions, transitions_log
        self._last_state: Optional[HealthState] = None
        self._last_reason = ""
        self._override: Optional[tuple[HealthState, str]] = None
        self._transitions = 0
        #: recent transitions [(unix, from, to, reason)] — the flight
        #: recorder's health-history section
        self.transitions_log: deque[dict] = deque(maxlen=64)
        # transition listeners (the flight recorder's trigger seam);
        # invoked OUTSIDE the monitor lock, exceptions contained
        self._listeners: list[Callable[[HealthState, str], None]] = []

    def set_engine(self, engine) -> None:
        """Live-reshard handoff: derive state from the newly installed
        engine (keto_tpu/fleet/reshard.py) — the retiring engine's
        health inputs stop mattering the moment it stops serving."""
        self._engine = engine

    def set_replica(self, replica) -> None:
        """Fleet promotion handoff: detach (None) or attach the replica
        controller this monitor derives replication state from — a
        promoted node stops reading STARTING/DEGRADED(replication_lag)
        off a feed it no longer runs."""
        self._replica = replica

    @property
    def staleness_budget_s(self) -> float:
        return self._budget

    @property
    def transitions(self) -> int:
        """State transitions observed since boot (the /metrics counter)."""
        with self._lock:
            return self._transitions

    # -- the state machine ---------------------------------------------------

    def add_listener(self, fn: Callable[[HealthState, str], None]) -> None:
        """Call ``fn(state, reason)`` on every state transition (the
        flight recorder hooks anomaly dumps here). Listeners run outside
        the monitor lock; exceptions are contained and logged."""
        with self._lock:
            self._listeners.append(fn)

    def status(self) -> tuple[HealthState, str]:
        """Current ``(state, reason)``; reason is "" while SERVING."""
        state, reason = self._compute()
        transitioned = False
        with self._lock:
            if state != self._last_state:
                if self._last_state is not None:
                    _log.warning(
                        "health transition %s -> %s%s",
                        self._last_state.value, state.value,
                        f" ({reason})" if reason else "",
                    )
                self.transitions_log.append(
                    {
                        "unix": round(time.time(), 3),
                        "from": (
                            self._last_state.value
                            if self._last_state is not None else None
                        ),
                        "to": state.value,
                        "reason": reason,
                    }
                )
                transitioned = self._last_state is not None
                self._transitions += 1
                self._last_state = state
                self._record(state)
            self._last_reason = reason
            listeners = list(self._listeners) if transitioned else []
        for fn in listeners:
            try:
                fn(state, reason)
            except Exception:
                _log.warning(
                    "health transition listener failed", exc_info=True
                )
        return state, reason

    def ready(self) -> bool:
        return self.status()[0] in READY_STATES

    def set_override(self, state: Optional[HealthState], reason: str = "") -> None:
        """Operator seam: pin the reported state (drain before maintenance,
        fault rehearsal); ``None`` returns control to the derived state."""
        with self._lock:
            self._override = None if state is None else (state, reason)

    def _compute(self) -> tuple[HealthState, str]:
        with self._lock:
            if self._override is not None:
                return self._override
        eng = self._engine
        if eng is None or not hasattr(eng, "health"):
            return HealthState.SERVING, ""
        try:
            h = eng.health()
        except Exception as e:  # a broken health probe is itself a failure
            return HealthState.NOT_SERVING, f"health probe failed: {e}"
        if not h.get("maintenance_alive", True):
            return (
                HealthState.NOT_SERVING,
                "snapshot maintenance thread died: " + (h.get("refresh_last_error") or "unknown"),
            )
        staleness = float(h.get("staleness_s", 0.0))
        if staleness > self._budget:
            reason = (
                f"snapshot {staleness:.1f}s behind the store "
                f"(budget {self._budget:.1f}s)"
            )
            err = h.get("refresh_last_error")
            if err:
                reason += f"; last refresh error: {err}"
            return HealthState.NOT_SERVING, reason
        if not h.get("has_snapshot", True):
            # a 50M-tuple cold start builds for minutes — the streaming
            # pipeline's progress (keto_tpu/graph/stream_build.py) is the
            # boot heartbeat, so STARTING reads as alive, not hung
            phase = h.get("build_phase")
            if phase and phase != "idle":
                pct = float(h.get("build_pct") or 0.0)
                return (
                    HealthState.STARTING,
                    f"building first snapshot: phase={phase} ({pct:.0%}, "
                    f"{int(h.get('build_rows_ingested') or 0)} rows ingested)",
                )
            return HealthState.STARTING, "first snapshot not built yet"
        if int(h.get("audit_mismatches", 0) or 0) > 0:
            # the one alarm that must never be rationalized away: a
            # sampled live decision diverged from the CPU reference
            # oracle (keto_audit_mismatches_total)
            return (
                HealthState.DEGRADED,
                "shadow-parity audit observed device/oracle divergence "
                f"({int(h['audit_mismatches'])} mismatches)",
            )
        if h.get("degraded"):
            return (
                HealthState.DEGRADED,
                "device path failing; serving bit-identical decisions "
                "from the CPU fallback engine",
            )
        rep = self._replica
        if rep is not None:
            if not rep.bootstrapped:
                return (
                    HealthState.STARTING,
                    "replica bootstrapping from the primary "
                    f"({rep.primary_url})",
                )
            lag = rep.lag_s()
            if lag > rep.staleness_budget_s:
                detail = (
                    "primary unreachable"
                    if not rep.primary_connected
                    else "watch feed behind"
                )
                return (
                    HealthState.DEGRADED,
                    f"replication_lag: {detail} — last confirmed caught up "
                    f"{lag:.1f}s ago (budget {rep.staleness_budget_s:.1f}s); "
                    f"serving at applied watermark {rep.watermark}",
                )
        if h.get("memory_pressure"):
            # the HBM governor refused the last refresh with every
            # eviction rung spent: answers stay correct but bounded-stale
            # until pressure clears (staleness_budget_s still escalates
            # to NOT_SERVING above)
            return (
                HealthState.DEGRADED,
                "memory_pressure: HBM budget refused the last snapshot "
                "refresh (eviction ladder spent); serving stale within "
                "the staleness budget",
            )
        return HealthState.SERVING, ""

    def starting_detail(self) -> dict:
        """``{"phase": ..., "pct": ...}`` of an in-flight first build
        (the streaming pipeline's progress), or ``{}`` — REST
        ``/health/ready`` merges this into the STARTING body so a
        multi-minute boot reports where it is instead of a bare state."""
        eng = self._engine
        if eng is None or not hasattr(eng, "health"):
            return {}
        try:
            h = eng.health()
        except Exception:
            return {}
        phase = h.get("build_phase")
        if not phase or phase == "idle":
            return {}
        return {
            "phase": str(phase),
            "pct": round(float(h.get("build_pct") or 0.0), 3),
            "rows_ingested": int(h.get("build_rows_ingested") or 0),
        }

    def _record(self, state: HealthState) -> None:
        stats = getattr(self._engine, "maintenance", None)
        if stats is not None:
            stats.incr("health_transitions")
            stats.set_gauge("health_state", state.value)

    # -- streaming (gRPC Watch) ----------------------------------------------

    def watch(self, poll_s: float = 0.2, should_stop: Optional[Callable[[], bool]] = None):
        """Yield ``(state, reason)`` — the current state immediately, then
        one entry per transition. ``should_stop()`` (e.g. a gRPC
        context-active probe, negated) ends the stream."""
        last: Optional[HealthState] = None
        while should_stop is None or not should_stop():
            state, reason = self.status()
            if state != last:
                yield state, reason
                last = state
            time.sleep(poll_s)

    # -- introspection -------------------------------------------------------

    def snapshot(self) -> dict:
        """Operator view: state, reason, budget, transition count, and the
        engine's raw health inputs."""
        state, reason = self.status()
        out = {
            "state": state.value,
            "reason": reason,
            "staleness_budget_s": self._budget,
            "transitions": self._transitions,
            "transitions_log": list(self.transitions_log),
        }
        eng = self._engine
        if eng is not None and hasattr(eng, "health"):
            try:
                out["engine"] = eng.health()
            except Exception as e:
                out["engine"] = {"error": str(e)}
        return out
