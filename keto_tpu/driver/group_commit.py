"""Group-commit coordinator: many writers, one durable transaction.

Every write API call used to pay its own SQL BEGIN/COMMIT (an fsync per
writer on sqlite, a WAL flush per writer on postgres) — the write-path
ceiling was durability round-trips, not row work. This coordinator is
the write-side sibling of the check batcher (keto_tpu/driver/batch.py):
concurrent writers enqueue their (insert, delete, idempotency_key)
intents, a collector thread coalesces them over a small size/latency
window (``serve.group_commit_max_writers`` / ``serve.group_commit_
window_ms``), and ONE ``Manager.transact_many`` call commits the whole
group durably — batched ``executemany`` row inserts, one fsync.

Per-writer semantics are untouched: each writer receives its own
snaptoken from the group's commit sequence, its own replayable
idempotency-key row (committed atomically with its rows), and — because
watch commit groups key on commit_time — its own Watch commit group
carrying its own traceparent (the handlers register token → traceparent
AFTER the future resolves, exactly as on the solo path). The group is
all-or-nothing durably: the chaos kill points ``group-commit`` (inside
the shared transaction, pre-COMMIT — no writer survives) and
``group-ack`` (post-COMMIT, pre-fanout — every writer survives and
every keyed retry replays) bracket the commit (tests/test_chaos.py).

Failure semantics: a store error fails EVERY writer in the group with
the same exception — the callers retry individually (keyed retries
dedup), exactly as if their solo transactions had all hit the same
outage. Backpressure is blocking, not shedding: a write has no cheap
"try again" answer, so past ``max_pending`` queued writers the enqueue
waits (bounded by the caller's timeout) instead of 429ing.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Optional, Sequence

from keto_tpu.relationtuple.manager import TransactResult, TransactWrite

_log = logging.getLogger("keto_tpu.driver.group_commit")


class GroupCommitCoordinator:
    """Batches concurrent ``transact`` calls into ``transact_many``
    groups. Start with :meth:`start`; stop via :meth:`stop` (fails
    leftover writers) after :meth:`drain` (waits for quiesce)."""

    def __init__(
        self,
        store,
        *,
        max_writers: int = 128,
        window_ms: float = 2.0,
        max_pending: int = 4096,
        wait_histogram=None,
        batch_histogram=None,
    ):
        self._store = store
        self._max_writers = max(1, int(max_writers))
        self._window_s = max(0.0, float(window_ms)) / 1e3
        self._max_pending = max(self._max_writers, int(max_pending))
        self._wait_hist = wait_histogram
        self._batch_hist = batch_histogram
        self._cond = threading.Condition()
        self._queue: deque = deque()  # (TransactWrite, _Slot)
        self._stopping = False
        self._thread: Optional[threading.Thread] = None
        self._idle = threading.Event()
        self._idle.set()
        self._inflight = 0
        #: groups committed (keto_group_commit_flush_total)
        self.flush_total = 0
        #: writers committed across all groups (avg batch size =
        #: writers_total / flush_total)
        self.writers_total = 0
        #: size of the most recent group (keto_group_commit_batch_size
        #: gauge peek)
        self.last_batch_size = 0
        #: groups that failed (every writer saw the error)
        self.flush_errors = 0

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="group-commit", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        """Stop the collector; leftover queued writers fail with a
        RuntimeError (the daemon drains before stopping, so a leftover
        here means the drain window expired)."""
        with self._cond:
            self._stopping = True
            self._cond.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout=10.0)
            self._thread = None
        with self._cond:
            leftovers = list(self._queue)
            self._queue.clear()
            self._idle.set()
        for slot in leftovers:
            slot.fail(RuntimeError("group-commit coordinator stopped"))

    def drain(self, timeout_s: float) -> bool:
        """Wait until no writer is queued or inflight (daemon shutdown
        sequencing). True when quiesced within the window."""
        return self._idle.wait(timeout=max(0.0, timeout_s))

    @property
    def inflight(self) -> int:
        with self._cond:
            return self._inflight + len(self._queue)

    # -- write path ----------------------------------------------------------

    def transact(
        self,
        insert: Sequence,
        delete: Sequence,
        idempotency_key: Optional[str] = None,
        timeout_s: float = 30.0,
    ) -> Optional[TransactResult]:
        """Enqueue one writer's intent and block until its group
        commits. Raises whatever the group's ``transact_many`` raised,
        or TimeoutError when the result misses ``timeout_s``."""
        slot = _Slot(
            TransactWrite(
                insert=tuple(insert),
                delete=tuple(delete),
                idempotency_key=idempotency_key,
            )
        )
        deadline = time.monotonic() + timeout_s
        with self._cond:
            if self._stopping:
                raise RuntimeError("group-commit coordinator stopped")
            # blocking backpressure: a write has no sheddable answer
            while len(self._queue) >= self._max_pending:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._stopping:
                    raise TimeoutError("group-commit queue full")
                self._cond.wait(min(remaining, 0.1))
            self._queue.append(slot)
            self._idle.clear()
            self._cond.notify_all()
        return slot.wait(deadline)

    # -- collector -----------------------------------------------------------

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._stopping:
                    self._idle.set()
                    self._cond.wait(0.05)
                if self._stopping:
                    return  # stop() fails whatever is left
                self._idle.clear()
                # coalescing window: wait (bounded) for more writers,
                # flush at the size cap or the latency deadline —
                # whichever lands first
                if self._window_s > 0:
                    deadline = time.monotonic() + self._window_s
                    while (
                        len(self._queue) < self._max_writers
                        and not self._stopping
                    ):
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            break
                        self._cond.wait(remaining)
                n = min(len(self._queue), self._max_writers)
                batch = [self._queue.popleft() for _ in range(n)]
                self._inflight += n
                self._cond.notify_all()  # wake backpressured enqueuers
            if batch:
                self._commit(batch)
            with self._cond:
                self._inflight -= len(batch)
                if not self._queue and self._inflight == 0:
                    self._idle.set()

    def _commit(self, batch: list) -> None:
        start = time.monotonic()
        if self._wait_hist is not None:
            for slot in batch:
                self._wait_hist.observe(value=start - slot.enqueued_at)
        try:
            results = self._store.transact_many([s.write for s in batch])
        except Exception as e:  # noqa: BLE001 — forwarded to every writer
            self.flush_errors += 1
            for slot in batch:
                slot.fail(e)
            return
        self.flush_total += 1
        self.writers_total += len(batch)
        self.last_batch_size = len(batch)
        if self._batch_hist is not None:
            self._batch_hist.observe(value=float(len(batch)))
        for slot, result in zip(batch, results):
            slot.resolve(result)


class _Slot:
    """One writer's parked result: event + cell (lighter than a Future,
    and immune to InvalidStateError races on shutdown)."""

    __slots__ = ("write", "enqueued_at", "_done", "_result", "_exc")

    def __init__(self, write: TransactWrite):
        self.write = write
        self.enqueued_at = time.monotonic()
        self._done = threading.Event()
        self._result: Optional[TransactResult] = None
        self._exc: Optional[BaseException] = None

    def resolve(self, result) -> None:
        self._result = result
        self._done.set()

    def fail(self, exc: BaseException) -> None:
        if not self._done.is_set():
            self._exc = exc
            self._done.set()

    def wait(self, deadline: float) -> Optional[TransactResult]:
        if not self._done.wait(timeout=max(0.0, deadline - time.monotonic())):
            raise TimeoutError("group commit timed out")
        if self._exc is not None:
            raise self._exc
        return self._result
