"""Request-coalescing check batcher.

The reference serves one goroutine per request, each paying its own
traversal (reference internal/driver/daemon.go:62-69). On TPU the economics
invert: one device program answers thousands of checks, so concurrent
single-check requests are *coalesced* — a caller enqueues its tuple and
blocks on a future; a collector thread drains the queue up to
``batch_size`` or ``window_ms`` (whichever first) and dispatches one
``batch_check``. This is the serving-plane analog of the data-parallel axis
(SURVEY §2.3: request concurrency → batch parallelism).

Against the TPU engine the dispatch is STREAMING: the coalesced batch goes
through ``batch_check_stream_with_token(ordered=False)`` — the engine's
latency-adaptive ready-order pipeline — and each caller's future resolves
the moment its slice lands, re-associated by query offset. Production
``/check`` traffic (REST async/threading backends and gRPC all route
through this batcher) therefore sees per-slice serving latency, not
whole-batch latency, when the device splits a large batch.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future, InvalidStateError
from typing import Optional, Sequence

from keto_tpu.relationtuple.model import RelationTuple


class CheckBatcher:
    def __init__(
        self,
        engine,
        batch_size: int = 4096,
        window_ms: float = 1.0,
        max_pending: Optional[int] = None,
    ):
        """``engine`` needs ``batch_check(list[RelationTuple]) -> list[bool]``.

        ``max_pending`` bounds the queue (default 8×batch_size): when the
        device can't keep up, callers block in ``check`` up to their own
        timeout instead of growing an unbounded backlog — backpressure
        propagates to the accepting sockets rather than to memory."""
        self._engine = engine
        self._batch_size = batch_size
        self._window_s = window_ms / 1e3
        self._queue: queue.Queue = queue.Queue(maxsize=max_pending or 8 * batch_size)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._thread:
            return
        self._thread = threading.Thread(target=self._loop, name="check-batcher", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        try:
            self._queue.put_nowait(None)  # fast wake when the queue is idle
        except queue.Full:
            pass  # collector is mid-drain; it polls the stop flag
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None
        # requests still queued would otherwise block their callers for the
        # full future timeout — fail them promptly instead
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item is not None and not item[1].done():
                item[1].set_exception(RuntimeError("check batcher stopped"))

    # -- API -----------------------------------------------------------------

    def check(
        self,
        tuple_: RelationTuple,
        timeout: Optional[float] = 30.0,
        *,
        at_least: Optional[int] = None,
        latest: bool = False,
    ) -> bool:
        """Blocking single check, transparently batched with concurrent
        callers. Default consistency is the serving mode (bounded
        staleness, never stalled by a rebuild); ``at_least`` pins a
        caller's snaptoken, ``latest`` forces read-your-writes."""
        return self.check_with_token(
            tuple_, timeout, at_least=at_least, latest=latest
        )[0]

    def check_with_token(
        self,
        tuple_: RelationTuple,
        timeout: Optional[float] = 30.0,
        *,
        at_least: Optional[int] = None,
        latest: bool = False,
    ) -> tuple[bool, Optional[int]]:
        """``check`` plus the id of the snapshot that decided it (None when
        the engine has no snapshot concept — e.g. the recursive oracle,
        which reads the store directly and is always fresh)."""
        if self._stop.is_set():
            raise RuntimeError("check batcher stopped")
        deadline = None if timeout is None else time.monotonic() + timeout
        fut: Future = Future()
        try:
            # a full queue blocks the caller — the backpressure seam
            # between accepts and the device — against the SAME deadline
            # the result wait uses, so the total never exceeds ``timeout``
            self._queue.put((tuple_, fut, at_least, latest), timeout=timeout)
        except queue.Full:
            raise TimeoutError("check queue full (device backlogged)") from None
        if self._stop.is_set() and not fut.done():
            # raced with stop()'s drain: nobody will serve the queue
            # anymore — unless the collector's final batch got there first
            try:
                fut.set_exception(RuntimeError("check batcher stopped"))
            except InvalidStateError:
                pass  # the collector resolved it; return that result
        remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
        return fut.result(timeout=remaining)

    def check_batch(self, tuples: Sequence[RelationTuple]) -> list[bool]:
        """Pre-batched requests skip the queue entirely."""
        return self._engine.batch_check(list(tuples))

    @staticmethod
    def _consistency_kw(at_leasts, latests) -> dict:
        """The strongest requested consistency wins (freshness is monotone
        — a fresher snapshot satisfies every weaker requirement in the
        batch)."""
        if any(latests):
            # read-your-writes dominates every floor in the batch
            return {"mode": "latest"}
        floors = [a for a in at_leasts if a is not None]
        return {"at_least": max(floors) if floors else None, "mode": "serving"}

    def _dispatch(self, tuples, at_leasts, latests):
        """One engine call for a coalesced batch."""
        if hasattr(self._engine, "batch_check_with_token"):
            return self._engine.batch_check_with_token(
                tuples, **self._consistency_kw(at_leasts, latests)
            )
        # oracle engine: always fresh (reads the store per traversal
        # step), no snapshot concept
        if hasattr(self._engine, "batch_check"):
            return self._engine.batch_check(tuples), None
        return [self._engine.subject_is_allowed(t) for t in tuples], None

    def _dispatch_stream(self, batch, tuples, at_leasts, latests) -> None:
        """Streaming dispatch for engines with the ready-order stream API:
        each caller's future resolves the moment ITS slice lands (the
        ``ordered=False`` fast path — re-association is by query offset),
        so early-finishing slices of a large coalesced batch don't wait
        behind stragglers. Mid-stream failures propagate to the caller
        (``_loop`` fails every still-unresolved future)."""
        gen, token = self._engine.batch_check_stream_with_token(
            iter(tuples), ordered=False,
            **self._consistency_kw(at_leasts, latests),
        )
        for off, out in gen:
            for j, allowed in enumerate(out.tolist()):
                fut = batch[off + j][1]
                if not fut.done():
                    fut.set_result((bool(allowed), token))

    # -- collector -----------------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                # bounded wait so a stop() against a FULL queue (whose
                # sentinel could not be enqueued) still terminates the loop
                item = self._queue.get(timeout=0.25)
            except queue.Empty:
                continue
            if item is None:
                continue
            batch = [item]
            # drain whatever arrives within the window, up to batch_size —
            # each wait blocks on the queue's condition for exactly the
            # remaining window, no polling
            deadline = time.monotonic() + self._window_s
            while len(batch) < self._batch_size:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    nxt = self._queue.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is None:
                    break
                batch.append(nxt)

            tuples = [t for t, _, _, _ in batch]
            at_leasts = [a for _, _, a, _ in batch]
            latests = [l for _, _, _, l in batch]
            try:
                if hasattr(self._engine, "batch_check_stream_with_token"):
                    self._dispatch_stream(batch, tuples, at_leasts, latests)
                    continue
                results, token = self._dispatch(tuples, at_leasts, latests)
            except Exception as e:  # engine failure → every caller sees it
                for _, fut, _, _ in batch:
                    if not fut.done():
                        fut.set_exception(e)
                continue
            for (_, fut, _, _), allowed in zip(batch, results):
                if not fut.done():
                    fut.set_result((allowed, token))
