"""Request-coalescing check batcher.

The reference serves one goroutine per request, each paying its own
traversal (reference internal/driver/daemon.go:62-69). On TPU the economics
invert: one device program answers thousands of checks, so concurrent
single-check requests are *coalesced* — a caller enqueues its tuple and
blocks on a future; a collector thread drains the queue up to
``batch_size`` or ``window_ms`` (whichever first) and dispatches one
``batch_check``. This is the serving-plane analog of the data-parallel axis
(SURVEY §2.3: request concurrency → batch parallelism).

Against the TPU engine the dispatch is STREAMING: the coalesced batch goes
through ``batch_check_stream_with_token(ordered=False)`` — the engine's
latency-adaptive ready-order pipeline — and each caller's future resolves
the moment its slice lands, re-associated by query offset. Production
``/check`` traffic (REST async/threading backends and gRPC all route
through this batcher) therefore sees per-slice serving latency, not
whole-batch latency, when the device splits a large batch.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from concurrent.futures import Future, InvalidStateError
from concurrent.futures import TimeoutError as FutureTimeout
from typing import Optional, Sequence

from keto_tpu.relationtuple.model import RelationTuple
from keto_tpu.x import faults
from keto_tpu.x.errors import ErrDeadlineExceeded, ErrTooManyRequests, KetoError

_log = logging.getLogger("keto_tpu.batch")


class CheckBatcher:
    def __init__(
        self,
        engine,
        batch_size: int = 4096,
        window_ms: float = 1.0,
        max_pending: Optional[int] = None,
        shed_on_full: bool = False,
    ):
        """``engine`` needs ``batch_check(list[RelationTuple]) -> list[bool]``.

        ``max_pending`` bounds the queue (default 8×batch_size): when the
        device can't keep up, callers block in ``check`` up to their own
        timeout instead of growing an unbounded backlog — backpressure
        propagates to the accepting sockets rather than to memory. With
        ``shed_on_full`` (what the registry configures for serving
        processes), a full queue instead *sheds immediately* with
        ``ErrTooManyRequests`` (REST 429 / gRPC RESOURCE_EXHAUSTED) — the
        client learns it should back off *now*, seconds ahead of the
        future timeout it would otherwise burn."""
        self._engine = engine
        self._batch_size = batch_size
        self._window_s = window_ms / 1e3
        self._queue: queue.Queue = queue.Queue(maxsize=max_pending or 8 * batch_size)
        self._shed_on_full = shed_on_full
        #: requests refused at the door (queue full)
        self.shed_count = 0
        #: requests dropped at dispatch because their deadline had passed
        self.deadline_drop_count = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # in-flight accounting for graceful drain: accepted requests whose
        # futures have not resolved yet (queued OR dispatched)
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self._idle = threading.Event()
        self._idle.set()

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._thread:
            return
        self._thread = threading.Thread(target=self._loop, name="check-batcher", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        try:
            self._queue.put_nowait(None)  # fast wake when the queue is idle
        except queue.Full:
            pass  # collector is mid-drain; it polls the stop flag
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None
        # requests still queued would otherwise block their callers for the
        # full future timeout — fail them promptly instead
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item is not None and not item[1].done():
                item[1].set_exception(RuntimeError("check batcher stopped"))

    # -- API -----------------------------------------------------------------

    def check(
        self,
        tuple_: RelationTuple,
        timeout: Optional[float] = 30.0,
        *,
        at_least: Optional[int] = None,
        latest: bool = False,
        deadline: Optional[float] = None,
    ) -> bool:
        """Blocking single check, transparently batched with concurrent
        callers. Default consistency is the serving mode (bounded
        staleness, never stalled by a rebuild); ``at_least`` pins a
        caller's snaptoken, ``latest`` forces read-your-writes."""
        return self.check_with_token(
            tuple_, timeout, at_least=at_least, latest=latest, deadline=deadline
        )[0]

    def check_with_token(
        self,
        tuple_: RelationTuple,
        timeout: Optional[float] = 30.0,
        *,
        at_least: Optional[int] = None,
        latest: bool = False,
        deadline: Optional[float] = None,
    ) -> tuple[bool, Optional[int]]:
        """``check`` plus the id of the snapshot that decided it (None when
        the engine has no snapshot concept — e.g. the recursive oracle,
        which reads the store directly and is always fresh).

        ``deadline`` is the request's *absolute* ``time.monotonic()``
        deadline (REST/gRPC propagate theirs): it rides with the queued
        request so the collector sheds it *before packing* if it expires
        waiting, and the caller gets ``ErrDeadlineExceeded`` (504 /
        DEADLINE_EXCEEDED) instead of an answer nobody is waiting for.
        ``timeout`` remains the relative cap; the earlier of the two
        wins."""
        if self._stop.is_set():
            raise RuntimeError("check batcher stopped")
        if timeout is not None:
            t_deadline = time.monotonic() + timeout
            deadline = t_deadline if deadline is None else min(deadline, t_deadline)
        if deadline is not None and time.monotonic() >= deadline:
            raise ErrDeadlineExceeded("deadline expired before the check was queued")
        fut: Future = Future()
        item = (tuple_, fut, at_least, latest, deadline)
        if self._shed_on_full:
            # serving mode: a full queue answers 429 NOW — the device is
            # backlogged and queueing deeper only converts the client's
            # timeout budget into server memory
            try:
                self._queue.put_nowait(item)
            except queue.Full:
                self.shed_count += 1
                raise ErrTooManyRequests(
                    "check queue full (device backlogged); retry with backoff"
                ) from None
        else:
            try:
                # a full queue blocks the caller — the backpressure seam
                # between accepts and the device — against the SAME
                # deadline the result wait uses, so the total never
                # exceeds ``timeout``
                block = None
                if deadline is not None:
                    block = max(0.0, deadline - time.monotonic())
                self._queue.put(item, timeout=block)
            except queue.Full:
                raise TimeoutError("check queue full (device backlogged)") from None
        with self._inflight_lock:
            self._inflight += 1
            self._idle.clear()
        fut.add_done_callback(self._note_done)
        if self._stop.is_set() and not fut.done():
            # raced with stop()'s drain: nobody will serve the queue
            # anymore — unless the collector's final batch got there first
            try:
                fut.set_exception(RuntimeError("check batcher stopped"))
            except InvalidStateError:
                pass  # the collector resolved it; return that result
        remaining = None
        if deadline is not None:
            remaining = max(0.0, deadline - time.monotonic())
        try:
            return fut.result(timeout=remaining)
        except FutureTimeout:
            raise ErrDeadlineExceeded(
                "deadline expired waiting for the check result"
            ) from None

    def check_batch(self, tuples: Sequence[RelationTuple]) -> list[bool]:
        """Pre-batched requests skip the queue entirely."""
        return self._engine.batch_check(list(tuples))

    # -- graceful drain ------------------------------------------------------

    def _note_done(self, _fut) -> None:
        with self._inflight_lock:
            self._inflight -= 1
            if self._inflight <= 0:
                self._idle.set()

    @property
    def inflight(self) -> int:
        """Accepted check requests whose futures have not resolved yet."""
        with self._inflight_lock:
            return self._inflight

    @property
    def queue_depth(self) -> int:
        """Requests queued but not yet packed into a device batch (the
        /metrics pressure gauge; approximate by nature)."""
        return self._queue.qsize()

    def drain(self, timeout_s: float) -> bool:
        """Wait until every in-flight request has been answered (the
        SIGTERM drain seam: new traffic is already shed by the health
        override before this runs). True when the batcher went idle
        within ``timeout_s``."""
        return self._idle.wait(timeout=max(0.0, timeout_s))

    @staticmethod
    def _consistency_kw(at_leasts, latests) -> dict:
        """The strongest requested consistency wins (freshness is monotone
        — a fresher snapshot satisfies every weaker requirement in the
        batch)."""
        if any(latests):
            # read-your-writes dominates every floor in the batch
            return {"mode": "latest"}
        floors = [a for a in at_leasts if a is not None]
        return {"at_least": max(floors) if floors else None, "mode": "serving"}

    def _dispatch(self, tuples, at_leasts, latests):
        """One engine call for a coalesced batch."""
        if hasattr(self._engine, "batch_check_with_token"):
            return self._engine.batch_check_with_token(
                tuples, **self._consistency_kw(at_leasts, latests)
            )
        # oracle engine: always fresh (reads the store per traversal
        # step), no snapshot concept
        if hasattr(self._engine, "batch_check"):
            return self._engine.batch_check(tuples), None
        return [self._engine.subject_is_allowed(t) for t in tuples], None

    def _expire(self, fut: Future) -> None:
        self.deadline_drop_count += 1
        if not fut.done():
            fut.set_exception(
                ErrDeadlineExceeded("deadline expired before dispatch")
            )

    def _dispatch_stream(self, batch, at_leasts, latests) -> None:
        """Streaming dispatch for engines with the ready-order stream API:
        each caller's future resolves the moment ITS slice lands (the
        ``ordered=False`` fast path — re-association is by query offset),
        so early-finishing slices of a large coalesced batch don't wait
        behind stragglers. Mid-stream failures propagate to the caller
        (``_loop`` retries unresolved futures once, then fails them).

        Deadlines are enforced at PACK time: the tuple iterator the
        stream slices from skips requests whose deadline has passed —
        they get ``ErrDeadlineExceeded`` and never occupy a device slice
        (an expired request in a slice would displace a live one)."""
        emitted: list = []  # stream offset -> batch item, built at pull time

        def live_tuples():
            for item in batch:
                dl = item[4]
                if dl is not None and time.monotonic() >= dl:
                    self._expire(item[1])
                    continue
                emitted.append(item)
                yield item[0]

        gen, token = self._engine.batch_check_stream_with_token(
            live_tuples(), ordered=False,
            **self._consistency_kw(at_leasts, latests),
        )
        for off, out in gen:
            for j, allowed in enumerate(out.tolist()):
                fut = emitted[off + j][1]
                if not fut.done():
                    fut.set_result((bool(allowed), token))

    # -- collector -----------------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                # bounded wait so a stop() against a FULL queue (whose
                # sentinel could not be enqueued) still terminates the loop
                item = self._queue.get(timeout=0.25)
            except queue.Empty:
                continue
            if item is None:
                continue
            batch = [item]
            # drain whatever arrives within the window, up to batch_size —
            # each wait blocks on the queue's condition for exactly the
            # remaining window, no polling
            deadline = time.monotonic() + self._window_s
            while len(batch) < self._batch_size:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    nxt = self._queue.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is None:
                    break
                batch.append(nxt)

            # shed expired requests before any engine work: they never
            # occupy a slice, and their callers hear 504 immediately
            now = time.monotonic()
            live = []
            for item in batch:
                if item[4] is not None and now >= item[4]:
                    self._expire(item[1])
                else:
                    live.append(item)
            batch = live
            if not batch:
                continue
            at_leasts = [a for _, _, a, _, _ in batch]
            latests = [l for _, _, _, l, _ in batch]
            try:
                faults.check("check-dispatch")
                if hasattr(self._engine, "batch_check_stream_with_token"):
                    self._dispatch_stream(batch, at_leasts, latests)
                    continue
                tuples = [t for t, _, _, _, _ in batch]
                results, token = self._dispatch(tuples, at_leasts, latests)
            except Exception as e:
                self._fail_or_retry(batch, e)
                continue
            for (_, fut, _, _, _), allowed in zip(batch, results):
                if not fut.done():
                    fut.set_result((allowed, token))

    def _fail_or_retry(self, batch, exc: Exception) -> None:
        """A failed dispatch retries its unresolved requests ONCE through
        the engine's plain batch path — a device fault mid-stream flips
        the engine into its CPU degraded mode, so the retry lands on the
        fallback and callers never see the fault. Client errors
        (KetoError) and a failed retry propagate to every waiting
        future."""
        pending = [item for item in batch if not item[1].done()]
        if pending and not isinstance(exc, KetoError):
            _log.warning(
                "batch dispatch failed (%s: %s); retrying %d unresolved "
                "checks on the engine's recovery path",
                type(exc).__name__, exc, len(pending),
            )
            try:
                results, token = self._dispatch(
                    [t for t, _, _, _, _ in pending],
                    [a for _, _, a, _, _ in pending],
                    [l for _, _, _, l, _ in pending],
                )
            except Exception as e2:
                exc = e2
            else:
                for (_, fut, _, _, _), allowed in zip(pending, results):
                    if not fut.done():
                        fut.set_result((bool(allowed), token))
                return
        for item in batch:
            if not item[1].done():
                item[1].set_exception(exc)
