"""Request-coalescing check batcher.

The reference serves one goroutine per request, each paying its own
traversal (reference internal/driver/daemon.go:62-69). On TPU the economics
invert: one device program answers thousands of checks, so concurrent
single-check requests are *coalesced* — a caller enqueues its tuple and
blocks on a future; a collector thread drains the queue up to
``batch_size`` or ``window_ms`` (whichever first) and dispatches one
``batch_check``. This is the serving-plane analog of the data-parallel axis
(SURVEY §2.3: request concurrency → batch parallelism).
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import Future
from typing import Optional, Sequence

from keto_tpu.relationtuple.model import RelationTuple


class CheckBatcher:
    def __init__(self, engine, batch_size: int = 4096, window_ms: float = 1.0):
        """``engine`` needs ``batch_check(list[RelationTuple]) -> list[bool]``."""
        self._engine = engine
        self._batch_size = batch_size
        self._window_s = window_ms / 1e3
        self._queue: queue.Queue = queue.Queue()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._thread:
            return
        self._thread = threading.Thread(target=self._loop, name="check-batcher", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._queue.put(None)  # wake the collector
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None

    # -- API -----------------------------------------------------------------

    def check(self, tuple_: RelationTuple, timeout: Optional[float] = 30.0) -> bool:
        """Blocking single check, transparently batched with concurrent
        callers."""
        fut: Future = Future()
        self._queue.put((tuple_, fut))
        return fut.result(timeout=timeout)

    def check_batch(self, tuples: Sequence[RelationTuple]) -> list[bool]:
        """Pre-batched requests skip the queue entirely."""
        return self._engine.batch_check(list(tuples))

    # -- collector -----------------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.is_set():
            item = self._queue.get()
            if item is None:
                continue
            batch = [item]
            deadline = threading.Event()
            # drain whatever arrives within the window, up to batch_size
            timer = threading.Timer(self._window_s, deadline.set)
            timer.start()
            try:
                while len(batch) < self._batch_size and not deadline.is_set():
                    try:
                        nxt = self._queue.get(timeout=self._window_s / 10)
                    except queue.Empty:
                        continue
                    if nxt is None:
                        break
                    batch.append(nxt)
            finally:
                timer.cancel()

            tuples = [t for t, _ in batch]
            try:
                results = self._engine.batch_check(tuples)
            except Exception as e:  # engine failure → every caller sees it
                for _, fut in batch:
                    if not fut.done():
                        fut.set_exception(e)
                continue
            for (_, fut), allowed in zip(batch, results):
                if not fut.done():
                    fut.set_result(allowed)
