"""Request-coalescing check batcher with priority lanes.

The reference serves one goroutine per request, each paying its own
traversal (reference internal/driver/daemon.go:62-69). On TPU the economics
invert: one device program answers thousands of checks, so concurrent
single-check requests are *coalesced* — a caller enqueues its tuple and
blocks on a future; a collector thread drains the queues up to
``batch_size`` or ``window_ms`` (whichever first) and dispatches one
``batch_check``. This is the serving-plane analog of the data-parallel axis
(SURVEY §2.3: request concurrency → batch parallelism).

PRIORITY LANES. A single FIFO convoys: one interactive check behind a
64k-wide batch request waits the whole batch's service time, which is
exactly the p50≈100 ms / p99≈2 s shape every bench round shows. The
batcher therefore keeps TWO lanes:

- ``interactive`` — single checks and small batches (≤
  ``interactive_max_tuples``): packed into the **next** dispatch round
  ahead of all queued batch work.
- ``batch`` — pre-batched chunks: dispatched in bounded **sub-slices**
  (≤ ``batch_sub_slice`` tuples per round), so a monster request
  interleaves with the interactive lane instead of owning the device
  for its full width. A small reserve (``batch_reserve_share`` of the
  round) keeps the batch lane from starving when interactive traffic
  alone can fill every round.

Lane choice: explicit (``lane=``, from the REST ``X-Keto-Priority``
header / gRPC ``x-keto-priority`` metadata) or by size. ADMISSION
CONTROL: when an ``AdmissionController`` (keto_tpu/driver/admission.py)
is attached, batch-lane arrivals beyond its AIMD window shed 429 +
``Retry-After`` at the door — overload converts to explicit backpressure
before it becomes queue delay, and interactive p99 stays flat through
bursts.

Against the TPU engine the dispatch is STREAMING: each round goes
through ``batch_check_stream_with_token(ordered=False)`` — the engine's
latency-adaptive ready-order pipeline — and each caller's future resolves
the moment its slice lands, re-associated by query offset.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from concurrent.futures import Future, InvalidStateError
from concurrent.futures import TimeoutError as FutureTimeout
from typing import TYPE_CHECKING, Optional, Sequence

from keto_tpu.relationtuple.model import RelationTuple
from keto_tpu.x import faults
from keto_tpu.x.errors import ErrDeadlineExceeded, ErrTooManyRequests, KetoError
from keto_tpu.x.timeline import current_timeline

if TYPE_CHECKING:
    from keto_tpu.driver.admission import AdmissionController

_log = logging.getLogger("keto_tpu.batch")

INTERACTIVE = "interactive"
BATCH = "batch"
LANES = (INTERACTIVE, BATCH)


class _Item:
    """One queued request: a single tuple (the common case) or a
    pre-batched chunk. Chunks are consumed in bounded sub-slices across
    dispatch rounds; the future resolves once every tuple has a result."""

    __slots__ = (
        "tuples", "fut", "at_least", "latest", "deadline", "lane",
        "results", "taken", "remaining", "tl",
    )

    def __init__(self, tuples, fut, at_least, latest, deadline, lane, tl=None):
        self.tuples = tuples
        self.fut = fut
        self.at_least = at_least
        self.latest = latest
        self.deadline = deadline
        self.lane = lane
        self.results: list = [None] * len(tuples)
        self.taken = 0  # tuples already handed to a dispatch round
        self.remaining = len(tuples)  # results not yet filled in
        #: the caller's request timeline (keto_tpu/x/timeline.py), bound
        #: by the serving layer; the batcher stamps queue/pack/dispatch/
        #: device/land stages through it. None when recording is off.
        self.tl = tl

    @property
    def n(self) -> int:
        return len(self.tuples)


class CheckBatcher:
    def __init__(
        self,
        engine,
        batch_size: int = 4096,
        window_ms: float = 1.0,
        max_pending: Optional[int] = None,
        shed_on_full: bool = False,
        interactive_max_tuples: int = 16,
        batch_sub_slice: Optional[int] = None,
        batch_reserve_share: float = 0.125,
        admission: Optional["AdmissionController"] = None,
        tenant: Optional[str] = None,
    ):
        """``engine`` needs ``batch_check(list[RelationTuple]) -> list[bool]``.

        ``max_pending`` bounds each lane's queued tuples (default
        8×batch_size): when the device can't keep up, callers block in
        ``check`` up to their own deadline instead of growing an unbounded
        backlog — backpressure propagates to the accepting sockets rather
        than to memory. With ``shed_on_full`` (what the registry
        configures for serving processes), a full lane instead *sheds
        immediately* with ``ErrTooManyRequests`` (REST 429 + Retry-After /
        gRPC RESOURCE_EXHAUSTED) — the client learns it should back off
        *now*, seconds ahead of the future timeout it would otherwise
        burn. ``admission`` (an AdmissionController) additionally sheds
        batch-lane arrivals beyond its adaptive window.

        ``tenant`` names the tenant this batcher serves (multi-tenant
        mode, keto_tpu/driver/tenants.py): every shed error then carries
        it in ``details`` so the serving layers answer with an
        ``X-Keto-Tenant`` header, and ``retry_after_s`` comes from THIS
        batcher's admission controller — one tenant's consecutive
        overloaded ticks never inflate another tenant's backoff."""
        self._engine = engine
        #: tenant identity stamped onto shed errors (None = untagged)
        self.tenant = tenant
        #: optional ``fn(tenant, lane)`` invoked on every shed — the
        #: TenantPool's shed-rate spike tracker. Called under ``_cond``;
        #: the callback must not re-enter this batcher.
        self.on_shed = None
        self._batch_size = batch_size
        self._window_s = window_ms / 1e3
        self._max_pending = max_pending or 8 * batch_size
        self._shed_on_full = shed_on_full
        self._interactive_max_tuples = max(1, interactive_max_tuples)
        self._sub_slice = max(1, batch_sub_slice or max(1, batch_size // 4))
        self._batch_reserve = max(1, int(batch_size * batch_reserve_share))
        self.admission = admission
        self._cond = threading.Condition()  # guards: _lanes, _lane_tuples, _current_round, shed_count, shed_by_lane, admission_shed_count
        self._lanes: dict[str, deque] = {lane: deque() for lane in LANES}
        self._lane_tuples: dict[str, int] = {lane: 0 for lane in LANES}
        #: items taken into the current dispatch round (failed promptly
        #: by ``stop`` so no caller ever hangs on a dead collector)
        self._current_round: list[_Item] = []
        #: requests refused at the door (lane full or admission window)
        self.shed_count = 0
        self.shed_by_lane: dict[str, int] = {lane: 0 for lane in LANES}
        #: the admission-window subset of ``shed_count``
        self.admission_shed_count = 0
        #: requests dropped at dispatch because their deadline had passed
        self.deadline_drop_count = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # in-flight accounting for graceful drain: accepted requests whose
        # futures have not resolved yet (queued OR dispatched)
        self._inflight = 0
        self._inflight_lock = threading.Lock()  # guards: _inflight
        self._idle = threading.Event()
        self._idle.set()

    def set_engine(self, engine) -> None:
        """Live-reshard handoff: point subsequent dispatch rounds at a
        new engine. The collector reads ``self._engine`` per dispatch,
        so in-flight rounds finish on the old engine (which keeps a
        valid snapshot until released) and the swap needs no quiesce."""
        self._engine = engine

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._thread:
            return
        self._thread = threading.Thread(target=self._loop, name="check-batcher", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None
        # requests still queued (or stranded in a wedged dispatch round)
        # would otherwise block their callers for the full future timeout
        # — fail them promptly with a definitive error instead
        with self._cond:
            leftovers = []
            for lane in LANES:
                leftovers.extend(self._lanes[lane])
                self._lanes[lane].clear()
                self._lane_tuples[lane] = 0
            leftovers.extend(self._current_round)
            self._cond.notify_all()
        for item in leftovers:
            if not item.fut.done():
                try:
                    item.fut.set_exception(RuntimeError("check batcher stopped"))
                except InvalidStateError:
                    pass

    # -- API -----------------------------------------------------------------

    def check(
        self,
        tuple_: RelationTuple,
        timeout: Optional[float] = 30.0,
        *,
        at_least: Optional[int] = None,
        latest: bool = False,
        deadline: Optional[float] = None,
        lane: Optional[str] = None,
    ) -> bool:
        """Blocking single check, transparently batched with concurrent
        callers. Default consistency is the serving mode (bounded
        staleness, never stalled by a rebuild); ``at_least`` pins a
        caller's snaptoken, ``latest`` forces read-your-writes."""
        return self.check_with_token(
            tuple_, timeout, at_least=at_least, latest=latest, deadline=deadline,
            lane=lane,
        )[0]

    def check_with_token(
        self,
        tuple_: RelationTuple,
        timeout: Optional[float] = 30.0,
        *,
        at_least: Optional[int] = None,
        latest: bool = False,
        deadline: Optional[float] = None,
        lane: Optional[str] = None,
    ) -> tuple[bool, Optional[int]]:
        """``check`` plus the id of the snapshot that decided it (None when
        the engine has no snapshot concept — e.g. the recursive oracle,
        which reads the store directly and is always fresh).

        ``deadline`` is the request's *absolute* ``time.monotonic()``
        deadline (REST/gRPC propagate theirs): it rides with the queued
        request so the collector sheds it *before packing* if it expires
        waiting, and the caller gets ``ErrDeadlineExceeded`` (504 /
        DEADLINE_EXCEEDED) instead of an answer nobody is waiting for.
        ``timeout`` remains the relative cap; the earlier of the two wins.
        ``lane`` pins the priority lane (single checks default to
        interactive)."""
        results, token = self._submit(
            [tuple_], timeout, at_least, latest, deadline, lane or INTERACTIVE
        )
        return bool(results[0]), token

    def check_batch(
        self,
        tuples: Sequence[RelationTuple],
        timeout: Optional[float] = None,
        *,
        at_least: Optional[int] = None,
        latest: bool = False,
        deadline: Optional[float] = None,
        lane: Optional[str] = None,
    ) -> list[bool]:
        """Pre-batched requests ride the lanes like everything else: big
        chunks land in the batch lane and dispatch in bounded sub-slices
        that interleave with interactive work."""
        return self.check_batch_with_token(
            tuples, timeout, at_least=at_least, latest=latest, deadline=deadline,
            lane=lane,
        )[0]

    def check_batch_with_token(
        self,
        tuples: Sequence[RelationTuple],
        timeout: Optional[float] = None,
        *,
        at_least: Optional[int] = None,
        latest: bool = False,
        deadline: Optional[float] = None,
        lane: Optional[str] = None,
    ) -> tuple[list[bool], Optional[int]]:
        tuples = list(tuples)
        if not tuples:
            return [], None
        if lane is None:
            lane = self.classify_lane(len(tuples), None)
        results, token = self._submit(tuples, timeout, at_least, latest, deadline, lane)
        return [bool(r) for r in results], token

    def classify_lane(self, n_tuples: int, hint: Optional[str]) -> str:
        """The lane a request belongs to: an explicit hint wins, else
        size decides (≤ ``interactive_max_tuples`` → interactive)."""
        if hint in LANES:
            return hint
        return INTERACTIVE if n_tuples <= self._interactive_max_tuples else BATCH

    def admission_precheck(self, lane: str = BATCH) -> None:
        """Cheap early shed: raise ``ErrTooManyRequests`` when the batch
        lane is already over its admitted window. Serving layers call
        this BEFORE decoding a batch payload — during a brownout the
        refusals must cost microseconds, not a 64k-tuple JSON parse, or
        the parse work itself becomes the overload."""
        if lane != BATCH or self.admission is None:
            return
        with self._cond:
            self.admission.tick(backlog=self._lane_tuples[BATCH])
            if self._lane_tuples[BATCH] >= self.admission.window:
                raise self._shed(
                    lane, True,
                    "batch lane over the admitted window (server near its "
                    "latency budget); retry after the advised backoff",
                )

    # -- enqueue -------------------------------------------------------------

    def _submit(self, tuples, timeout, at_least, latest, deadline, lane):
        if self._stop.is_set():
            raise RuntimeError("check batcher stopped")
        if lane not in LANES:
            raise ValueError(f"unknown priority lane {lane!r} (expected {LANES})")
        if timeout is not None:
            t_deadline = time.monotonic() + timeout
            deadline = t_deadline if deadline is None else min(deadline, t_deadline)
        if deadline is not None and time.monotonic() >= deadline:
            raise ErrDeadlineExceeded("deadline expired before the check was queued")
        item = _Item(
            tuples, Future(), at_least, latest, deadline, lane,
            tl=current_timeline(),
        )
        self._enqueue(item)
        remaining = None
        if deadline is not None:
            remaining = max(0.0, deadline - time.monotonic())
        try:
            return item.fut.result(timeout=remaining)
        except FutureTimeout:
            raise ErrDeadlineExceeded(
                "deadline expired waiting for the check result"
            ) from None

    def _shed(self, lane: str, admission: bool, message: str) -> ErrTooManyRequests:  # holds: _cond
        self.shed_count += 1
        self.shed_by_lane[lane] += 1
        if admission:
            self.admission_shed_count += 1
        retry_after = (
            self.admission.retry_after_s() if self.admission is not None else 1.0
        )
        cb, tenant = self.on_shed, self.tenant
        if cb is not None:
            try:
                cb(tenant or "", lane)
            except Exception:
                _log.warning("on_shed callback failed", exc_info=True)
        return ErrTooManyRequests(
            message,
            retry_after_s=retry_after,
            details={"tenant": tenant} if tenant else None,
        )

    def _enqueue(self, item: _Item) -> None:
        lane, n = item.lane, item.n
        with self._cond:
            if self._stop.is_set():
                raise RuntimeError("check batcher stopped")
            if lane == BATCH and self.admission is not None:
                self.admission.tick(backlog=self._lane_tuples[BATCH])
                if self._lane_tuples[BATCH] + n > self.admission.window:
                    if item.tl is not None:
                        item.tl.stamp("shed", lane=lane, why="admission")
                    raise self._shed(
                        lane, True,
                        "batch lane over the admitted window (server near its "
                        "latency budget); retry after the advised backoff",
                    )
            cap = self._max_pending
            if self._shed_on_full:
                # serving mode: a full lane answers 429 NOW — the device
                # is backlogged and queueing deeper only converts the
                # client's timeout budget into server memory. An
                # oversized chunk is still admitted into an EMPTY lane
                # (the sub-slice split serves it in bounded rounds).
                if self._lane_tuples[lane] + n > cap and self._lane_tuples[lane] > 0:
                    if item.tl is not None:
                        item.tl.stamp("shed", lane=lane, why="queue-full")
                    raise self._shed(
                        lane, False,
                        "check queue full (device backlogged); retry with backoff",
                    )
            else:
                # library mode: a full lane blocks the caller — the
                # backpressure seam between accepts and the device —
                # against the SAME deadline the result wait uses. A
                # deadline that expires while blocked here is a 504
                # (ErrDeadlineExceeded), NOT a queue-full error: the
                # caller ran out of time, the server did not refuse it.
                while (
                    self._lane_tuples[lane] + n > cap and self._lane_tuples[lane] > 0
                ):
                    if self._stop.is_set():
                        raise RuntimeError("check batcher stopped")
                    if item.deadline is not None:
                        remaining = item.deadline - time.monotonic()
                        if remaining <= 0:
                            raise ErrDeadlineExceeded(
                                "deadline expired while blocked on a full check queue"
                            )
                        self._cond.wait(timeout=min(remaining, 0.25))
                    else:
                        self._cond.wait(timeout=0.25)
            self._lanes[lane].append(item)
            self._lane_tuples[lane] += n
            if item.tl is not None:
                item.tl.stamp("admit", lane=lane)
            self._cond.notify_all()
        with self._inflight_lock:
            self._inflight += 1
            self._idle.clear()
        item.fut.add_done_callback(self._note_done)
        if self._stop.is_set() and not item.fut.done():
            # raced with stop()'s drain: nobody will serve the queue
            # anymore — unless the collector's final round got there first
            try:
                item.fut.set_exception(RuntimeError("check batcher stopped"))
            except InvalidStateError:
                pass  # the collector resolved it; return that result

    # -- graceful drain ------------------------------------------------------

    def _note_done(self, _fut) -> None:
        with self._inflight_lock:
            self._inflight -= 1
            if self._inflight <= 0:
                self._idle.set()

    @property
    def inflight(self) -> int:
        """Accepted check requests whose futures have not resolved yet."""
        with self._inflight_lock:
            return self._inflight

    @property
    def queue_depth(self) -> int:
        """Tuples queued across both lanes, not yet packed into a device
        batch (the /metrics pressure gauge; approximate by nature)."""
        with self._cond:
            return sum(self._lane_tuples.values())

    @property
    def lane_depths(self) -> dict[str, int]:
        """Queued tuples per lane (the /metrics per-lane gauge)."""
        with self._cond:
            return dict(self._lane_tuples)

    @property
    def max_pending(self) -> int:
        """Per-lane queue capacity — the denominator of the autoscaler's
        queue_depth_ratio signal (keto_tpu/fleet/autoscale.py)."""
        return self._max_pending

    def drain(self, timeout_s: float) -> bool:
        """Wait until every in-flight request has been answered (the
        SIGTERM drain seam: new traffic is already shed by the health
        override before this runs). True when the batcher went idle
        within ``timeout_s``."""
        return self._idle.wait(timeout=max(0.0, timeout_s))

    # -- dispatch ------------------------------------------------------------

    @staticmethod
    def _consistency_kw(at_leasts, latests) -> dict:
        """The strongest requested consistency wins (freshness is monotone
        — a fresher snapshot satisfies every weaker requirement in the
        batch)."""
        if any(latests):
            # read-your-writes dominates every floor in the batch
            return {"mode": "latest"}
        floors = [a for a in at_leasts if a is not None]
        return {"at_least": max(floors) if floors else None, "mode": "serving"}

    def _dispatch(self, tuples, at_leasts, latests):
        """One engine call for a coalesced round."""
        if hasattr(self._engine, "batch_check_with_token"):
            return self._engine.batch_check_with_token(
                tuples, **self._consistency_kw(at_leasts, latests)
            )
        # oracle engine: always fresh (reads the store per traversal
        # step), no snapshot concept
        if hasattr(self._engine, "batch_check"):
            return self._engine.batch_check(tuples), None
        return [self._engine.subject_is_allowed(t) for t in tuples], None

    def _expire(self, item: _Item) -> None:
        self.deadline_drop_count += 1
        if not item.fut.done():
            try:
                item.fut.set_exception(
                    ErrDeadlineExceeded("deadline expired before dispatch")
                )
            except InvalidStateError:
                pass

    def _fill(self, item: _Item, idx: int, allowed: bool, token) -> None:
        if item.results[idx] is None:
            item.results[idx] = allowed
            item.remaining -= 1
        if item.remaining == 0 and not item.fut.done():
            if item.tl is not None:
                item.tl.stamp("land")  # every tuple has its decision
            try:
                item.fut.set_result((item.results, token))
            except InvalidStateError:
                pass  # expired/failed concurrently; caller already has an answer

    def _emit_live(self, segments):
        """Flatten this round's segments into (item, idx) → tuple pairs,
        shedding items whose deadline has passed: they never occupy a
        device slice (an expired request in a slice would displace a live
        one), and their callers hear 504 immediately."""
        emitted: list = []
        now = time.monotonic()
        for item, start, count in segments:
            if item.fut.done():
                continue
            if item.deadline is not None and now >= item.deadline:
                self._expire(item)
                continue
            for idx in range(start, start + count):
                emitted.append((item, idx))
        return emitted

    def _dispatch_stream(self, segments, at_leasts, latests) -> None:
        """Streaming dispatch for engines with the ready-order stream API:
        each caller's future resolves the moment ITS slice lands (the
        ``ordered=False`` fast path — re-association is by query offset),
        so early-finishing slices don't wait behind stragglers, and the
        interactive tuples at the head of the round land first.

        Engines advertising ``STREAM_INFO`` additionally yield a
        per-slice info record (width / BFS steps / label-vs-BFS route /
        halo rounds+bytes / service time), which is stamped onto every
        rider's request timeline as its ``device`` stage."""
        emitted: list = []  # stream offset -> (item, idx), built at pull time

        def live_tuples():
            for item, start, count in segments:
                if item.fut.done():
                    continue
                if item.deadline is not None and time.monotonic() >= item.deadline:
                    self._expire(item)
                    continue
                if item.tl is not None:
                    item.tl.stamp("dispatch")
                for idx in range(start, start + count):
                    emitted.append((item, idx))
                    yield item.tuples[idx]

        want_info = bool(getattr(self._engine, "STREAM_INFO", False))
        kw = self._consistency_kw(at_leasts, latests)
        if want_info:
            kw["with_info"] = True
        gen, token = self._engine.batch_check_stream_with_token(
            live_tuples(), ordered=False, **kw
        )
        for rec in gen:
            if want_info:
                off, out, info = rec
                # stamp the slice's route/cost onto every distinct rider
                # BEFORE filling results, so the device stage precedes
                # land in each timeline (items are contiguous per slice —
                # dedup against the previous one suffices)
                prev = None
                for j in range(len(out)):
                    item = emitted[off + j][0]
                    if item is not prev and item.tl is not None:
                        item.tl.stamp("device", **info)
                    prev = item
            else:
                off, out = rec
            for j, allowed in enumerate(out.tolist()):
                item, idx = emitted[off + j]
                self._fill(item, idx, bool(allowed), token)

    # -- collector -----------------------------------------------------------

    def _queued(self) -> int:
        return self._lane_tuples[INTERACTIVE] + self._lane_tuples[BATCH]

    def _take_locked(self) -> list:  # holds: _cond
        """Pack one dispatch round (called under ``_cond``): interactive
        items first — every one of them rides the NEXT round — then batch
        lane work up to ``batch_sub_slice``, taking *partial* chunks so a
        monster batch request interleaves instead of convoying. A reserve
        keeps the batch lane moving when interactive traffic alone could
        fill every round. Returns ``[(item, start, count), ...]``."""
        segments = []
        n = 0
        cap = self._batch_size
        inter, batchq = self._lanes[INTERACTIVE], self._lanes[BATCH]
        reserve = self._batch_reserve if batchq else 0
        inter_cap = max(1, cap - reserve)
        while inter and n < inter_cap:
            item = inter.popleft()
            self._lane_tuples[INTERACTIVE] -= item.n
            if item.fut.done():
                continue  # expired/failed while queued
            segments.append((item, 0, item.n))
            item.taken = item.n
            if item.tl is not None:
                item.tl.stamp("pack")  # queue wait ended here
            n += item.n
        batch_cap = min(cap - n, self._sub_slice)
        # service-time-aware sub-slicing: the engine's slice controller
        # predicts how many queries fit one target-latency slice for the
        # routes currently in play — a batch sub-slice wider than that
        # would be split by the engine anyway, so bound the round here
        # and let the freed capacity interleave the NEXT interactive
        # round sooner (host-side sizing only: slice geometry on the
        # device stays the engine's decision, lockstep-safe)
        cap_fn = getattr(
            getattr(self._engine, "stream_ctrl", None), "cap", None
        )
        if cap_fn is not None:
            batch_cap = min(batch_cap, max(1, int(cap_fn())))
        while batchq and batch_cap > 0:
            head = batchq[0]
            if head.fut.done():
                batchq.popleft()
                self._lane_tuples[BATCH] -= head.n - head.taken
                continue
            take = min(batch_cap, head.n - head.taken)
            segments.append((head, head.taken, take))
            if head.tl is not None and head.taken == 0:
                head.tl.stamp("pack")  # first sub-slice: queue wait ended
            head.taken += take
            self._lane_tuples[BATCH] -= take
            batch_cap -= take
            n += take
            if head.taken == head.n:
                batchq.popleft()
        return segments

    def _loop(self) -> None:
        while not self._stop.is_set():
            with self._cond:
                if not self._queued():
                    # bounded wait so stop() always terminates the loop
                    self._cond.wait(timeout=0.25)
                    if not self._queued():
                        continue
                # coalescing window: wait for more arrivals up to
                # window_ms or a full round — each wait blocks on the
                # condition for exactly the remaining window, no polling
                window_end = time.monotonic() + self._window_s
                while self._queued() < self._batch_size and not self._stop.is_set():
                    remaining = window_end - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(timeout=remaining)
                segments = self._take_locked()
                self._current_round = [item for item, _, _ in segments]
                backlog = self._lane_tuples[BATCH]
                # space freed: wake producers blocked on a full lane
                self._cond.notify_all()
            if not segments:
                continue
            if self.admission is not None:
                self.admission.tick(backlog=backlog)
            n_tuples = sum(count for _, _, count in segments)
            t0 = time.monotonic()
            try:
                faults.check("check-dispatch")
                at_leasts = [item.at_least for item, _, _ in segments]
                latests = [item.latest for item, _, _ in segments]
                if hasattr(self._engine, "batch_check_stream_with_token"):
                    self._dispatch_stream(segments, at_leasts, latests)
                else:
                    emitted = self._emit_live(segments)
                    if emitted:
                        results, token = self._dispatch(
                            [item.tuples[idx] for item, idx in emitted],
                            at_leasts, latests,
                        )
                        for (item, idx), allowed in zip(emitted, results):
                            self._fill(item, idx, bool(allowed), token)
            except Exception as e:
                self._fail_or_retry(segments, e)
            finally:
                if self.admission is not None:
                    self.admission.observe_round(n_tuples, time.monotonic() - t0)
                with self._cond:
                    self._current_round = []

    def _fail_or_retry(self, segments, exc: Exception) -> None:
        """A failed dispatch retries its unresolved requests ONCE through
        the engine's plain batch path — a device fault mid-stream flips
        the engine into its CPU degraded mode, so the retry lands on the
        fallback and callers never see the fault. Client errors
        (KetoError) and a failed retry propagate to every waiting
        future."""
        pending = []
        for item, start, count in segments:
            if item.fut.done():
                continue
            idxs = [i for i in range(start, start + count) if item.results[i] is None]
            if idxs:
                pending.append((item, idxs))
        if pending and not isinstance(exc, KetoError):
            n = sum(len(idxs) for _, idxs in pending)
            _log.warning(
                "batch dispatch failed (%s: %s); retrying %d unresolved "
                "checks on the engine's recovery path",
                type(exc).__name__, exc, n,
            )
            try:
                results, token = self._dispatch(
                    [item.tuples[i] for item, idxs in pending for i in idxs],
                    [item.at_least for item, _ in pending],
                    [item.latest for item, _ in pending],
                )
            except Exception as e2:
                exc = e2
            else:
                k = 0
                for item, idxs in pending:
                    for i in idxs:
                        self._fill(item, i, bool(results[k]), token)
                        k += 1
                return
        for item, _, _ in segments:
            if not item.fut.done():
                try:
                    item.fut.set_exception(exc)
                except InvalidStateError:
                    pass
