"""Driver: dependency-injection registry, batching, and the serve loop.

The analog of the reference's ``internal/driver`` package: a registry of
lazily constructed singletons that every component hangs off (reference
internal/driver/registry_default.go:56-79), a factory from config, and the
daemon that serves the read and write APIs (reference
internal/driver/daemon.go:62-69).
"""

from keto_tpu.driver.registry import Registry

__all__ = ["Registry"]
