"""Serve loop: read + write APIs, each multiplexing REST and gRPC on one port.

The analog of the reference's ``ServeAll`` (reference
internal/driver/daemon.go:62-159): the read API (default :4466) serves
check/expand/list over both protocols, the write API (default :4467) serves
tuple mutations, and each public port is a sniffing mux in front of loopback
REST and gRPC backends (keto_tpu/servers/mux.py). Graceful shutdown stops
the muxes first, then drains the backends.

Rolling-restart contract: SIGTERM/SIGINT (install_signal_handlers) pins
the health state to NOT_SERVING — load balancers and readiness probes
stop routing new traffic — then waits up to ``serve.drain_timeout_s`` for
every in-flight check to resolve before tearing the stacks down, so a
rolling restart drops zero accepted requests.
"""

from __future__ import annotations

import signal
import threading
import time
from dataclasses import dataclass
from typing import TYPE_CHECKING, Any, Optional

from keto_tpu.servers.grpc_api import build_grpc_server
from keto_tpu.servers.native_mux import make_port_mux
from keto_tpu.servers.rest import READ, WRITE, RestServer

if TYPE_CHECKING:
    from keto_tpu.driver.registry import Registry


def make_rest_server(
    registry: "Registry", role: str, host: str = "127.0.0.1", port: int = 0
) -> Any:
    """REST backend per ``serve.http_backend``: the asyncio reactor
    (default — one event loop, bounded handler pool) or the stdlib
    thread-per-connection server."""
    backend = registry.config().get("serve.http_backend", "async")
    if backend == "threading":
        return RestServer(registry, role, host=host, port=port)
    from keto_tpu.servers.async_rest import AsyncRestServer

    return AsyncRestServer(registry, role, host=host, port=port)


@dataclass
class _RoleServers:
    rest: Any  # RestServer or AsyncRestServer
    grpc_server: Any
    mux: Any  # NativePortMux or PortMux

    @property
    def port(self) -> int:
        return self.mux.port


class Daemon:
    """Owns both roles' server stacks."""

    def __init__(self, registry: "Registry"):
        self.registry = registry
        self._roles: dict[str, _RoleServers] = {}
        # set by a shutdown signal (or shutdown_soon()); serve_all's
        # blocking loop waits on it and then drains
        self._stop_requested = threading.Event()
        # boot warmup worker (_warm_snapshot); shutdown joins it briefly
        self._warm_thread: Optional[threading.Thread] = None

    def _start_role(self, role: str, host: str, port: int) -> _RoleServers:
        rest = make_rest_server(self.registry, role, host="127.0.0.1", port=0)
        rest.start()
        grpc_server, grpc_port = build_grpc_server(self.registry, role)
        grpc_server.start()
        # native epoll mux when built (make native), Python fallback else
        mux = make_port_mux(host, port, rest_port=rest.port, grpc_port=grpc_port)
        mux.start()
        self.registry.logger().info(
            "serving %s API on :%d (REST+gRPC multiplexed)", role, mux.port
        )
        return _RoleServers(rest=rest, grpc_server=grpc_server, mux=mux)

    def serve_all(self, block: bool = True) -> None:
        cfg = self.registry.config()
        # prime the namespace manager before accepting traffic: a watched
        # source (file/dir/websocket URI) connects and loads at BOOT, the
        # way the reference resolves config during registry Init
        # (reference registry_default.go:240-261) — not on first request
        self.registry.namespace_manager()
        # prime the health state machine before accepting traffic so the
        # very first /health/ready or grpc.health.v1 Watch reads a live
        # state instead of constructing the monitor mid-request
        self.registry.health_monitor()
        # prime the observability companions the scrape-time bridges
        # peek at (timeline recorder, SLO engine), and attach the flight
        # recorder's anomaly triggers now that the components exist
        self.registry.timeline_recorder()
        self.registry.slo_engine()
        self.registry.wire_flight_recorder()
        rep = self.registry.replica_controller()
        if rep is not None:
            # replica mode: the controller's supervised feed bootstraps
            # the store from the primary and builds the first snapshot
            # itself (the boot warm below would only build an EMPTY
            # pre-bootstrap snapshot); reads are gated 503 until the
            # first bootstrap completes
            rep.start()
        else:
            self._warm_snapshot()
        # fleet control plane LAST: by the time this node renews/contends
        # for the lease (and could be asked to promote), the engine,
        # health machine, and replica feed it hands off around all exist
        fleet = self.registry.fleet_controller()
        if fleet is not None:
            fleet.start()
        scaler = self.registry.autoscaler()
        if scaler is not None:
            scaler.start()
        read_host, read_port = cfg.read_api_address()
        write_host, write_port = cfg.write_api_address()
        self._roles[READ] = self._start_role(READ, read_host, read_port)
        self._roles[WRITE] = self._start_role(WRITE, write_host, write_port)
        if block:
            try:
                self.wait_for_shutdown()
            except KeyboardInterrupt:
                pass
            self.drain_and_shutdown()

    def wait_for_shutdown(self, poll_s: float = 1.0) -> None:
        """Block until a shutdown signal (or ``shutdown_soon``). The wait
        is BOUNDED and looped rather than a bare ``Event.wait()``: an
        unbounded wait in the main thread delays signal-handler delivery
        on some platforms (CPython runs handlers between bytecodes, and a
        C-level lock wait can absorb the wakeup), which is exactly the
        shutdown-hang class the KTA204 lint flags — a SIGTERM must always
        terminate this wait within ``poll_s``."""
        while not self._stop_requested.wait(timeout=poll_s):
            pass

    # -- graceful shutdown ---------------------------------------------------

    def install_signal_handlers(self) -> None:
        """SIGTERM/SIGINT → drain-then-shutdown (the k8s preStop /
        rolling-restart path). Only callable from the main thread (a
        CPython constraint on signal.signal); elsewhere it is a no-op so
        embedded daemons can call it unconditionally."""
        if threading.current_thread() is not threading.main_thread():
            return
        for sig in (signal.SIGTERM, signal.SIGINT):
            signal.signal(sig, self._on_signal)

    def _on_signal(self, signum, frame) -> None:
        # the handler itself must stay tiny and async-signal-safe-ish:
        # flag the event; serve_all's blocking loop (or whoever owns the
        # daemon) performs the actual drain
        self._stop_requested.set()

    def shutdown_soon(self) -> None:
        """Programmatic equivalent of a shutdown signal."""
        self._stop_requested.set()

    def drain_and_shutdown(self) -> None:
        """Stop taking NEW traffic (health pinned NOT_SERVING so probes
        and load balancers route away), wait up to
        ``serve.drain_timeout_s`` for in-flight checks to resolve, then
        tear the stacks down. In-flight requests accepted before the
        signal complete normally — the zero-dropped-requests half of the
        rolling-restart contract."""
        if not self._roles:
            return
        self._stop_requested.set()
        drain_s = float(self.registry.config().get("serve.drain_timeout_s", 5.0))
        # flight-recorder drain bundle FIRST, while the state it freezes
        # (queues, timelines, health) still describes live serving
        fr = self.registry.flight_recorder()
        if fr is not None:
            fr.trigger("drain", "SIGTERM/SIGINT drain requested")
        try:
            from keto_tpu.driver.health import HealthState

            self.registry.health_monitor().set_override(
                HealthState.NOT_SERVING, "draining: shutdown requested"
            )
        except Exception:
            # health never blocks shutdown — but the failure is a finding,
            # not a non-event: log it and count it where maintenance
            # counters already surface (keto_maintenance_events_total)
            self._count_shutdown_failure("drain_health_override_failures")
            self.registry.logger().warning(
                "health override failed during drain; continuing shutdown",
                exc_info=True,
            )
        deadline = time.monotonic() + drain_s
        # fleet loops first: a draining node must stop renewing the lease
        # (so a successor can take it promptly), stop heartbeating
        # membership, and must not promote or spawn mid-teardown
        for key in ("autoscaler", "fleet"):
            loop = self.registry.peek(key)
            if loop is not None:
                try:
                    loop.stop()
                except Exception:
                    self._count_shutdown_failure(f"drain_{key}_stop_failures")
                    self.registry.logger().warning(
                        "%s stop failed during drain; continuing shutdown",
                        key, exc_info=True,
                    )
        # replica feed next: stop applying new commit groups before the
        # read plane drains, so in-flight reads resolve against a stable
        # watermark (the durable applied-watermark already covers every
        # applied group — a later restart resumes exactly-once)
        rep = self.registry.peek("replica")
        if rep is not None:
            try:
                rep.stop()
            except Exception:
                self._count_shutdown_failure("drain_replica_stop_failures")
                self.registry.logger().warning(
                    "replica feed stop failed during drain; continuing "
                    "shutdown", exc_info=True,
                )
        # watch streams are long-lived BY DESIGN: close the hub first so
        # every changefeed generator ends at its next poll tick and the
        # REST backends' drains below aren't held open by subscribers
        # (clients reconnect-with-resume through their SDK, exactly-once
        # per commit group)
        hub = self.registry.peek("watch_hub")
        if hub is not None:
            try:
                hub.close()
            except Exception:
                self._count_shutdown_failure("drain_watch_close_failures")
                self.registry.logger().warning(
                    "watch hub close failed during drain; continuing shutdown",
                    exc_info=True,
                )
        batcher = self.registry.peek("check_batcher")
        if batcher is not None and hasattr(batcher, "drain"):
            if not batcher.drain(drain_s):
                self.registry.logger().warning(
                    "drain timed out after %.1fs with %d checks in flight",
                    drain_s, getattr(batcher, "inflight", -1),
                )
        # group-commit coordinator: let queued writers flush durably
        # before teardown (an acked snaptoken must survive this exit;
        # unflushed writers were never acked, so a timeout loses nothing
        # a client could have observed)
        co = self.registry.peek("group_commit")
        if co is not None:
            if not co.drain(max(0.5, deadline - time.monotonic())):
                self._count_shutdown_failure("drain_group_commit_timeouts")
                self.registry.logger().warning(
                    "group-commit drain timed out with %d writers in flight",
                    getattr(co, "inflight", -1),
                )
        # the batcher resolving a future is not the response reaching the
        # wire: wait for the REST backends to flush every accepted
        # exchange before connections are torn down
        for role in self._roles.values():
            drain = getattr(role.rest, "drain", None)
            if drain is not None:
                drain(max(0.5, deadline - time.monotonic()))
        # drain the TRACER too: the otlp-http exporter batches spans on a
        # background thread, and a SIGTERM that tears the stacks down
        # while a batch is queued (or held by the worker) would drop the
        # very spans that explain the final requests. close() flushes and
        # joins the exporter — inside the drain window, before teardown.
        tracer = self.registry.peek("tracer")
        if tracer is not None:
            try:
                tracer.close()
            except Exception:
                # telemetry never blocks shutdown; log + count instead of
                # dropping the one signal that says spans were lost
                self._count_shutdown_failure("drain_tracer_close_failures")
                self.registry.logger().warning(
                    "tracer flush failed during drain; spans may be lost",
                    exc_info=True,
                )
        self.shutdown()

    def _count_shutdown_failure(self, event: str) -> None:
        """Count a swallowed shutdown-path failure into the engine's
        maintenance stats (scraped as keto_maintenance_events_total) —
        best-effort by nature: failing to count must not block shutdown
        either."""
        engine = self.registry.peek("permission_engine")
        stats = getattr(engine, "maintenance", None)
        if stats is not None:
            stats.incr(event)

    def _warm_snapshot(self) -> None:
        """Kick the first snapshot build/reload off the request path: with
        a snapshot cache configured (serve.snapshot_cache_dir) the engine
        mmap-reloads in seconds and catches up from the cached watermark
        through the delta path; without one this merely moves the first
        request's build cost to boot. Failures log and defer to the
        ordinary first-request path."""
        engine = self.registry.permission_engine()
        if not hasattr(engine, "snapshot"):
            return

        warm_widths = bool(
            self.registry.config().get("serve.compile_cache_dir", "")
        ) and hasattr(engine, "warm_compile")

        def run():
            try:
                engine.snapshot()
                gov = getattr(engine, "hbm", None)
                if gov is not None:
                    # boot-time memory picture: the budget the governor
                    # enforces and where the first snapshot landed it —
                    # an over-budget cold boot logs its ladder walk above
                    snap = gov.snapshot()
                    self.registry.logger().info(
                        "HBM governor: %d / %d bytes resident after boot "
                        "snapshot (eviction rung %d/%d)",
                        snap["resident_bytes"], snap["budget_bytes"],
                        snap["rung"], len(snap["rungs"]),
                    )
                if warm_widths:
                    # ahead-of-time compile of the full slice-width
                    # ladder (BFS + label kernels): with the persistent
                    # compilation cache configured, the first boot pays
                    # the compiles once per binary and every later boot
                    # replays them from disk before traffic arrives
                    n = engine.warm_compile()
                    self.registry.logger().info(
                        "width-ladder warmup compiled/loaded %d kernels", n
                    )
            except Exception:
                stats = getattr(engine, "maintenance", None)
                if stats is not None:
                    stats.incr("warm_failures")
                self.registry.logger().warning(
                    "boot snapshot warm failed; first request will build",
                    exc_info=True,
                )

        self._warm_thread = threading.Thread(
            target=run, name="keto-tpu-snapshot-warm", daemon=True
        )
        self._warm_thread.start()

    @property
    def read_port(self) -> int:
        return self._roles[READ].port

    @property
    def write_port(self) -> int:
        return self._roles[WRITE].port

    def shutdown(self) -> None:
        """Stop muxes, drain backends, close the registry. Idempotent —
        callers (tests, signal handlers) may race a second invocation."""
        if not self._roles:
            return
        # end watch streams first even on the non-drain path (tests,
        # double shutdown): their generators exit at the next poll tick
        # instead of leaving stream tasks pending at loop teardown
        hub = self.registry.peek("watch_hub")
        if hub is not None:
            try:
                hub.close()
            except Exception:
                self.registry.logger().debug(
                    "watch hub close raced shutdown", exc_info=True
                )
        for role in self._roles.values():
            role.mux.stop()
        for role in self._roles.values():
            role.rest.stop()
            role.grpc_server.stop(grace=2)
        self._roles.clear()
        self.registry.close()
        # the warm thread checks the engine's closing flag between
        # kernels; a bounded join here keeps interpreter teardown from
        # racing an in-flight XLA compile (observed as a segfault at
        # exit when a quick boot-shutdown cycle interrupted the
        # width-ladder warmup)
        warm = self._warm_thread
        if warm is not None and warm.is_alive():
            warm.join(timeout=30.0)
