"""Expand engine: materialize the subject-set tree.

Faithful to reference internal/expand/engine.go:30-98: depth-limited
recursion with the shared visited-set cycle guard, page loop per node,
``rest_depth <= 1`` truncates a set node to a leaf, and a SubjectID is always
a leaf. Returns ``None`` for depth ≤ 0, cycles, and empty sets — exactly the
reference's nil-tree cases.
"""

from __future__ import annotations

from typing import Optional

from keto_tpu.expand.tree import LEAF, UNION, Tree
from keto_tpu.relationtuple.manager import Manager
from keto_tpu.relationtuple.model import RelationQuery, Subject, SubjectSet
from keto_tpu.x.graph import check_and_add_visited
from keto_tpu.x.pagination import with_size, with_token


class ExpandEngine:
    def __init__(self, manager: Manager, page_size: int = 0):
        self._manager = manager
        self._page_size = page_size

    def build_tree(self, subject: Subject, rest_depth: int) -> Optional[Tree]:
        return self._build_tree(subject, rest_depth, visited=set())

    def _build_tree(self, subject: Subject, rest_depth: int, visited: set[str]) -> Optional[Tree]:
        if rest_depth <= 0:
            return None

        if not isinstance(subject, SubjectSet):
            return Tree(type=LEAF, subject=subject)

        if check_and_add_visited(visited, subject):
            return None

        sub_tree = Tree(type=UNION, subject=subject)
        next_page = ""
        while True:
            opts = [with_token(next_page)]
            if self._page_size:
                opts.append(with_size(self._page_size))
            rels, next_page = self._manager.get_relation_tuples(
                RelationQuery(
                    namespace=subject.namespace, object=subject.object, relation=subject.relation
                ),
                *opts,
            )
            if not rels:
                return None

            if rest_depth <= 1:
                sub_tree.type = LEAF
                return sub_tree

            for r in rels:
                child = self._build_tree(r.subject, rest_depth - 1, visited)
                if child is None:
                    child = Tree(type=LEAF, subject=r.subject)
                sub_tree.children.append(child)

            if next_page == "":
                return sub_tree
