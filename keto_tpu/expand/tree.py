"""Subject-set expansion trees.

Node types and codecs mirroring reference internal/expand/tree.go: the engine
only emits ``union`` and ``leaf`` today (exclusion/intersection are reserved
for userset rewrites, tree.go:15-30), JSON uses the ``subject_id`` XOR
``subject_set`` convention (tree.go:84-139), and the pretty printer renders
the same box art (tree.go:218-235).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Mapping, Optional

from keto_tpu.relationtuple.model import Subject, SubjectID, SubjectSet
from keto_tpu.x.errors import ErrBadRequest, ErrDuplicateSubject, ErrNilSubject

UNION = "union"
EXCLUSION = "exclusion"
INTERSECTION = "intersection"
LEAF = "leaf"

_VALID_TYPES = {UNION, EXCLUSION, INTERSECTION, LEAF}


@dataclass
class Tree:
    type: str
    subject: Subject
    children: list["Tree"] = field(default_factory=list)

    def to_json(self) -> dict[str, Any]:
        body: dict[str, Any] = {"type": self.type}
        if self.children:
            body["children"] = [c.to_json() for c in self.children]
        sid = self.subject.subject_id
        sset = self.subject.subject_set
        if sid is not None:
            body["subject_id"] = sid
        if sset is not None:
            body["subject_set"] = {
                "namespace": sset.namespace,
                "object": sset.object,
                "relation": sset.relation,
            }
        return body

    @classmethod
    def from_json(cls, obj: Mapping[str, Any]) -> "Tree":
        t = obj.get("type")
        if t not in _VALID_TYPES:
            raise ErrBadRequest(f"unknown node type {t!r}")
        sid = obj.get("subject_id")
        sset = obj.get("subject_set")
        if sid is None and sset is None:
            raise ErrNilSubject()
        if sid is not None and sset is not None:
            raise ErrDuplicateSubject()
        subject: Subject
        if sid is not None:
            if not isinstance(sid, str):
                raise ErrBadRequest("subject_id must be a string")
            subject = SubjectID(id=sid)
        else:
            if not isinstance(sset, Mapping):
                raise ErrBadRequest("subject_set must be an object")
            subject = SubjectSet(
                namespace=sset.get("namespace", ""),
                object=sset.get("object", ""),
                relation=sset.get("relation", ""),
            )
        raw_children = obj.get("children", [])
        if not isinstance(raw_children, list):
            raise ErrBadRequest("children must be a list")
        children = [cls.from_json(c) for c in raw_children]
        return cls(type=t, subject=subject, children=children)

    def __str__(self) -> str:
        """Pretty printer; byte-identical art to reference tree.go:218-235
        (including the trailing variation selector after the clover)."""
        sub = str(self.subject)
        if self.type == LEAF:
            return f"☘ {sub}️"
        children = ["\n│  ".join(str(c).split("\n")) for c in self.children]
        return f"∪ {sub}\n├─ " + "\n├─ ".join(children)

    def equals(self, other: Optional["Tree"]) -> bool:
        """Order-insensitive equality over children (the e2e suite compares
        trees irrespective of sibling order, reference
        internal/e2e/cases_test.go)."""
        if other is None:
            return False
        if self.type != other.type or self.subject != other.subject:
            return False
        if len(self.children) != len(other.children):
            return False
        remaining = list(other.children)
        for c in self.children:
            for i, o in enumerate(remaining):
                if c.equals(o):
                    remaining.pop(i)
                    break
            else:
                return False
        return True
