"""Snapshot-backed expand engine: bulk per-level BFS + exact host-tree
reconstruction.

The reference builds the tree with one Manager query per subject-set node
per page — the N+1 pattern (reference internal/expand/engine.go:30-98,
51-61). This engine answers from the SAME immutable device snapshot the
TPU check engine serves (keto_tpu/graph/snapshot.py), in two phases:

- **Phase A — bulk adjacency capture.** Breadth-first from the root set:
  ONE vectorized gather per level over the snapshot's forward CSR
  (``out_neighbors_bulk``) collects the ordered child list of every set
  node reachable within the depth budget. No storage round trips, no
  pages, no per-node work.
- **Phase B — reference-exact construction.** The host engine's
  depth-first recursion (pre-order visited-set pruning via
  ``check_and_add_visited``, ``rest_depth <= 1`` leaf conversion, ``None``
  for empty sets — reference engine.go:36-39, 51-71) replayed over the
  captured in-memory adjacency. Tree-child order equals the Manager's
  page order because the snapshot's per-node edge order preserves store
  row order (keto_tpu/graph/interner.py dedup note).

Why no device round trip: expand's output IS the edge list (a
materialized tree), not a reduction over it. The check kernel earns its
device dispatch by compressing millions of edge traversals into packed
decision bits; expand must ship every traversed edge to the host anyway,
so the snapshot CSR gather — the same arrays the device layout is built
from — is the bandwidth-optimal path; a device pass would move the same
bytes plus a D2H latency per level.

Known (documented) divergences from the Manager-backed host engine
(keto_tpu/expand/engine.py — kept as the differential oracle; the e2e
suite compares trees order-insensitively like the reference's):

- duplicate store rows collapse to one edge: a tuple inserted twice
  yields one child, not two (identical grant set);
- a wildcard-bearing set node's children dedup across the tuples its
  pattern matches (the same subject reached via two matching tuples
  appears once);
- a root pattern that exists as no set node (e.g. an empty-namespace
  root) concatenates the ordered child lists of the matching keys, which
  can interleave differently than global row order when wildcard-bearing
  keys also match.

While a delta overlay is pending, the fast path still serves: the
snapshot's unified overlay adjacency (``ov_fwd``,
keto_tpu/graph/overlay.py) is merged into each node's base child list
**in Manager order** — base children are already in subject-sort order
(one literal node's rows are contiguous in the store's ORDER BY), and
overlay children sort by the same subject key, so a two-way ordered
merge reproduces the Manager's page order exactly; tombstoned base
edges are masked in place. Only two overlay cases still delegate to the
Manager-backed engine: a graph containing wildcard-bearing set nodes
(their child order is GLOBAL row order, not subject order — not
reconstructible from the per-node merge) and a pattern root with no
literal node (same reason, via _pattern_children).
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from keto_tpu import namespace as namespace_pkg
from keto_tpu.expand.tree import LEAF, UNION, Tree
from keto_tpu.graph.snapshot import WILDCARD, GraphSnapshot
from keto_tpu.relationtuple.model import Subject, SubjectID, SubjectSet
from keto_tpu.x.errors import ErrNamespaceUnknown
from keto_tpu.x.graph import check_and_add_visited

#: virtual device id for a root pattern that exists as no set node
_PATTERN_ROOT = -1


class SnapshotExpandEngine:
    """Expand over the check engine's device snapshot.

    ``check_engine`` is the registry's TpuCheckEngine — snapshots (and
    their freshness semantics: read-your-writes via the store watermark)
    are shared with the check path, so an expand issued after a write sees
    the write exactly like a check does.
    """

    def __init__(self, check_engine, namespaces):
        self._engine = check_engine
        if isinstance(namespaces, namespace_pkg.Manager):
            self._nm: Callable[[], namespace_pkg.Manager] = lambda: namespaces
        else:
            self._nm = namespaces
        from keto_tpu.expand.engine import ExpandEngine

        #: exact-order engine for overlay-pending snapshots (see module doc)
        self._manager_engine = ExpandEngine(check_engine._store)

    # -- public API (host engine signature) ----------------------------------

    def build_tree(self, subject: Subject, rest_depth: int) -> Optional[Tree]:
        if rest_depth <= 0:
            return None
        if not isinstance(subject, SubjectSet):
            return Tree(type=LEAF, subject=subject)
        snap = self._engine.snapshot()
        if snap.has_overlay and snap.has_wildcards:
            # wildcard-bearing nodes order children by GLOBAL row order —
            # not reconstructible from the per-node overlay merge (module
            # doc); serve the reference's exact tree from the Manager
            return self._manager_engine.build_tree(subject, rest_depth)
        nm = self._nm()

        ns = subject.namespace
        if ns == "":
            ns_id: int = WILDCARD
        else:
            # unknown namespace raises, exactly like the host engine's
            # first Manager query (reference engine.go:51-61 propagates)
            ns_id = nm.get_namespace_by_name(ns).id

        root_dev = None
        if ns_id != WILDCARD:
            root_dev = snap.resolve_set(ns_id, subject.object, subject.relation)
        pattern = (
            ns_id == WILDCARD
            or ns_id in snap.wild_ns_ids
            or subject.object == ""
            or subject.relation == ""
        )
        children_of: dict[int, np.ndarray] = {}
        if root_dev is None:
            if not pattern:
                return None  # literal key absent → no tuples → nil tree
            if snap.has_overlay:
                # a pattern root concatenates MATCHING KEYS' lists in
                # global row order — same non-reconstructible case
                return self._manager_engine.build_tree(subject, rest_depth)
            starts = snap.resolve_starts(ns_id, subject.object, subject.relation)
            if starts.size == 0:
                return None
            children_of[_PATTERN_ROOT] = self._pattern_children(snap, starts)
            root_dev = _PATTERN_ROOT

        self._capture_adjacency(snap, root_dev, rest_depth, children_of)

        ns_names = {n.id: n.name for n in nm.namespaces()}

        def subject_of(dev: int) -> Subject:
            kind, key = snap.key_of_dev(dev)
            if kind == "leaf":
                return SubjectID(key)
            k_ns, k_obj, k_rel = key
            name = ns_names.get(k_ns)
            if name is None:
                # tuples can outlive a namespace removed by config reload;
                # the Manager-backed engine raises from its id→name
                # resolution in the same situation
                raise ErrNamespaceUnknown(f"namespace id {k_ns}")
            return SubjectSet(name, k_obj, k_rel)

        visited: set[str] = set()

        def rec(sub: Subject, dev: int, rd: int) -> Optional[Tree]:
            # mirrors keto_tpu/expand/engine.py _build_tree line for line
            if rd <= 0:
                return None
            if not isinstance(sub, SubjectSet):
                return Tree(type=LEAF, subject=sub)
            if check_and_add_visited(visited, sub):
                return None
            ch = children_of.get(dev)
            if ch is None or ch.size == 0:
                return None
            if rd <= 1:
                return Tree(type=LEAF, subject=sub)
            node = Tree(type=UNION, subject=sub)
            for c in ch.tolist():
                cs = subject_of(c)
                t = rec(cs, c, rd - 1)
                node.children.append(t if t is not None else Tree(type=LEAF, subject=cs))
            return node

        return rec(subject, root_dev, rest_depth)

    # -- phase A -------------------------------------------------------------

    def _subject_order_key(self, snap: GraphSnapshot, dev: int):
        """Manager ORDER BY position of a child: subject sets first
        (NULL-first on the subject_id column), each group sorted by its
        key fields — comparable tuples."""
        kind, key = snap.key_of_dev(dev)
        return (0, key) if kind == "set" else (1, (key,))

    def _merge_overlay_children(
        self, snap: GraphSnapshot, dev: int, base: np.ndarray
    ) -> np.ndarray:
        """Base children (already in subject-sort order — one literal
        node's rows are contiguous in the store's ORDER BY) merged with
        the node's overlay children in the SAME order: the Manager's page
        order, reproduced without a storage round trip. Overlay lists are
        tiny by design, so each overlay child bisects into the sorted
        base list (O(k log n) key computations, not O(n)); the merged
        array memoizes on the immutable snapshot."""
        import bisect as _bisect

        extra = snap.ov_fwd.get(int(dev))
        if not extra:
            return base
        cache_key = ("_exp_merge", int(dev))
        with snap._cache_lock:
            hit = snap._pattern_cache.get(cache_key)
        if hit is not None:
            return hit
        okey = lambda d: self._subject_order_key(snap, int(d))  # noqa: E731
        ov_sorted = sorted(extra, key=okey)
        positions = [
            _bisect.bisect_left(base, okey(d), key=okey) for d in ov_sorted
        ]
        out = np.insert(base.astype(np.int64), positions, ov_sorted)
        with snap._cache_lock:
            snap._pattern_cache[cache_key] = out
        return out

    def _capture_adjacency(
        self,
        snap: GraphSnapshot,
        root_dev: int,
        rest_depth: int,
        children_of: dict[int, np.ndarray],
    ) -> None:
        """Fill ``children_of`` for every set node reachable within the
        depth budget: one ``out_neighbors_bulk`` gather per BFS level
        (base edges, tombstone-masked), plus the per-node overlay merge
        when a delta is pending."""
        if root_dev == _PATTERN_ROOT:
            ch = children_of[_PATTERN_ROOT]
            m = snap.is_set_dev_bulk(ch)
            frontier = list(dict.fromkeys(ch[m].tolist()))
        else:
            frontier = [root_dev]
        seen = set(frontier)
        level = 0
        has_ov = bool(snap.ov_fwd)
        # a node at BFS level L expands with rest_depth - L; it consults
        # its children whenever that is ≥ 1
        while frontier and level <= rest_depth - 1:
            arr = np.asarray(frontier, np.int64)
            rows, cnts = snap.out_neighbors_bulk(arr, overlay=False)
            ends = np.cumsum(cnts)
            nxt: list[int] = []
            new_children: list[np.ndarray] = []
            start = 0
            for i, dev in enumerate(frontier):
                ch = rows[start : ends[i]]
                start = int(ends[i])
                if has_ov:
                    ch = self._merge_overlay_children(snap, dev, ch)
                children_of[dev] = ch
                new_children.append(ch)
            if new_children:
                flat = np.concatenate(new_children) if len(new_children) > 1 else new_children[0]
                if flat.size:
                    m = snap.is_set_dev_bulk(flat)
                    for c in flat[m].tolist():
                        if c not in seen:
                            seen.add(c)
                            nxt.append(c)
            frontier = nxt
            level += 1

    @staticmethod
    def _pattern_children(snap: GraphSnapshot, starts: np.ndarray) -> np.ndarray:
        """Ordered union of the matching keys' child lists for a root
        pattern with no node of its own: keys sort by (ns_id, object,
        relation) — the leading columns of the store's ORDER BY — then
        each key contributes its children in its own (row-order) edge
        order; duplicates keep the first occurrence. (Never called with a
        pending overlay: build_tree delegates that case to the Manager.)"""
        keyed = []
        for dev in starts.tolist():
            kind, key = snap.key_of_dev(dev)
            if kind == "set":
                keyed.append((key, dev))
        keyed.sort(key=lambda kv: kv[0])
        if not keyed:
            return np.zeros(0, np.int64)
        rows, _ = snap.out_neighbors_bulk(np.asarray([d for _, d in keyed], np.int64))
        _, first = np.unique(rows, return_index=True)
        return rows[np.sort(first)]
