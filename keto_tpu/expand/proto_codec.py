"""Tree ↔ protobuf codecs (reference internal/expand/tree.go:165-216)."""

from __future__ import annotations

from typing import Optional

from ory.keto.acl.v1alpha1 import expand_service_pb2

from keto_tpu.expand.tree import EXCLUSION, INTERSECTION, LEAF, UNION, Tree
from keto_tpu.relationtuple.proto_codec import subject_from_proto, subject_to_proto

_TYPE_TO_PROTO = {
    UNION: expand_service_pb2.NODE_TYPE_UNION,
    EXCLUSION: expand_service_pb2.NODE_TYPE_EXCLUSION,
    INTERSECTION: expand_service_pb2.NODE_TYPE_INTERSECTION,
    LEAF: expand_service_pb2.NODE_TYPE_LEAF,
}
_TYPE_FROM_PROTO = {v: k for k, v in _TYPE_TO_PROTO.items()}


def tree_to_proto(tree: Optional[Tree]) -> Optional[expand_service_pb2.SubjectTree]:
    if tree is None:
        return None
    node = expand_service_pb2.SubjectTree(
        node_type=_TYPE_TO_PROTO[tree.type], subject=subject_to_proto(tree.subject)
    )
    if tree.type != LEAF:
        node.children.extend(tree_to_proto(c) for c in tree.children)
    return node


def tree_from_proto(proto: Optional[expand_service_pb2.SubjectTree]) -> Optional[Tree]:
    if proto is None or proto.node_type == expand_service_pb2.NODE_TYPE_UNSPECIFIED:
        return None
    tree = Tree(
        type=_TYPE_FROM_PROTO[proto.node_type], subject=subject_from_proto(proto.subject)
    )
    if tree.type != LEAF:
        tree.children = [tree_from_proto(c) for c in proto.children]
    return tree
