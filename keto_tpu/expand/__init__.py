from keto_tpu.expand.engine import ExpandEngine
from keto_tpu.expand.tree import LEAF, UNION, EXCLUSION, INTERSECTION, Tree

__all__ = ["ExpandEngine", "Tree", "LEAF", "UNION", "EXCLUSION", "INTERSECTION"]
