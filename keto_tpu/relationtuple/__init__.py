from keto_tpu.relationtuple.model import (
    RelationQuery,
    RelationTuple,
    Subject,
    SubjectID,
    SubjectSet,
    subject_from_string,
)
from keto_tpu.relationtuple.manager import Manager, ManagerWrapper

__all__ = [
    "RelationQuery",
    "RelationTuple",
    "Subject",
    "SubjectID",
    "SubjectSet",
    "subject_from_string",
    "Manager",
    "ManagerWrapper",
]
