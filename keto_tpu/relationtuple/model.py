"""Relation-tuple data model.

A relation tuple ``namespace:object#relation@subject`` states that ``subject``
has ``relation`` on ``object`` within ``namespace``. The subject is a sum
type: either an opaque subject ID, or a *subject set*
``namespace:object#relation`` referencing every subject that (transitively)
has ``relation`` on ``object``.

Semantics follow the reference model exactly:
- string grammar & parsing: reference internal/relationtuple/definitions.go:138-193, 273-306
- JSON codec (``subject_id`` XOR ``subject_set``): definitions.go:316-343
- URL-query codec incl. dropped legacy ``subject`` key: definitions.go:378-414, 458-516
- query semantics (zero values mean "any"): definitions.go:44-66
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Mapping, Optional, Union
from urllib.parse import parse_qs, urlencode

from keto_tpu.x.errors import (
    ErrDroppedSubjectKey,
    ErrDuplicateSubject,
    ErrIncompleteSubject,
    ErrMalformedInput,
    ErrNilSubject,
)

_SUBJECT_ID_KEY = "subject_id"
_SSET_NS_KEY = "subject_set.namespace"
_SSET_OBJ_KEY = "subject_set.object"
_SSET_REL_KEY = "subject_set.relation"


@dataclass(frozen=True)
class SubjectID:
    """A concrete subject, e.g. a user id. Reference definitions.go:39-42."""

    id: str = ""

    def __str__(self) -> str:
        return self.id

    def to_json(self) -> dict[str, Any]:
        return {"subject_id": self.id}

    @property
    def subject_id(self) -> Optional[str]:
        return self.id

    @property
    def subject_set(self) -> Optional["SubjectSet"]:
        return None


@dataclass(frozen=True)
class SubjectSet:
    """An indirect subject: everyone with ``relation`` on ``namespace:object``.
    Reference definitions.go:103-118."""

    namespace: str = ""
    object: str = ""
    relation: str = ""

    def __str__(self) -> str:
        return f"{self.namespace}:{self.object}#{self.relation}"

    def to_json(self) -> dict[str, Any]:
        return {
            "subject_set": {
                "namespace": self.namespace,
                "object": self.object,
                "relation": self.relation,
            }
        }

    @property
    def subject_id(self) -> Optional[str]:
        return None

    @property
    def subject_set(self) -> Optional["SubjectSet"]:
        return self


Subject = Union[SubjectID, SubjectSet]


def subject_from_string(s: str) -> Subject:
    """Parse a subject: anything containing ``#`` is a subject set.
    Reference definitions.go:138-143, 172-193."""
    if "#" in s:
        parts = s.split("#")
        if len(parts) != 2:
            raise ErrMalformedInput()
        inner = parts[0].split(":")
        if len(inner) != 2:
            raise ErrMalformedInput()
        return SubjectSet(namespace=inner[0], object=inner[1], relation=parts[1])
    return SubjectID(id=s)


def subject_set_from_url_query(query: Union[str, Mapping[str, list[str]]]) -> SubjectSet:
    """Decode a subject set from bare ``namespace``/``object``/``relation``
    query keys — the expand endpoint's subject (reference
    internal/relationtuple/definitions.go:145-151)."""
    q = parse_qs(query, keep_blank_values=True) if isinstance(query, str) else query

    def get(k: str) -> str:
        v = q.get(k, [])
        return v[0] if v else ""

    return SubjectSet(namespace=get("namespace"), object=get("object"), relation=get("relation"))


def _subject_from_json(obj: Mapping[str, Any]) -> Subject:
    """Decode the ``subject_id`` XOR ``subject_set`` JSON convention.
    Reference definitions.go:316-339."""
    sid = obj.get("subject_id")
    sset = obj.get("subject_set")
    if sid is not None and sset is not None:
        raise ErrDuplicateSubject()
    if sid is None and sset is None:
        raise ErrNilSubject()
    if sid is not None:
        if not isinstance(sid, str):
            raise ErrMalformedInput("subject_id must be a string")
        return SubjectID(id=sid)
    if not isinstance(sset, Mapping):
        raise ErrMalformedInput("subject_set must be an object")
    return SubjectSet(
        namespace=str(sset.get("namespace", "")),
        object=str(sset.get("object", "")),
        relation=str(sset.get("relation", "")),
    )


@dataclass(frozen=True)
class RelationTuple:
    """An internal relation tuple. Reference definitions.go:95-100."""

    namespace: str
    object: str
    relation: str
    subject: Subject

    def __str__(self) -> str:
        return f"{self.namespace}:{self.object}#{self.relation}@{self.subject}"

    # -- string grammar ------------------------------------------------------

    @classmethod
    def from_string(cls, s: str) -> "RelationTuple":
        """Parse ``ns:obj#rel@subject`` with optional parens around the
        subject. Reference definitions.go:277-306."""
        ns, sep, rest = s.partition(":")
        if not sep:
            raise ErrMalformedInput("expected input to contain ':'")
        obj, sep, rest = rest.partition("#")
        if not sep:
            raise ErrMalformedInput("expected input to contain '#'")
        rel, sep, sub = rest.partition("@")
        if not sep:
            raise ErrMalformedInput("expected input to contain '@'")
        # optional brackets around the subject set, e.g. "@(ns:obj#rel)"
        sub = sub.strip("()")
        return cls(namespace=ns, object=obj, relation=rel, subject=subject_from_string(sub))

    # -- JSON ----------------------------------------------------------------

    def to_json(self) -> dict[str, Any]:
        body: dict[str, Any] = {
            "namespace": self.namespace,
            "object": self.object,
            "relation": self.relation,
        }
        body.update(self.subject.to_json())
        return body

    @classmethod
    def from_json(cls, obj: Mapping[str, Any]) -> "RelationTuple":
        if not isinstance(obj, Mapping):
            raise ErrMalformedInput("expected a JSON object")
        return cls(
            namespace=str(obj.get("namespace", "")),
            object=str(obj.get("object", "")),
            relation=str(obj.get("relation", "")),
            subject=_subject_from_json(obj),
        )

    # -- URL query -----------------------------------------------------------

    def to_url_query(self) -> str:
        vals = [
            ("namespace", self.namespace),
            ("object", self.object),
            ("relation", self.relation),
        ]
        if isinstance(self.subject, SubjectID):
            vals.append((_SUBJECT_ID_KEY, self.subject.id))
        else:
            vals.append((_SSET_NS_KEY, self.subject.namespace))
            vals.append((_SSET_OBJ_KEY, self.subject.object))
            vals.append((_SSET_REL_KEY, self.subject.relation))
        return urlencode(vals)

    @classmethod
    def from_url_query(cls, query: Union[str, Mapping[str, list[str]]]) -> "RelationTuple":
        """Reference definitions.go:378-395 — a tuple (unlike a query)
        requires a subject."""
        q = RelationQuery.from_url_query(query)
        sub = q.subject
        if sub is None:
            raise ErrNilSubject()
        return cls(namespace=q.namespace, object=q.object, relation=q.relation, subject=sub)

    def to_query(self) -> "RelationQuery":
        return RelationQuery(
            namespace=self.namespace,
            object=self.object,
            relation=self.relation,
            subject_id=self.subject.subject_id,
            subject_set=self.subject.subject_set,
        )

    def derive_subject(self) -> SubjectSet:
        """The subject set referring to this tuple's object+relation.
        Reference definitions.go:308-314."""
        return SubjectSet(namespace=self.namespace, object=self.object, relation=self.relation)


@dataclass
class RelationQuery:
    """A tuple query; empty namespace/object/relation mean "any", and the
    subject filter is optional (but at most one of id/set).
    Reference definitions.go:44-66."""

    namespace: str = ""
    object: str = ""
    relation: str = ""
    subject_id: Optional[str] = None
    subject_set: Optional[SubjectSet] = None

    @property
    def subject(self) -> Optional[Subject]:
        if self.subject_id is not None:
            return SubjectID(id=self.subject_id)
        if self.subject_set is not None:
            return self.subject_set
        return None

    def to_json(self) -> dict[str, Any]:
        body: dict[str, Any] = {
            "namespace": self.namespace,
            "object": self.object,
            "relation": self.relation,
        }
        if self.subject_id is not None:
            body["subject_id"] = self.subject_id
        if self.subject_set is not None:
            body["subject_set"] = {
                "namespace": self.subject_set.namespace,
                "object": self.subject_set.object,
                "relation": self.subject_set.relation,
            }
        return body

    @classmethod
    def from_url_query(cls, query: Union[str, Mapping[str, list[str]]]) -> "RelationQuery":
        """Reference definitions.go:458-493. Notable cases:
        - legacy ``subject`` key → ErrDroppedSubjectKey
        - all four subject keys present → ErrDuplicateSubject
        - partial ``subject_set.*`` without ``subject_id`` → ErrIncompleteSubject
        """
        if isinstance(query, str):
            q = parse_qs(query, keep_blank_values=True)
        else:
            q = dict(query)

        def has(k: str) -> bool:
            return k in q

        def get(k: str) -> str:
            v = q.get(k, [])
            return v[0] if v else ""

        if has("subject"):
            raise ErrDroppedSubjectKey()

        subject_id: Optional[str] = None
        subject_set: Optional[SubjectSet] = None
        has_id = has(_SUBJECT_ID_KEY)
        has_set = has(_SSET_NS_KEY) or has(_SSET_OBJ_KEY) or has(_SSET_REL_KEY)
        has_full_set = has(_SSET_NS_KEY) and has(_SSET_OBJ_KEY) and has(_SSET_REL_KEY)

        if not has_id and not has_set:
            pass  # not queried for the subject
        elif has_id and has_full_set:
            raise ErrDuplicateSubject()
        elif has_id:
            subject_id = get(_SUBJECT_ID_KEY)
        elif has_full_set:
            subject_set = SubjectSet(
                namespace=get(_SSET_NS_KEY),
                object=get(_SSET_OBJ_KEY),
                relation=get(_SSET_REL_KEY),
            )
        else:
            raise ErrIncompleteSubject()

        return cls(
            namespace=get("namespace"),
            object=get("object"),
            relation=get("relation"),
            subject_id=subject_id,
            subject_set=subject_set,
        )

    def to_url_query(self) -> str:
        vals: list[tuple[str, str]] = []
        if self.namespace:
            vals.append(("namespace", self.namespace))
        if self.relation:
            vals.append(("relation", self.relation))
        if self.object:
            vals.append(("object", self.object))
        if self.subject_id is not None:
            vals.append((_SUBJECT_ID_KEY, self.subject_id))
        elif self.subject_set is not None:
            vals.append((_SSET_NS_KEY, self.subject_set.namespace))
            vals.append((_SSET_OBJ_KEY, self.subject_set.object))
            vals.append((_SSET_REL_KEY, self.subject_set.relation))
        return urlencode(vals)
