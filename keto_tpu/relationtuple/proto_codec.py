"""Model ↔ protobuf codecs.

Mirrors the reference's proto conversions (reference
internal/relationtuple/definitions.go:206-271: ToProto/FromDataProvider,
SubjectFromProto) over the wire-compatible messages in
proto/ory/keto/acl/v1alpha1.
"""

from __future__ import annotations

from ory.keto.acl.v1alpha1 import acl_pb2

from keto_tpu.relationtuple.model import (
    RelationQuery,
    RelationTuple,
    Subject,
    SubjectID,
    SubjectSet,
)
from keto_tpu.x.errors import ErrNilSubject


def subject_to_proto(subject: Subject) -> acl_pb2.Subject:
    if isinstance(subject, SubjectID):
        return acl_pb2.Subject(id=subject.id)
    return acl_pb2.Subject(
        set=acl_pb2.SubjectSet(
            namespace=subject.namespace, object=subject.object, relation=subject.relation
        )
    )


def subject_from_proto(proto: acl_pb2.Subject) -> Subject:
    which = proto.WhichOneof("ref")
    if which == "id":
        return SubjectID(id=proto.id)
    if which == "set":
        return SubjectSet(
            namespace=proto.set.namespace, object=proto.set.object, relation=proto.set.relation
        )
    raise ErrNilSubject()


def tuple_to_proto(rt: RelationTuple) -> acl_pb2.RelationTuple:
    return acl_pb2.RelationTuple(
        namespace=rt.namespace,
        object=rt.object,
        relation=rt.relation,
        subject=subject_to_proto(rt.subject),
    )


def tuple_from_proto(proto) -> RelationTuple:
    """Accepts any message with namespace/object/relation/subject fields
    (RelationTuple, CheckRequest — the reference's TupleData interface,
    definitions.go:70-76)."""
    return RelationTuple(
        namespace=proto.namespace,
        object=proto.object,
        relation=proto.relation,
        subject=subject_from_proto(proto.subject),
    )


def query_from_proto(proto) -> RelationQuery:
    """ListRelationTuplesRequest.Query → RelationQuery (reference
    read_server.go:21-48)."""
    q = RelationQuery(namespace=proto.namespace, object=proto.object, relation=proto.relation)
    if proto.HasField("subject"):
        sub = subject_from_proto(proto.subject)
        if isinstance(sub, SubjectID):
            q.subject_id = sub.id
        else:
            q.subject_set = sub
    return q
