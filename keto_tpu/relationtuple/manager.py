"""Tuple-manager contract.

Mirrors the reference's ``relationtuple.Manager`` interface
(reference internal/relationtuple/definitions.go:28-33): paginated query,
write, delete, and an atomic insert+delete transaction. Engines depend only
on this contract, so any store (in-memory, SQLite, ...) plugs in underneath
both the oracle engines and the TPU snapshot builder.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass
from typing import Iterable, Optional, Sequence

from keto_tpu.relationtuple.model import RelationQuery, RelationTuple
from keto_tpu.x.pagination import PaginationOptionSetter, get_pagination_options


@dataclass(frozen=True)
class TransactResult:
    """Outcome of one write transaction.

    ``snaptoken`` is the watermark the transaction committed at (the
    consistency token a caller can pin subsequent checks to — the
    durability contract says an acknowledged snaptoken survives server
    death, docs/concepts/snaptokens.md). ``replayed`` is True when the
    transaction was deduplicated against an earlier application of the
    same idempotency key: nothing was re-applied and ``snaptoken`` is the
    ORIGINAL transaction's token, so a client retrying after an ambiguous
    failure (connection lost post-commit, pre-ack) observes exactly the
    response it missed."""

    snaptoken: int
    replayed: bool = False


@dataclass(frozen=True)
class TransactWrite:
    """One writer's intent inside a multi-writer group transact
    (:meth:`Manager.transact_many`): the same (insert, delete,
    idempotency_key) triple a solo ``transact_relation_tuples`` call
    takes, carried as data so a commit coordinator can batch many
    writers into one durable transaction."""

    insert: Sequence[RelationTuple] = ()
    delete: Sequence[RelationTuple] = ()
    idempotency_key: Optional[str] = None


class Manager(abc.ABC):
    @abc.abstractmethod
    def get_relation_tuples(
        self, query: RelationQuery, *options: PaginationOptionSetter
    ) -> tuple[list[RelationTuple], str]:
        """Return (tuples, next_page_token); "" token means last page."""

    @abc.abstractmethod
    def write_relation_tuples(self, *tuples: RelationTuple) -> None: ...

    @abc.abstractmethod
    def delete_relation_tuples(self, *tuples: RelationTuple) -> None: ...

    @abc.abstractmethod
    def transact_relation_tuples(
        self,
        insert: Sequence[RelationTuple],
        delete: Sequence[RelationTuple],
        idempotency_key: Optional[str] = None,
    ) -> Optional[TransactResult]:
        """Atomically apply inserts then deletes; all-or-nothing.

        With ``idempotency_key`` set, the transaction is exactly-once per
        key: the key → snaptoken binding is recorded atomically WITH the
        writes, and a retry of an already-applied key re-applies nothing
        and returns the original snaptoken with ``replayed=True`` (the
        CRDB-style answer to ambiguous-commit retries). Implementations
        return a :class:`TransactResult`; the base contract allows None
        for legacy stores without a watermark concept."""

    def transact_many(
        self, writes: Sequence[TransactWrite]
    ) -> list[Optional[TransactResult]]:
        """Apply many independent write transactions in one durable
        group: one BEGIN/COMMIT (SQL stores), one lock hold (memory), N
        per-writer outcomes in input order.

        The per-writer semantics are EXACTLY those of N serial
        ``transact_relation_tuples`` calls in the same order: each
        writer gets its own snaptoken from the group's commit sequence
        (consecutive, monotone), its own replayable idempotency-key row
        committed atomically with its rows, and replay detection against
        both prior transactions and earlier writers in the same group.
        Atomicity is all-or-nothing for the GROUP: either every writer's
        effects are durable or none are (the chaos kill points
        ``group-commit`` / ``group-ack`` bracket the shared COMMIT).

        The base implementation loops over ``transact_relation_tuples``
        — correct but per-commit-durable; stores override it with a real
        batched path (sql_base/memory)."""
        return [
            self.transact_relation_tuples(
                w.insert, w.delete, idempotency_key=w.idempotency_key
            )
            for w in writes
        ]

    def watermark(self) -> int:
        """Monotonic write counter, used by the TPU engine to detect staleness
        of its device-resident graph snapshot (the real implementation of what
        the reference stubs as "snaptoken", reference
        internal/check/handler.go:162)."""
        return 0


class ManagerWrapper(Manager):
    """Test spy recording requested page tokens, used to assert the engines'
    pagination behavior. Reference definitions.go:645-683."""

    def __init__(self, manager: Manager, *page_opts: PaginationOptionSetter):
        self.manager = manager
        self.page_opts = list(page_opts)
        self.requested_pages: list[str] = []

    def get_relation_tuples(
        self, query: RelationQuery, *options: PaginationOptionSetter
    ) -> tuple[list[RelationTuple], str]:
        opts = get_pagination_options(*options)
        self.requested_pages.append(opts.token)
        return self.manager.get_relation_tuples(query, *(self.page_opts + list(options)))

    def write_relation_tuples(self, *tuples: RelationTuple) -> None:
        self.manager.write_relation_tuples(*tuples)

    def delete_relation_tuples(self, *tuples: RelationTuple) -> None:
        self.manager.delete_relation_tuples(*tuples)

    def transact_relation_tuples(
        self,
        insert: Sequence[RelationTuple],
        delete: Sequence[RelationTuple],
        idempotency_key: Optional[str] = None,
    ) -> Optional[TransactResult]:
        return self.manager.transact_relation_tuples(
            insert, delete, idempotency_key=idempotency_key
        )

    def transact_many(
        self, writes: Sequence[TransactWrite]
    ) -> list[Optional[TransactResult]]:
        return self.manager.transact_many(writes)

    def watermark(self) -> int:
        return self.manager.watermark()
