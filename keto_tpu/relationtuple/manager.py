"""Tuple-manager contract.

Mirrors the reference's ``relationtuple.Manager`` interface
(reference internal/relationtuple/definitions.go:28-33): paginated query,
write, delete, and an atomic insert+delete transaction. Engines depend only
on this contract, so any store (in-memory, SQLite, ...) plugs in underneath
both the oracle engines and the TPU snapshot builder.
"""

from __future__ import annotations

import abc
from typing import Iterable, Optional, Sequence

from keto_tpu.relationtuple.model import RelationQuery, RelationTuple
from keto_tpu.x.pagination import PaginationOptionSetter, get_pagination_options


class Manager(abc.ABC):
    @abc.abstractmethod
    def get_relation_tuples(
        self, query: RelationQuery, *options: PaginationOptionSetter
    ) -> tuple[list[RelationTuple], str]:
        """Return (tuples, next_page_token); "" token means last page."""

    @abc.abstractmethod
    def write_relation_tuples(self, *tuples: RelationTuple) -> None: ...

    @abc.abstractmethod
    def delete_relation_tuples(self, *tuples: RelationTuple) -> None: ...

    @abc.abstractmethod
    def transact_relation_tuples(
        self, insert: Sequence[RelationTuple], delete: Sequence[RelationTuple]
    ) -> None:
        """Atomically apply inserts then deletes; all-or-nothing."""

    def watermark(self) -> int:
        """Monotonic write counter, used by the TPU engine to detect staleness
        of its device-resident graph snapshot (the real implementation of what
        the reference stubs as "snaptoken", reference
        internal/check/handler.go:162)."""
        return 0


class ManagerWrapper(Manager):
    """Test spy recording requested page tokens, used to assert the engines'
    pagination behavior. Reference definitions.go:645-683."""

    def __init__(self, manager: Manager, *page_opts: PaginationOptionSetter):
        self.manager = manager
        self.page_opts = list(page_opts)
        self.requested_pages: list[str] = []

    def get_relation_tuples(
        self, query: RelationQuery, *options: PaginationOptionSetter
    ) -> tuple[list[RelationTuple], str]:
        opts = get_pagination_options(*options)
        self.requested_pages.append(opts.token)
        return self.manager.get_relation_tuples(query, *(self.page_opts + list(options)))

    def write_relation_tuples(self, *tuples: RelationTuple) -> None:
        self.manager.write_relation_tuples(*tuples)

    def delete_relation_tuples(self, *tuples: RelationTuple) -> None:
        self.manager.delete_relation_tuples(*tuples)

    def transact_relation_tuples(
        self, insert: Sequence[RelationTuple], delete: Sequence[RelationTuple]
    ) -> None:
        self.manager.transact_relation_tuples(insert, delete)

    def watermark(self) -> int:
        return self.manager.watermark()
