"""Snaptoken-consistent read replicas riding the Watch changefeed.

A replica process holds **no SQL access**: its tuple state is a local
materialization of the primary's commit log, cold-started from
``GET /snapshot/export`` (full tuple state at a consistent watermark,
plus the primary's snapshot-cache segments when they line up) and kept
current by applying each Watch commit group — at the primary's own
snaptoken — through the engine's existing delta-overlay/compaction
path. The applied watermark is durable, so a SIGKILL'd replica resumes
with exactly-once application; reads pinned above the watermark block
briefly and then answer 412 with the current watermark (the
bounded-staleness contract); feed lag and horizon loss feed the health
state machine. See docs/concepts/replication.md.
"""

from keto_tpu.replica.checkcache import CheckCache
from keto_tpu.replica.controller import ReplicaController
from keto_tpu.replica.store import ReplicaStore, row_to_tuple

__all__ = ["CheckCache", "ReplicaController", "ReplicaStore", "row_to_tuple"]
