"""Replica controller: bootstrap, the Watch feed, and the read gate.

One object owns a replica's replication lifecycle:

- **Bootstrap** — fetch the primary's ``/snapshot/export``: the manifest
  (primary watermark + its snapshot-cache segment listing), then the
  streamed full tuple state at a consistent watermark, installed into
  the ``ReplicaStore`` at exactly that token. When the primary's cache
  watermark matches the export watermark, the cache segments are also
  fetched into the local snapshot-cache directory so the engine's cold
  start mmap-reloads instead of rebuilding (the quiet-primary fast
  path); otherwise the engine device-builds from the exported rows.
- **Feed** — a supervised worker subscribes to ``/watch`` through the
  SDK's retry-budget-gated reconnect and applies each commit group at
  its primary snaptoken through ``ReplicaStore.apply_commit`` (the
  engine then catches up through its existing delta-overlay/compaction
  path). Every applied token is persisted to the durable
  applied-watermark file BEFORE the next group is read, so a SIGKILL'd
  replica resumes from its last applied snaptoken and the store's
  watermark guard makes re-delivery exactly-once. ``ErrWatchExpired``
  (the primary GC'd the change log past the replica's cursor) triggers
  an automatic full re-bootstrap — never a crash loop, never silent
  divergence — and clears the check cache.
- **Probe** — a second supervised worker polls the primary's export
  manifest for its watermark: replication lag is "seconds since this
  replica last confirmed it was caught up", which keeps growing when the
  primary is unreachable (primary kill → DEGRADED(replication_lag) once
  past ``serve.replica_staleness_budget_s``).
- **Gate** — serving-path admission for pinned reads: ``at_least`` at or
  below the applied watermark passes; above it blocks up to
  ``serve.staleness_wait_ms`` on the apply condition variable, then
  raises 412 + Retry-After carrying the current watermark. ``latest``
  reads are refused with 412 outright — a replica cannot promise
  read-your-writes against the primary.
"""

from __future__ import annotations

import json
import logging
import os
import re
import tempfile
import threading
import time
from collections import deque
from pathlib import Path
from typing import Callable, Optional

from keto_tpu.replica.checkcache import CheckCache
from keto_tpu.replica.store import ReplicaStore
from keto_tpu.x.errors import (
    ErrPreconditionFailed,
    ErrServiceUnavailable,
    ErrWatchExpired,
)
from keto_tpu.x.supervise import SupervisedTask
from keto_tpu.x.tracing import NOOP as NOOP_TRACER
from keto_tpu.x.tracing import parse_traceparent

_log = logging.getLogger("keto_tpu.replica")

_CACHE_TAG_RE = re.compile(r"^v\d+-w\d+$")
_SEGMENT_NAME_RE = re.compile(r"^[A-Za-z0-9._-]+$")

#: durable applied-watermark file name under serve.replica_dir
WATERMARK_FILE = "applied-watermark.json"


class DurableWatermark:
    """The replica's applied snaptoken, surviving SIGKILL.

    One tiny JSON file written atomically (tmp + fsync + rename): after
    a kill the replica resumes from the last token whose application was
    recorded — re-reading a group at or below it is skipped by the
    store's watermark guard, so recovery is exactly-once. ``path=None``
    (no serve.replica_dir) keeps the watermark in memory only."""

    def __init__(self, path: Optional[Path]):
        self._path = path
        self._value: Optional[int] = None
        if path is not None:
            path.parent.mkdir(parents=True, exist_ok=True)

    def load(self) -> Optional[int]:
        if self._path is None or not self._path.exists():
            return self._value
        try:
            return int(json.loads(self._path.read_text())["watermark"])
        except Exception:
            _log.warning(
                "unreadable durable watermark %s; treating as absent",
                self._path, exc_info=True,
            )
            return None

    def store(self, token: int) -> None:
        self._value = int(token)
        if self._path is None:
            return
        payload = json.dumps({"watermark": int(token), "updated_at": time.time()})
        fd, tmp = tempfile.mkstemp(
            dir=str(self._path.parent), prefix=".wm-", suffix=".tmp"
        )
        try:
            with os.fdopen(fd, "w") as f:
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise


class ReplicaController:
    def __init__(
        self,
        store: ReplicaStore,
        engine_source: Callable[[], object],
        primary_url: str,
        *,
        replica_dir: str = "",
        snapshot_cache_dir: str = "",
        staleness_wait_ms: float = 200.0,
        staleness_budget_s: float = 30.0,
        probe_s: float = 1.0,
        checkcache_entries: int = 65536,
        client_factory: Optional[Callable[[], object]] = None,
        stats=None,
        tracer=None,
        apply_delay_histogram=None,
    ):
        if not primary_url:
            raise ValueError("serve.role=replica requires serve.primary_url")
        self._store = store
        self._engine_source = engine_source
        self.primary_url = primary_url.rstrip("/")
        self._cache_dir = snapshot_cache_dir
        self.staleness_wait_s = max(0.0, float(staleness_wait_ms)) / 1e3
        self.staleness_budget_s = float(staleness_budget_s)
        self._probe_s = max(0.05, float(probe_s))
        self._client_factory = client_factory or self._default_client
        self._stats = stats
        # serve.checkcache_entries=0 disables the cache outright
        self.checkcache: Optional[CheckCache] = (
            CheckCache(entries=checkcache_entries)
            if int(checkcache_entries) > 0
            else None
        )
        self.durable = DurableWatermark(
            Path(replica_dir) / WATERMARK_FILE if replica_dir else None
        )
        self._lock = threading.Lock()  # guards: _primary_wm, _caught_up_at, _last_contact
        self._applied = threading.Condition()  # notified per applied commit
        self._stop = threading.Event()
        self._bootstrapped = threading.Event()
        self._primary_wm = 0
        self._caught_up_at: Optional[float] = None
        self._last_contact: Optional[float] = None
        #: feed-apply failures on groups that had to be skipped (namespace
        #: config drift between primary and replica — a deployment bug)
        self.apply_failures = 0
        #: primary watermark regressions observed across re-bootstraps
        self.watermark_regressions = 0
        # REPLICATION-AWARE TRACING: each applied commit group's apply
        # runs under a span joined to the WRITER's traceparent (carried
        # on the watch message), so one trace spans primary transact →
        # watch emit → replica apply → 412-gate visibility; the
        # commit→apply delay feeds keto_replication_apply_delay_seconds
        # with the writer's trace id as the exemplar.
        self._tracer = tracer or NOOP_TRACER
        self._delay_hist = apply_delay_histogram
        #: per-commit replication timelines, newest last — the replica
        #: half of GET /debug/requests (clock-skew caveat: committed_at/
        #: emitted_at are the PRIMARY's wall clock)
        self._replication_log: deque[dict] = deque(maxlen=256)
        self._feed = SupervisedTask("replica-feed", self._feed_pass, stats=stats)
        self._probe = SupervisedTask("replica-probe", self._probe_pass, stats=stats)

    def _default_client(self):
        from keto_tpu.httpclient import KetoClient

        # a short transport timeout bounds how long stop() waits for the
        # feed's blocking readline; idle-stream timeouts reconnect free
        return KetoClient(self.primary_url, self.primary_url, timeout=5.0)

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> None:
        self._feed.kick()
        self._probe.kick()

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        with self._applied:
            self._applied.notify_all()
        self._feed.stop(timeout=timeout)
        self._probe.stop(timeout=timeout)

    # -- read-side surface -----------------------------------------------------

    @property
    def bootstrapped(self) -> bool:
        return self._bootstrapped.is_set()

    @property
    def watermark(self) -> int:
        return self._store.watermark()

    @property
    def applied_commits(self) -> int:
        return self._store.applied_commits

    @property
    def bootstraps(self) -> int:
        return self._store.bootstraps

    @property
    def primary_connected(self) -> bool:
        with self._lock:
            last = self._last_contact
        return last is not None and (time.monotonic() - last) < 3 * self._probe_s + 2.0

    def lag_s(self) -> float:
        """Seconds since this replica last CONFIRMED being caught up with
        the primary (applied watermark >= the primary's, observed over a
        live connection). Grows while the feed lags — and while the
        primary is unreachable, which is indistinguishable from lagging
        and handled the same way (DEGRADED past the budget)."""
        if not self.bootstrapped:
            return 0.0  # STARTING covers the pre-bootstrap phase
        with self._lock:
            caught = self._caught_up_at
        if caught is None:
            return 0.0
        return max(0.0, time.monotonic() - caught)

    def gate_read(self, at_least: Optional[int], latest: bool = False) -> None:
        """Serving-path admission (check/expand/list/relation-tuples on a
        replica). Raises 503 before the first bootstrap (an empty replica
        must never answer "deny" for everything), 412 for ``latest``
        reads and for pins the feed did not reach within
        ``serve.staleness_wait_ms``."""
        if not self.bootstrapped:
            raise ErrServiceUnavailable(
                "replica has not completed its first bootstrap from the "
                "primary; retry shortly or read from the primary",
                retry_after_s=1.0,
            )
        if latest:
            raise ErrPreconditionFailed(
                "latest=true requires the primary: a replica serves bounded "
                "staleness (any snaptoken <= its applied watermark), not "
                "read-your-writes",
                details={"watermark": str(self.watermark)},
                retry_after_s=1.0,
            )
        if at_least is None:
            return
        at_least = int(at_least)
        if at_least <= self.watermark:
            return
        deadline = time.monotonic() + self.staleness_wait_s
        with self._applied:
            while at_least > self.watermark:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._stop.is_set():
                    break
                self._applied.wait(timeout=remaining)
        wm = self.watermark
        if at_least <= wm:
            return
        raise ErrPreconditionFailed(
            f"requested snaptoken {at_least} is ahead of this replica's "
            f"applied watermark {wm}; retry, or read from the primary",
            details={"watermark": str(wm)},
            retry_after_s=1.0,
        )

    def snapshot(self) -> dict:
        """Operator/metrics view."""
        return {
            "role": "replica",
            "primary_url": self.primary_url,
            "bootstrapped": self.bootstrapped,
            "watermark": self.watermark,
            "primary_watermark": self._primary_wm,
            "lag_s": self.lag_s(),
            "primary_connected": self.primary_connected,
            "applied_commits": self.applied_commits,
            "skipped_commits": self._store.skipped_commits,
            "bootstraps": self.bootstraps,
            "apply_failures": self.apply_failures,
            "checkcache": (
                self.checkcache.snapshot() if self.checkcache is not None else {}
            ),
        }

    # -- replication internals -------------------------------------------------

    def _incr(self, event: str) -> None:
        if self._stats is not None:
            self._stats.incr(event)

    def _note_contact(self, primary_wm: Optional[int] = None) -> None:
        now = time.monotonic()
        with self._lock:
            self._last_contact = now
            if primary_wm is not None:
                self._primary_wm = max(self._primary_wm, int(primary_wm))
            if self._store.watermark() >= self._primary_wm:
                self._caught_up_at = now

    def _probe_pass(self) -> None:
        """One probe-loop lifetime: poll the primary's export manifest
        for its watermark until stop. Failures raise into the supervised
        backoff (the feed keeps running independently)."""
        client = self._client_factory()
        while not self._stop.is_set():
            manifest = client.snapshot_export_manifest()
            self._note_contact(int(manifest.get("watermark", 0)))
            if self._stop.wait(timeout=self._probe_s):
                return

    def _feed_pass(self) -> None:
        """One feed-loop lifetime: bootstrap if needed, then tail the
        changefeed, applying commit groups exactly-once. A clean watch
        end (SDK retry budget spent, primary drain) loops into a fresh
        budget-gated subscribe; exceptions raise into the supervised
        jittered-backoff retry."""
        client = self._client_factory()
        reconnect_wait = 0.2
        while not self._stop.is_set():
            if not self.bootstrapped:
                self._bootstrap()
            try:
                for token, changes in client.watch(snaptoken=self.watermark):
                    reconnect_wait = 0.2
                    self._apply_group(
                        int(token), changes,
                        meta=getattr(client, "last_commit_meta", None),
                    )
                    if self._stop.is_set():
                        return
            except ErrWatchExpired:
                # the primary GC'd its change log past our cursor: the
                # ONLY correct recovery is a full re-bootstrap — resuming
                # anywhere else silently diverges, crashing loops forever
                _log.warning(
                    "watch horizon lost at watermark %d; re-bootstrapping "
                    "from the primary", self.watermark,
                )
                self._incr("replica_horizon_losses")
                self._bootstrapped.clear()
                continue
            if self._stop.is_set():
                return
            # watch generator ended without error (SDK retry budget
            # drained or the primary closed the stream): pause — growing
            # while the primary stays silent, so a dead primary is not
            # stormed past what the budget already allowed — then
            # resubscribe from the durable cursor
            if self._stop.wait(timeout=reconnect_wait):
                return
            reconnect_wait = min(2.0, reconnect_wait * 2)

    def _apply_group(self, token: int, changes, meta: Optional[dict] = None) -> None:
        insert = [rt for action, rt in changes if action == "insert"]
        delete = [rt for action, rt in changes if action != "insert"]
        meta = meta or {}
        remote = parse_traceparent(str(meta.get("traceparent", "") or ""))
        t_recv = time.time()
        # the apply span joins the WRITER's trace (carried on the watch
        # message) and closes only after the watermark is raised and the
        # 412 gate notified — its end IS the visibility point
        with self._tracer.span(
            "replica.apply", remote_parent=remote, snaptoken=token,
            changes=len(changes),
        ) as span:
            try:
                applied = self._store.apply_commit(token, insert, delete)
            except Exception:
                # namespace-config drift between primary and replica is the
                # only way a replayed commit can fail to apply; skipping the
                # group (loudly) keeps the feed alive — retrying it forever
                # would freeze the watermark and take the whole replica down
                self.apply_failures += 1
                self._incr("replica_apply_failures")
                _log.error(
                    "failed to apply watch commit group at snaptoken %d; "
                    "skipping it (namespace config drift?)", token, exc_info=True,
                )
                return
            if span is not None:
                span.tags["applied"] = applied
            if applied:
                self.durable.store(token)
                if self.checkcache is not None:
                    self.checkcache.note_commit(token)
                with self._applied:
                    self._applied.notify_all()
                # ride the engine's existing delta-overlay/compaction path
                # eagerly so pinned reads above the old snapshot land fast
                try:
                    self._engine().snapshot_serving()
                except Exception:
                    _log.debug("post-apply engine refresh failed", exc_info=True)
        if applied:
            self._note_replication(token, len(changes), meta, remote, t_recv)
        self._note_contact(token)

    def _note_replication(
        self, token: int, n_changes: int, meta: dict, remote, t_recv: float
    ) -> None:
        """Record one commit's replication timeline and feed the
        commit→visible delay histogram (trace-id exemplar = the writer's
        trace). ``committed_at``/``emitted_at`` come from the primary's
        clock — delays are cross-clock and clamped at zero."""
        now = time.time()
        committed = meta.get("committed_at")
        delay = None
        if committed is not None:
            try:
                delay = max(0.0, now - float(committed))
            except (TypeError, ValueError):
                delay = None
        entry = {
            "snaptoken": token,
            "changes": n_changes,
            "trace_id": remote[0] if remote else "",
            "committed_at": committed,
            "emitted_at": meta.get("emitted_at"),
            "received_at": round(t_recv, 6),
            "visible_at": round(now, 6),
            "commit_to_visible_s": round(delay, 6) if delay is not None else None,
        }
        self._replication_log.append(entry)
        if self._delay_hist is not None and delay is not None:
            self._delay_hist.observe(
                (), delay, trace_id=remote[0] if remote else ""
            )

    def replication_timelines(self) -> list[dict]:
        """Per-commit replication timelines, newest first (the replica
        section of GET /debug/requests)."""
        return list(reversed(self._replication_log))

    def _engine(self):
        return self._engine_source()

    def _bootstrap(self) -> None:
        """Full-state install from the primary (cold start and every
        horizon-loss recovery)."""
        client = self._client_factory()
        manifest = client.snapshot_export_manifest()
        self._note_contact(int(manifest.get("watermark", 0)))
        watermark, tuples = client.fetch_snapshot_export()
        prior = self.durable.load()
        if prior is not None and watermark < prior:
            # the primary answered with LESS history than we already
            # durably applied (restored from backup?) — re-bootstrapping
            # forward from what it has is the only consistent option,
            # but it must never pass silently
            self.watermark_regressions += 1
            self._incr("replica_watermark_regressions")
            _log.error(
                "primary export watermark %d is behind this replica's "
                "durable applied watermark %d; re-bootstrapping onto the "
                "primary's (shorter) history", watermark, prior,
            )
        cache = manifest.get("cache")
        if cache and self._cache_dir and int(cache.get("watermark", -1)) == watermark:
            try:
                self._fetch_cache_segments(client, cache)
            except Exception:
                # strictly a fast-path: the engine builds from rows
                _log.warning(
                    "snapshot-cache segment fetch failed; cold start will "
                    "device-build from the exported rows", exc_info=True,
                )
        self._store.bootstrap(tuples, watermark)
        self.durable.store(watermark)
        if self.checkcache is not None:
            self.checkcache.clear(watermark)
        self._bootstrapped.set()
        self._incr("replica_bootstraps")
        self._note_contact(watermark)
        with self._applied:
            self._applied.notify_all()
        _log.info(
            "replica bootstrapped: %d tuples at snaptoken %d (bootstrap #%d)",
            len(tuples), watermark, self.bootstraps,
        )
        # build/reload the device snapshot off the serving path NOW so
        # the first read doesn't pay it; the segment fast path above
        # makes this an mmap reload when the watermarks lined up
        try:
            self._engine().snapshot()
        except Exception:
            _log.warning(
                "post-bootstrap snapshot build failed; first read will "
                "build inline", exc_info=True,
            )

    def _fetch_cache_segments(self, client, cache: dict) -> None:
        """Mirror the primary's newest snapshot-cache directory into the
        local cache dir (atomic: temp dir + rename) so the engine's
        ordinary cold-start reload finds it. Tag/segment names are
        validated against the manifest grammar — the server enforces the
        same on its side."""
        tag = str(cache.get("tag", ""))
        if not _CACHE_TAG_RE.match(tag):
            raise ValueError(f"malformed cache tag {tag!r}")
        base = Path(self._cache_dir)
        if (base / tag).exists():
            return  # already mirrored (a prior bootstrap or shared volume)
        base.mkdir(parents=True, exist_ok=True)
        tmp = Path(
            tempfile.mkdtemp(dir=str(base), prefix=f".fetch-{tag}-")
        )
        try:
            for seg in cache.get("segments", ()):
                name = str(seg["name"])
                if not _SEGMENT_NAME_RE.match(name):
                    raise ValueError(f"malformed segment name {name!r}")
                data = client.fetch_snapshot_segment(tag, name)
                (tmp / name).write_bytes(data)
            os.replace(tmp, base / tag)
        except BaseException:
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)
            raise
        _log.info(
            "mirrored primary snapshot cache %s (%d segments)",
            tag, len(cache.get("segments", ())),
        )


__all__ = ["ReplicaController", "DurableWatermark", "WATERMARK_FILE"]
