"""Watch-invalidated check cache: decisions keyed by (tuple, snaptoken window).

Every cached entry — positive AND negative decisions — records the
snaptoken it was computed at (``from_token``) and stays **open** until
the first commit group applied after it closes the window
(``closed_at``). Because reachability is transitive — one inserted edge
anywhere can flip a decision whose query tuple it never mentions, across
namespaces via subject-set edges — invalidation is deliberately
**global**: any applied delta closes every open window. That is the only
namespace-config-oblivious policy that can never serve a hit an applied
delta invalidated (the acceptance bar, fuzz-tested); the cost is that a
write burst empties the cache, which is exactly what a bounded-staleness
read tier wants.

Window semantics (sound by construction):

- an **open** entry represents the live state: it serves any request
  whose ``at_least`` the replica gate already admitted (``<= watermark``);
- a **closed** entry represents states ``[from_token, closed_at - 1]``:
  it serves only explicit snaptoken reads with ``at_least < closed_at``
  ("bypassed for snaptokens above the entry's window"); tokenless reads
  mean "current" and never accept a closed entry;
- an insert racing a concurrent invalidation (decision computed at
  ``token``, commit applied before the insert ran) is **dropped** — both
  paths take one lock, so the stale insert observes ``last_close >
  token`` and never becomes an open entry.

Bounded + LRU: at most ``entries`` decisions; lookups refresh recency.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional


class _Entry:
    __slots__ = ("allowed", "from_token", "closed_at")

    def __init__(self, allowed: bool, from_token: int):
        self.allowed = allowed
        self.from_token = from_token
        self.closed_at: Optional[int] = None


class CheckCache:
    def __init__(self, entries: int = 65536):
        self.capacity = max(1, int(entries))
        self._mu = threading.Lock()  # guards: _map, _open, _last_close, counters
        self._map: OrderedDict[str, _Entry] = OrderedDict()
        # keys of currently-open entries: closing on an applied commit is
        # O(open), and each entry closes at most once — amortized O(1)
        self._open: set[str] = set()
        self._last_close = 0
        #: /metrics bridges (keto_checkcache_* families)
        self.hits = 0
        self.misses = 0
        self.invalidations = 0

    def __len__(self) -> int:
        with self._mu:
            return len(self._map)

    def get(self, key: str, at_least: Optional[int]) -> Optional[tuple[bool, int]]:
        """Cached ``(allowed, decision_token)`` valid for ``at_least``
        (already gate-admitted: ``at_least <= replica watermark``), or
        None. Tokenless reads (``at_least=None``) mean "current" and only
        open windows qualify."""
        with self._mu:
            e = self._map.get(key)
            if e is None:
                self.misses += 1
                return None
            if e.closed_at is None:
                self._map.move_to_end(key)
                self.hits += 1
                return e.allowed, e.from_token
            if at_least is not None and at_least < e.closed_at:
                self._map.move_to_end(key)
                self.hits += 1
                return e.allowed, max(e.from_token, at_least)
            self.misses += 1
            return None

    def put(self, key: str, allowed: bool, token: Optional[int]) -> bool:
        """Record a decision computed at snaptoken ``token``. Dropped
        (returns False) when a delta already applied past the decision's
        state — caching it open would be the exact stale-hit bug the
        fuzz suite hunts."""
        if token is None:
            return False
        token = int(token)
        with self._mu:
            if token < self._last_close:
                return False
            old = self._map.pop(key, None)
            if old is not None:
                self._open.discard(key)
            e = _Entry(bool(allowed), token)
            self._map[key] = e
            # |= rather than .add(): the lock-order analyzer's
            # unique-name call resolution would read ``.add`` under this
            # lock as HbmGovernor.add and report a phantom lock cycle
            self._open |= {key}
            while len(self._map) > self.capacity:
                k, _ = self._map.popitem(last=False)
                self._open.discard(k)
            return True

    def note_commit(self, token: int) -> int:
        """An applied delta at snaptoken ``token``: close every open
        window (global invalidation — see the module docstring for why
        anything finer is unsound without rewrite-config analysis).
        Returns how many entries were invalidated."""
        token = int(token)
        with self._mu:
            self._last_close = max(self._last_close, token)
            n = len(self._open)
            for k in self._open:
                e = self._map.get(k)
                if e is not None:
                    e.closed_at = token
            self._open.clear()
            self.invalidations += n
            return n

    def clear(self, token: int) -> None:
        """Full reset at ``token`` (a re-bootstrap replaced the state
        discontinuously: even closed windows may describe a history this
        replica no longer vouches for)."""
        with self._mu:
            self._last_close = max(self._last_close, int(token))
            self.invalidations += len(self._map)
            self._map.clear()
            self._open.clear()

    def snapshot(self) -> dict:
        """Scrape-time view for the /metrics bridges."""
        with self._mu:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "invalidations": self.invalidations,
                "entries": len(self._map),
                "open_entries": len(self._open),
            }


__all__ = ["CheckCache"]
