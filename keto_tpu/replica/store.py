"""Replica tuple store: the primary's commit log, materialized locally.

A ``ReplicaStore`` is a ``MemoryPersister`` whose watermark is not its
own counter but the **primary's snaptokens**: every applied Watch commit
group lands at exactly the token it committed at on the primary, so
``check(snaptoken=)`` pins, page tokens, watch resumes, and the snapshot
cache keying all mean the same thing on a replica as on the primary.

Three contracts distinguish it from the ordinary in-memory store:

- **Read-only to the public write path.** ``transact_relation_tuples``
  raises ``ErrReplicaReadOnly`` — replicas hold no authority over the
  tuple log; mutations arrive only through ``apply_commit``.
- **Exactly-once application, guarded by the watermark.** A commit group
  with ``token <= watermark`` is skipped (counted, never re-applied), so
  a Watch reconnect that replays groups — or a feed restart resuming
  from a durable watermark older than the live state — is idempotent by
  construction.
- **Bootstrap replaces, never merges.** ``bootstrap`` installs a full
  tuple state at an exact watermark and raises every delta/watch horizon
  to it: a delta or watch read spanning a (re-)bootstrap can never be
  served (the history was not observed locally), so downstream snapshot
  maintenance rebuilds instead of silently diverging.
"""

from __future__ import annotations

from typing import Optional, Sequence

from keto_tpu.persistence.memory import InternalRow, MemoryPersister
from keto_tpu.relationtuple.manager import TransactResult
from keto_tpu.relationtuple.model import RelationTuple, SubjectID, SubjectSet
from keto_tpu.x.errors import ErrReplicaReadOnly


def row_to_tuple(nm, row: InternalRow) -> RelationTuple:
    """``InternalRow`` → ``RelationTuple`` through namespace manager
    ``nm`` — the export stream's row codec (both persisters' snapshot
    rows share the InternalRow shape)."""
    ns = nm.get_namespace_by_config_id(row.namespace_id)
    if row.subject_id is not None:
        subject: object = SubjectID(id=row.subject_id)
    else:
        sns = nm.get_namespace_by_config_id(row.sset_namespace_id)
        subject = SubjectSet(
            namespace=sns.name, object=row.sset_object, relation=row.sset_relation
        )
    return RelationTuple(
        namespace=ns.name, object=row.object, relation=row.relation, subject=subject
    )


class ReplicaStore(MemoryPersister):
    """Watch-fed, watermark-guarded view of the primary's tuple state."""

    def __init__(self, namespace_manager_source, network_id: str = "default"):
        super().__init__(namespace_manager_source, network_id)
        #: commit groups applied at their primary snaptoken
        self.applied_commits = 0
        #: commit groups skipped by the exactly-once watermark guard
        self.skipped_commits = 0
        #: full-state installs (cold start + every 410-triggered redo)
        self.bootstraps = 0

    # -- the public write path is closed --------------------------------------

    def transact_relation_tuples(
        self,
        insert: Sequence[RelationTuple],
        delete: Sequence[RelationTuple],
        idempotency_key: Optional[str] = None,
    ) -> TransactResult:
        raise ErrReplicaReadOnly()

    # -- replication-internal mutation ----------------------------------------

    def _apply_at(
        self,
        insert: Sequence[RelationTuple],
        delete: Sequence[RelationTuple],
        token: int,
    ) -> None:
        """Run one transaction through the parent's transact machinery,
        pinned to land at exactly ``token``: the parent bumps the shared
        watermark by one, so setting it to ``token - 1`` first makes the
        commit (and its insert/delete log entries) carry the primary's
        snaptoken. Caller holds the shared lock and has verified
        ``token > watermark``."""
        self._shared.watermark = int(token) - 1
        MemoryPersister.transact_relation_tuples(self, insert, delete)
        # deletes that matched nothing (the documented watch-replay
        # elision pairs them with elided inserts) must still land the
        # group's token: the parent bump always reaches token, but assert
        # the invariant rather than assume it
        assert self._shared.watermark == int(token)

    def apply_commit(
        self,
        token: int,
        insert: Sequence[RelationTuple],
        delete: Sequence[RelationTuple],
    ) -> bool:
        """Apply one Watch commit group at its primary snaptoken.
        Returns True when applied, False when the watermark guard skipped
        it (already applied — exactly-once across reconnect replays)."""
        token = int(token)
        with self._shared.lock:
            if token <= self._shared.watermark:
                self.skipped_commits += 1
                return False
            self._apply_at(insert, delete, token)
            self.applied_commits += 1
            return True

    def bootstrap(self, tuples: Sequence[RelationTuple], watermark: int) -> None:
        """Install a full tuple state at exactly ``watermark`` (the
        primary's export watermark), replacing whatever was held before.
        Every log floor rises to the watermark: deltas and watch resumes
        from before the bootstrap cannot be served from a history this
        process never observed."""
        watermark = int(watermark)
        nid = self.network_id
        with self._shared.lock:
            self._shared.rows[nid] = []
            self._shared.lhs_index = None
            self._shared.col_cache.pop(nid, None)
            self._shared.insert_log[nid] = []
            self._shared.delete_log[nid] = []
            self._shared.commit_times[nid] = []
            if tuples:
                self._apply_at(tuples, (), watermark)
            else:
                self._shared.watermark = watermark
            # the bootstrap is a state discontinuity, not an observed
            # history: raise every horizon so rows_since/changes_since/
            # watch below the watermark answer "rebuild"/"expired",
            # never a partial delta
            self._shared.insert_log[nid] = []
            self._shared.delete_log[nid] = []
            self._shared.log_floor[nid] = watermark
            self._shared.del_floor[nid] = watermark
            self._shared.delete_wm[nid] = watermark
            self.bootstraps += 1


__all__ = ["ReplicaStore", "row_to_tuple"]
