"""gRPC services over the wire-compatible ory.keto.acl.v1alpha1 contract.

The reference registers CheckService + ExpandService + ReadService on the
read server and WriteService on the write server, plus VersionService and
grpc.health.v1.Health on both (reference
internal/driver/registry_default.go:350-382). Service/method registration
here is hand-written over protoc-generated messages (the runtime image has
no grpc codegen plugin): each servicer installs a
``grpc.method_handlers_generic_handler`` keyed by the same full service
names, so generated clients from the reference ecosystem interoperate.

Errors map through the KetoError.grpc_code taxonomy; the check RPC returns a
real snaptoken — the watermark of the device graph snapshot that produced
the decision (the reference stubs this field, reference
internal/check/handler.go:162).
"""

from __future__ import annotations

import json
import logging
import math
import threading
import time
import uuid
from concurrent import futures
from typing import Optional

import grpc
from grpchealth.v1 import health_pb2
from ory.keto.acl.v1alpha1 import (
    check_service_pb2,
    expand_service_pb2,
    read_service_pb2,
    version_pb2,
    write_service_pb2,
)

from keto_tpu.expand.proto_codec import tree_to_proto
from keto_tpu.relationtuple.proto_codec import (
    query_from_proto,
    subject_from_proto,
    tuple_from_proto,
)
from keto_tpu.x.errors import ErrBadRequest, KetoError
from keto_tpu.x.logging import request_context
from keto_tpu.x.pagination import with_size, with_token
from keto_tpu.x.timeline import current_timeline
from keto_tpu.x.tracing import parse_traceparent

READ = "read"
WRITE = "write"

_log = logging.getLogger("keto_tpu.grpc")

_CODE_BY_NUM = {c.value[0]: c for c in grpc.StatusCode}


def _abort(context, err: KetoError):
    # overload errors (RESOURCE_EXHAUSTED / UNAVAILABLE) carry the
    # server's backoff advice as trailing metadata — the gRPC face of
    # the REST Retry-After header; tenant-scoped sheds additionally name
    # the tenant (the REST X-Keto-Tenant response header)
    trailing = []
    retry_after = getattr(err, "retry_after_s", None)
    if retry_after:
        trailing.append(("retry-after", str(max(1, math.ceil(retry_after)))))
    tn = (getattr(err, "details", None) or {}).get("tenant")
    if tn:
        trailing.append(("x-keto-tenant", str(tn)))
    if trailing:
        try:
            context.set_trailing_metadata(tuple(trailing))
        except Exception:
            # stream torn down; the status still reaches the client
            _log.debug("trailing metadata raced stream teardown", exc_info=True)
    context.abort(_CODE_BY_NUM.get(err.grpc_code, grpc.StatusCode.INTERNAL), err.message)


def _scope_from(registry, context):
    """The registry-shaped scope serving this call: the registry itself
    for the default tenant (absent/blank ``x-keto-tenant`` metadata —
    every pre-tenancy contract intact), or the tenant's pool context
    otherwise. The gRPC face of the REST ``X-Keto-Tenant`` header, with
    identical gating (``serve.tenant_enabled``, primary-only)."""
    from keto_tpu.driver.tenants import DEFAULT_TENANT, validate_tenant_id

    raw = ""
    for k, v in context.invocation_metadata() or ():
        if k.lower() == "x-keto-tenant" and v:
            raw = v
            break
    tenant = validate_tenant_id(raw)
    if tenant == DEFAULT_TENANT:
        return registry
    if not bool(registry.config().get("serve.tenant_enabled", True)):
        raise ErrBadRequest(
            "multi-tenant serving is disabled (serve.tenant_enabled)"
        )
    if registry.is_replica():
        raise ErrBadRequest("tenant-scoped requests are served by the primary only")
    return registry.tenant_pool().get(tenant)


def _request_metrics(m):
    """The gRPC request counter + latency histogram over metrics
    registry ``m`` (idempotent by name, so every servicer shares one
    pair — and the driver registry pre-declares them so scrapes before
    first traffic expose the families)."""
    return (
        m.counter(
            "keto_grpc_requests_total",
            "gRPC calls served, by full method and status code.",
            ("method", "code"),
        ),
        m.histogram(
            "keto_grpc_request_duration_seconds",
            "gRPC call handling latency; the slowest sample per method "
            "carries a trace_id exemplar.",
            ("method",),
        ),
    )


def _expand_metrics(m):
    """The expand request counter + build-latency histogram (idempotent
    by name; both serving surfaces share the pair, labeled by surface,
    and the driver registry pre-declares them for pre-traffic scrapes)."""
    return (
        m.counter(
            "keto_expand_requests_total",
            "Expand trees built, by serving surface (http/grpc).",
            ("surface",),
        ),
        m.histogram(
            "keto_expand_duration_seconds",
            "Expand tree construction latency (host-side recursion over "
            "the device snapshot or the Manager).",
            ("surface",),
        ),
    )


def _tenant_of(context) -> str:
    """The validated tenant the call addressed (x-keto-tenant metadata,
    absent -> the default tenant)."""
    from keto_tpu.driver.tenants import validate_tenant_id

    raw = ""
    for k, v in context.invocation_metadata() or ():
        if k.lower() == "x-keto-tenant" and v:
            raw = v
            break
    return validate_tenant_id(raw)


class _TrailingMergeContext:
    """A pass-through ServicerContext proxy that REMEMBERS the trailing
    metadata the handler set, so the wrapper can append its
    ``server-timing`` entry without clobbering it (gRPC's
    ``set_trailing_metadata`` replaces wholesale)."""

    __slots__ = ("_inner", "_trailing")

    def __init__(self, inner):
        self._inner = inner
        self._trailing: list = []

    def set_trailing_metadata(self, md) -> None:
        self._trailing = list(md or ())
        self._inner.set_trailing_metadata(tuple(self._trailing))

    def append_trailing(self, key: str, value: str) -> None:
        self._trailing.append((key, value))
        self._inner.set_trailing_metadata(tuple(self._trailing))

    def __getattr__(self, name):
        return getattr(self._inner, name)


def _wrap(fn, registry=None, name: str = ""):
    """Translate KetoError into gRPC status codes; trace + count + time
    the call (the reference's otgrpc/grpc_logrus interceptor slot,
    registry_default.go:327-346). Inbound ``traceparent`` metadata joins
    the caller's trace; ``x-request-id`` is echoed (or minted) back as
    initial metadata and bound into the logging context — the gRPC face
    of the REST correlation headers. Successful calls carry the request
    timeline's stage breakdown as ``server-timing`` trailing metadata
    (merged with whatever the handler set)."""

    def handler(request, context):
        if registry is None:
            try:
                return fn(request, context)
            except KetoError as e:
                _abort(context, e)
                return  # unreachable: abort raises
        md = {k.lower(): v for k, v in (context.invocation_metadata() or ())}
        remote = parse_traceparent(md.get("traceparent", ""))
        req_id = (md.get("x-request-id") or "").strip() or uuid.uuid4().hex
        registry.telemetry().record(f"grpc {name}")
        counter, latency = _request_metrics(registry.metrics())
        recorder = registry.timeline_recorder()
        code = "OK"
        trace_id = remote[0] if remote else ""
        t0 = time.perf_counter()
        tl = None
        context = _TrailingMergeContext(context)
        try:
            with registry.tracer().span(f"grpc.{name}", remote_parent=remote) as span:
                if span is not None:
                    trace_id = span.trace_id
                tl = recorder.begin(
                    name, trace_id=trace_id, request_id=req_id, surface="grpc",
                    tenant=(md.get("x-keto-tenant") or "").strip() or "default",
                )
                with request_context(request_id=req_id, trace_id=trace_id):
                    try:
                        context.send_initial_metadata((("x-request-id", req_id),))
                    except Exception:
                        # already sent / stream torn down
                        _log.debug(
                            "initial metadata raced stream teardown",
                            exc_info=True,
                        )
                    try:
                        with recorder.activate(tl):
                            result = fn(request, context)
                        recorder.finish(tl, status=code)
                        tl_done, tl = tl, None
                        if tl_done is not None:
                            # the gRPC face of the Server-Timing header:
                            # the stage breakdown rides trailing metadata
                            # (merged — never clobbering the handler's)
                            try:
                                context.append_trailing(
                                    "server-timing",
                                    recorder.server_timing(tl_done),
                                )
                            except Exception:
                                _log.debug(
                                    "trailing metadata raced stream teardown",
                                    exc_info=True,
                                )
                        return result
                    except KetoError as e:
                        code = _CODE_BY_NUM.get(
                            e.grpc_code, grpc.StatusCode.INTERNAL
                        ).name
                        if span is not None:
                            span.tags["code"] = code
                        _abort(context, e)
                    except Exception:
                        code = "INTERNAL"
                        raise
        finally:
            if tl is not None:  # error path: still recorded, no metadata
                recorder.finish(tl, status=code)
            counter.inc((name, code))
            latency.observe((name,), time.perf_counter() - t0, trace_id=trace_id)

    return handler


def _unary(fn, req_cls, resp_cls, registry=None, name: str = ""):
    return grpc.unary_unary_rpc_method_handler(
        _wrap(fn, registry, name),
        request_deserializer=req_cls.FromString,
        response_serializer=resp_cls.SerializeToString,
    )


class CheckService:
    """ory.keto.acl.v1alpha1.CheckService (reference internal/check/handler.go:148-164)."""

    def __init__(self, registry):
        self.registry = registry

    def Check(self, request, context):
        tuple_ = tuple_from_proto(request)
        at_least = None
        if request.snaptoken:
            # snaptokens are the snapshot ids this server minted (the
            # store watermark) — anything else is a caller bug
            try:
                at_least = int(request.snaptoken)
            except ValueError:
                raise ErrBadRequest(
                    f"malformed snaptoken {request.snaptoken!r}"
                ) from None
        # the client's gRPC deadline rides into the batcher: a request
        # that expires queued is shed with DEADLINE_EXCEEDED *before* it
        # occupies a device slice; a full queue (or the admission window)
        # is RESOURCE_EXHAUSTED with retry-after trailing metadata
        deadline = None
        remaining = context.time_remaining()
        if remaining is not None:
            deadline = time.monotonic() + max(0.0, remaining)
        # optional priority-lane hint, the gRPC face of X-Keto-Priority
        lane = None
        for k, v in context.invocation_metadata() or ():
            if k.lower() == "x-keto-priority" and v:
                lane = v.strip().lower()
                if lane not in ("interactive", "batch"):
                    raise ErrBadRequest(
                        f"invalid x-keto-priority {v!r} (expected interactive|batch)"
                    )
                break
        # replica mode: gate the pin against the applied watermark
        # (FAILED_PRECONDITION above it), then the Watch-invalidated
        # check cache — same semantics as the REST path
        scope = _scope_from(self.registry, context)
        rep = scope.replica_controller()
        cache = rep.checkcache if rep is not None else None
        key = None
        if rep is not None:
            rep.gate_read(at_least, bool(request.latest))
            if cache is not None:
                key = str(tuple_)
                got = cache.get(key, at_least)
                if got is not None:
                    allowed, token = got
                    return check_service_pb2.CheckResponse(
                        allowed=allowed, snaptoken=str(token)
                    )
        allowed, token = scope.check_batcher().check_with_token(
            tuple_, at_least=at_least, latest=request.latest, deadline=deadline,
            lane=lane,
        )
        if cache is not None and key is not None:
            cache.put(key, allowed, token)
        return check_service_pb2.CheckResponse(
            allowed=allowed, snaptoken="" if token is None else str(token)
        )

    def register(self, server):
        server.add_generic_rpc_handlers(
            (
                grpc.method_handlers_generic_handler(
                    "ory.keto.acl.v1alpha1.CheckService",
                    {
                        "Check": _unary(
                            self.Check,
                            check_service_pb2.CheckRequest,
                            check_service_pb2.CheckResponse,
                            self.registry,
                            "CheckService/Check",
                        )
                    },
                ),
            )
        )


class ExpandService:
    """ory.keto.acl.v1alpha1.ExpandService (reference internal/expand/handler.go:94-105)."""

    def __init__(self, registry):
        self.registry = registry

    def Expand(self, request, context):
        subject = subject_from_proto(request.subject)
        scope = _scope_from(self.registry, context)
        rep = scope.replica_controller()
        if rep is not None:
            rep.gate_read(None)  # UNAVAILABLE until the first bootstrap
        counter, latency = _expand_metrics(self.registry.metrics())
        depth = scope.expand_depth(request.max_depth)
        t0 = time.perf_counter()
        tree = scope.expand_engine().build_tree(subject, depth)
        dur_s = time.perf_counter() - t0
        counter.inc(("grpc",))
        latency.observe(("grpc",), dur_s)
        tl = current_timeline()
        if tl is not None:
            tl.stamp("expand", depth=depth)
        return expand_service_pb2.ExpandResponse(tree=tree_to_proto(tree))

    def register(self, server):
        server.add_generic_rpc_handlers(
            (
                grpc.method_handlers_generic_handler(
                    "ory.keto.acl.v1alpha1.ExpandService",
                    {
                        "Expand": _unary(
                            self.Expand,
                            expand_service_pb2.ExpandRequest,
                            expand_service_pb2.ExpandResponse,
                            self.registry,
                            "ExpandService/Expand",
                        )
                    },
                ),
            )
        )


class ReadService:
    """ory.keto.acl.v1alpha1.ReadService (reference internal/relationtuple/read_server.go:21-48)."""

    def __init__(self, registry):
        self.registry = registry

    def ListRelationTuples(self, request, context):
        if not request.HasField("query"):
            raise ErrBadRequest("invalid request")
        query = query_from_proto(request.query)
        scope = _scope_from(self.registry, context)
        rep = scope.replica_controller()
        if rep is not None:
            rep.gate_read(None)  # UNAVAILABLE until the first bootstrap
        opts = []
        if request.page_token:
            opts.append(with_token(request.page_token))
        if request.page_size:
            opts.append(with_size(request.page_size))
        rels, next_page = scope.relation_tuple_manager().get_relation_tuples(
            query, *opts
        )
        from keto_tpu.relationtuple.proto_codec import tuple_to_proto

        return read_service_pb2.ListRelationTuplesResponse(
            relation_tuples=[tuple_to_proto(r) for r in rels], next_page_token=next_page
        )

    def register(self, server):
        server.add_generic_rpc_handlers(
            (
                grpc.method_handlers_generic_handler(
                    "ory.keto.acl.v1alpha1.ReadService",
                    {
                        "ListRelationTuples": _unary(
                            self.ListRelationTuples,
                            read_service_pb2.ListRelationTuplesRequest,
                            read_service_pb2.ListRelationTuplesResponse,
                            self.registry,
                            "ReadService/ListRelationTuples",
                        )
                    },
                ),
            )
        )


class WriteService:
    """ory.keto.acl.v1alpha1.WriteService (reference internal/relationtuple/transact_server.go:30-53)."""

    def __init__(self, registry):
        self.registry = registry

    def TransactRelationTuples(self, request, context):
        if self.registry.is_replica():
            from keto_tpu.x.errors import ErrReplicaReadOnly

            raise ErrReplicaReadOnly()
        insert, delete = [], []
        for delta in request.relation_tuple_deltas:
            action = delta.action
            if action == write_service_pb2.RelationTupleDelta.INSERT:
                insert.append(tuple_from_proto(delta.relation_tuple))
            elif action == write_service_pb2.RelationTupleDelta.DELETE:
                delete.append(tuple_from_proto(delta.relation_tuple))
            else:
                raise ErrBadRequest(f"unknown action {action}")
        # the gRPC face of REST's X-Idempotency-Key: an
        # ``x-idempotency-key`` metadata entry makes the transaction
        # exactly-once per key — a retry after an ambiguous failure
        # (connection died post-commit, pre-ack) replays the original
        # snaptoken, flagged by ``keto-idempotent-replay`` trailing
        # metadata, instead of re-applying the deltas
        idem_key = None
        for k, v in context.invocation_metadata() or ():
            if k.lower() == "x-idempotency-key" and v:
                idem_key = v
                break
        scope = _scope_from(self.registry, context)
        manager = scope.relation_tuple_manager()
        # routed through the group-commit coordinator when enabled
        result = scope.transact_writes()(
            insert, delete, idempotency_key=idem_key
        )
        if result is not None:
            token = str(result.snaptoken)
            if result.replayed:
                context.set_trailing_metadata((("keto-idempotent-replay", "true"),))
            else:
                # replication-aware tracing: the watch emission of this
                # commit carries the writer's traceparent (rest.py's
                # _note_commit, gRPC face)
                from keto_tpu.x.tracing import current_traceparent

                try:
                    scope.watch_hub().note_commit_trace(
                        int(result.snaptoken), current_traceparent()
                    )
                except Exception:
                    _log.debug("commit-trace registration failed", exc_info=True)
        else:  # legacy manager without a transact result
            token = str(manager.watermark())
        return write_service_pb2.TransactRelationTuplesResponse(
            snaptokens=[token] * len(request.relation_tuple_deltas)
        )

    def register(self, server):
        server.add_generic_rpc_handlers(
            (
                grpc.method_handlers_generic_handler(
                    "ory.keto.acl.v1alpha1.WriteService",
                    {
                        "TransactRelationTuples": _unary(
                            self.TransactRelationTuples,
                            write_service_pb2.TransactRelationTuplesRequest,
                            write_service_pb2.TransactRelationTuplesResponse,
                            self.registry,
                            "WriteService/TransactRelationTuples",
                        )
                    },
                ),
            )
        )


def _json_ser(obj) -> bytes:
    return json.dumps(obj).encode()


def _json_de(data: bytes):
    return json.loads(data.decode() or "{}")


def _subject_from_request(req: dict):
    """subject_id XOR subject_set from a JSON-framed request (the same
    convention as the REST tuple codec)."""
    sid = req.get("subject_id")
    sset = req.get("subject_set")
    if sid is not None:
        from keto_tpu.relationtuple.model import SubjectID

        return SubjectID(id=str(sid))
    if isinstance(sset, dict):
        from keto_tpu.relationtuple.model import SubjectSet

        return SubjectSet(
            namespace=str(sset.get("namespace", "")),
            object=str(sset.get("object", "")),
            relation=str(sset.get("relation", "")),
        )
    return None


class ListService:
    """keto.tpu.list.v1.ListService — the gRPC face of the reverse-query
    endpoints. The upstream acl.v1alpha1 contract has no reverse-query
    surface and the runtime image carries no protoc plugin, so these
    methods frame requests/responses as UTF-8 JSON objects mirroring the
    REST payloads exactly (documented in docs/concepts/api-overview.md);
    any grpc client can call them with a JSON serializer."""

    def __init__(self, registry):
        self.registry = registry

    @staticmethod
    def _consistency(req: dict):
        raw = str(req.get("snaptoken", "") or "")
        at_least = None
        if raw:
            try:
                at_least = int(raw)
            except ValueError:
                raise ErrBadRequest(f"malformed snaptoken {raw!r}") from None
        return at_least, bool(req.get("latest"))

    def ListObjects(self, request, context):
        ns = str(request.get("namespace", ""))
        rel = str(request.get("relation", ""))
        if not ns:
            raise ErrBadRequest("namespace has to be specified")
        if not rel:
            raise ErrBadRequest("relation has to be specified")
        sub = _subject_from_request(request)
        if sub is None:
            raise ErrBadRequest("Subject has to be specified.")
        scope = _scope_from(self.registry, context)
        at_least, latest = self._consistency(request)
        rep = scope.replica_controller()
        if rep is not None:
            rep.gate_read(at_least, latest)
        objs, nxt, token = scope.list_engine().page_objects(
            ns, rel, sub,
            page_size=int(request.get("page_size", 0) or 0),
            page_token=str(request.get("page_token", "") or ""),
            at_least=at_least, latest=latest,
        )
        return {"objects": objs, "next_page_token": nxt, "snaptoken": str(token)}

    def ListSubjects(self, request, context):
        ns = str(request.get("namespace", ""))
        obj = str(request.get("object", ""))
        rel = str(request.get("relation", ""))
        if not ns:
            raise ErrBadRequest("namespace has to be specified")
        if not obj:
            raise ErrBadRequest("object has to be specified")
        if not rel:
            raise ErrBadRequest("relation has to be specified")
        scope = _scope_from(self.registry, context)
        at_least, latest = self._consistency(request)
        rep = scope.replica_controller()
        if rep is not None:
            rep.gate_read(at_least, latest)
        subs, nxt, token = scope.list_engine().page_subjects(
            ns, obj, rel,
            page_size=int(request.get("page_size", 0) or 0),
            page_token=str(request.get("page_token", "") or ""),
            at_least=at_least, latest=latest,
        )
        return {
            "subject_ids": subs,
            "next_page_token": nxt,
            "snaptoken": str(token),
        }

    def register(self, server):
        server.add_generic_rpc_handlers(
            (
                grpc.method_handlers_generic_handler(
                    "keto.tpu.list.v1.ListService",
                    {
                        "ListObjects": grpc.unary_unary_rpc_method_handler(
                            _wrap(self.ListObjects, self.registry,
                                  "ListService/ListObjects"),
                            request_deserializer=_json_de,
                            response_serializer=_json_ser,
                        ),
                        "ListSubjects": grpc.unary_unary_rpc_method_handler(
                            _wrap(self.ListSubjects, self.registry,
                                  "ListService/ListSubjects"),
                            request_deserializer=_json_de,
                            response_serializer=_json_ser,
                        ),
                    },
                ),
            )
        )


class ExplainService:
    """keto.tpu.explain.v1.ExplainService — the gRPC face of
    ``GET /check/explain`` (keto_tpu/explain). Like ListService, the
    upstream acl.v1alpha1 contract has no provenance surface, so the
    method frames requests/responses as UTF-8 JSON mirroring the REST
    payloads exactly: the request is a relation tuple (``subject_id``
    XOR ``subject_set``) plus optional ``snaptoken``/``latest``; the
    response carries the decision, the route that made it, the
    Manager-verified witness path or frontier-exhaustion certificate,
    and — on label-route grants — the winning landmark."""

    def __init__(self, registry):
        self.registry = registry

    def Explain(self, request, context):
        scope = _scope_from(self.registry, context)
        if not bool(scope.config().get("serve.explain_enabled", True)):
            from keto_tpu.x.errors import ErrNotFound

            raise ErrNotFound("explain disabled by configuration")
        from keto_tpu.relationtuple.model import RelationTuple

        rt = RelationTuple.from_json(request)
        at_least, latest = ListService._consistency(request)
        rep = scope.replica_controller()
        if rep is not None:
            rep.gate_read(at_least, latest)
        tl = current_timeline()
        resp = scope.explain_engine().explain(
            rt,
            at_least=at_least,
            trace_id=tl.trace_id if tl is not None else "",
            tenant=_tenant_of(context),
        )
        if tl is not None:
            tl.stamp(
                "explain",
                route=resp.get("route", ""),
                verified=bool(resp.get("verified")),
            )
        return resp

    def register(self, server):
        server.add_generic_rpc_handlers(
            (
                grpc.method_handlers_generic_handler(
                    "keto.tpu.explain.v1.ExplainService",
                    {
                        "Explain": grpc.unary_unary_rpc_method_handler(
                            _wrap(self.Explain, self.registry,
                                  "ExplainService/Explain"),
                            request_deserializer=_json_de,
                            response_serializer=_json_ser,
                        ),
                    },
                ),
            )
        )


def _wrap_stream(fn, registry, name: str):
    """The server-streaming analog of ``_wrap``: KetoError → status
    codes, request counter + latency on stream end."""

    def handler(request, context):
        counter, latency = _request_metrics(registry.metrics())
        code = "OK"
        t0 = time.perf_counter()
        try:
            yield from fn(request, context)
        except KetoError as e:
            code = _CODE_BY_NUM.get(e.grpc_code, grpc.StatusCode.INTERNAL).name
            _abort(context, e)
        except Exception:
            code = "INTERNAL"
            raise
        finally:
            counter.inc((name, code))
            latency.observe((name,), time.perf_counter() - t0)

    return handler


class WatchService:
    """keto.tpu.watch.v1.WatchService — server-streaming changefeed, the
    gRPC face of ``GET /watch``. JSON-framed like ListService; each
    message is one commit group ``{"snaptoken", "changes": [{"action",
    "relation_tuple"}]}``, resumable from any retained snaptoken
    (OUT_OF_RANGE past the horizon), ended by server drain."""

    def __init__(self, registry):
        self.registry = registry

    def Watch(self, request, context):
        hub = _scope_from(self.registry, context).watch_hub()
        raw = str(request.get("snaptoken", "") or "0")
        try:
            since = int(raw)
        except ValueError:
            raise ErrBadRequest(f"malformed snaptoken {raw!r}") from None
        hub.changes_since(since)  # OUT_OF_RANGE before any message flows
        for token, changes in hub.subscribe(since):
            if not context.is_active():
                return
            yield hub.enrich_group(
                token,
                {
                    "snaptoken": str(token),
                    "changes": [
                        {"action": action, "relation_tuple": rt.to_json()}
                        for action, rt in changes
                    ],
                },
            )

    def register(self, server):
        server.add_generic_rpc_handlers(
            (
                grpc.method_handlers_generic_handler(
                    "keto.tpu.watch.v1.WatchService",
                    {
                        "Watch": grpc.unary_stream_rpc_method_handler(
                            _wrap_stream(self.Watch, self.registry,
                                         "WatchService/Watch"),
                            request_deserializer=_json_de,
                            response_serializer=_json_ser,
                        ),
                    },
                ),
            )
        )


class VersionService:
    """ory.keto.acl.v1alpha1.VersionService (reference proto version.proto:15-19)."""

    def __init__(self, registry):
        self.registry = registry

    def GetVersion(self, request, context):
        return version_pb2.GetVersionResponse(version=self.registry.version())

    def register(self, server):
        server.add_generic_rpc_handlers(
            (
                grpc.method_handlers_generic_handler(
                    "ory.keto.acl.v1alpha1.VersionService",
                    {
                        "GetVersion": _unary(
                            self.GetVersion,
                            version_pb2.GetVersionRequest,
                            version_pb2.GetVersionResponse,
                        )
                    },
                ),
            )
        )


class HealthService:
    """grpc.health.v1.Health, driven by the health state machine
    (keto_tpu/driver/health.py) instead of the reference's static SERVING
    (registry_default.go:105-111). STARTING/SERVING/DEGRADED map to
    SERVING (traffic should flow — degraded answers are bit-identical,
    just slower); NOT_SERVING means the snapshot is beyond its staleness
    budget or maintenance died. ``Watch`` streams every transition, so
    load balancers drop the backend the moment it goes stale and re-add
    it when maintenance catches up."""

    def __init__(self, registry=None):
        self.registry = registry

    def _grpc_status(self):
        if self.registry is None:
            return health_pb2.HealthCheckResponse.SERVING
        from keto_tpu.driver.health import READY_STATES

        state, _ = self.registry.health_monitor().status()
        if state in READY_STATES:
            return health_pb2.HealthCheckResponse.SERVING
        return health_pb2.HealthCheckResponse.NOT_SERVING

    def Check(self, request, context):
        return health_pb2.HealthCheckResponse(status=self._grpc_status())

    def Watch(self, request, context):
        yield health_pb2.HealthCheckResponse(status=self._grpc_status())
        if self.registry is None:
            return
        last = self._grpc_status()
        while context.is_active():
            cur = self._grpc_status()
            if cur != last:
                yield health_pb2.HealthCheckResponse(status=cur)
                last = cur
            time.sleep(0.2)

    def register(self, server):
        server.add_generic_rpc_handlers(
            (
                grpc.method_handlers_generic_handler(
                    "grpc.health.v1.Health",
                    {
                        "Check": _unary(
                            self.Check,
                            health_pb2.HealthCheckRequest,
                            health_pb2.HealthCheckResponse,
                        ),
                        "Watch": grpc.unary_stream_rpc_method_handler(
                            self.Watch,
                            request_deserializer=health_pb2.HealthCheckRequest.FromString,
                            response_serializer=health_pb2.HealthCheckResponse.SerializeToString,
                        ),
                    },
                ),
            )
        )


def build_grpc_server(registry, role: str, address: str = "127.0.0.1:0"):
    """A grpc.Server with the role's services registered; returns
    (server, bound_port)."""
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=32))
    if role == READ:
        CheckService(registry).register(server)
        ExpandService(registry).register(server)
        ReadService(registry).register(server)
        ListService(registry).register(server)
        ExplainService(registry).register(server)
        WatchService(registry).register(server)
    else:
        WriteService(registry).register(server)
    VersionService(registry).register(server)
    HealthService(registry).register(server)
    port = server.add_insecure_port(address)
    return server, port
