"""Event-loop REST backend: one reactor, bounded handler pool.

The stdlib ``ThreadingHTTPServer`` backend (keto_tpu/servers/rest.py)
spends a thread per CONNECTION — fine for parity tests, thin behind the
serving-grade C++ epoll mux (native/mux.cpp). This backend serves the
same ``RestApp`` routes from one asyncio reactor: connections cost a
coroutine, HTTP/1.1 keep-alive is honored, and handler execution (which
blocks on engine futures) runs on a BOUNDED thread pool — concurrency
backpressure lands in the pool's queue instead of in an unbounded thread
count. Selected via ``serve.http_backend`` (default ``async``;
``threading`` keeps the stdlib backend).

The handler pool is LANED by endpoint: ``/check/batch`` requests run on
their own smaller pool, so batch POSTs blocked on chunk futures can
never occupy every handler thread and convoy interactive checks at the
HTTP layer — the server-side face of the batcher's priority lanes.

Protocol scope matches the reference surface: Content-Length bodies
(no chunked requests), small JSON responses, no upgrades.
"""

from __future__ import annotations

import asyncio
import json
import logging
import threading
from concurrent.futures import ThreadPoolExecutor
from http import HTTPStatus
from typing import Optional
from urllib.parse import parse_qs, urlsplit

from keto_tpu.servers.rest import RawBody, RestApp, StreamBody

_log = logging.getLogger("keto_tpu.rest")

_MAX_HEAD = 64 * 1024
_MAX_BODY = 64 * 1024 * 1024

_REASONS = {s.value: s.phrase for s in HTTPStatus}

#: the listener-level shed envelope (matches the x/errors 429 rendering)
_SHED_BODY = {
    "error": {
        "code": 429,
        "status": "Too Many Requests",
        "message": "batch check backlog full (server overloaded); retry with backoff",
    }
}


class AsyncRestServer:
    """Drop-in for ``RestServer`` (same constructor surface, ``port``,
    ``start``/``stop``) on an asyncio reactor."""

    def __init__(
        self,
        registry,
        role: str,
        host: str = "127.0.0.1",
        port: int = 0,
        workers: int = 32,
    ):
        self.app = RestApp(registry, role)
        self._host = host or "0.0.0.0"
        self._want_port = port
        self._port: Optional[int] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._thread: Optional[threading.Thread] = None
        self._ready = threading.Event()
        self._boot_error: Optional[BaseException] = None
        self._conns: set[asyncio.StreamWriter] = set()
        # request exchanges mid-flight (head parsed → response flushed);
        # only the event-loop thread mutates it, other threads poll it in
        # drain() — the SIGTERM path waits for this to hit zero before
        # connections are aborted, so accepted requests get their bytes
        self._active = 0
        self._pool = ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix=f"rest-{role}"
        )
        # batch-check requests block their handler thread for the whole
        # chunk's latency AND burn real CPU decoding their payloads; a
        # dedicated small pool keeps them from convoying interactive
        # checks out of handler threads (or out of the GIL). The pool's
        # waiting line is BOUNDED: past _batch_limit pending exchanges
        # the listener sheds 429 + Retry-After straight from the event
        # loop — every queue in the path is bounded and sheds
        # explicitly, none hides unbounded latency
        n_batch = max(4, workers // 8)
        self._batch_pool = ThreadPoolExecutor(
            max_workers=n_batch, thread_name_prefix=f"rest-{role}-batch"
        )
        self._batch_limit = 3 * n_batch
        self._batch_pending = 0  # event-loop thread only
        # watch streams live for the connection's lifetime and block
        # between events — a dedicated pool keeps them from occupying
        # request-handler threads (the hub's max_streams bounds the
        # count, so sizing the pool to it never queues a live stream
        # behind another); list traversals ride the BATCH pool so a
        # 100k-result listing never convoys interactive checks out of
        # handler threads — the server-side face of the batcher's
        # priority lanes, applied to the reverse-query surface
        self._watch_pool = ThreadPoolExecutor(
            max_workers=64, thread_name_prefix=f"rest-{role}-watch"
        )
        #: swallowed-with-a-trace counters (keto-analyze KTA401 seam):
        #: connection teardown races and protocol-level failures
        self.teardown_errors = 0
        self.protocol_errors = 0

    @property
    def port(self) -> int:
        assert self._port is not None, "server not started"
        return self._port

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name=f"rest-async-{self.app.role}", daemon=True
        )
        self._thread.start()
        if not self._ready.wait(timeout=10):
            raise RuntimeError("async REST server failed to start (timeout)")
        if self._boot_error is not None:
            raise RuntimeError(
                f"async REST server failed to start: {self._boot_error!r}"
            ) from self._boot_error

    def _run(self) -> None:
        loop = asyncio.new_event_loop()
        self._loop = loop
        asyncio.set_event_loop(loop)

        async def boot():
            self._server = await asyncio.start_server(
                self._serve_connection, self._host, self._want_port,
                limit=_MAX_HEAD,
            )
            self._port = self._server.sockets[0].getsockname()[1]

        try:
            try:
                loop.run_until_complete(boot())
            except BaseException as e:  # bind failures etc. → surface in start()
                self._boot_error = e
                return
            finally:
                self._ready.set()
            loop.run_forever()
        finally:
            loop.close()

    def drain(self, timeout_s: float) -> bool:
        """Wait (from any thread) until no request exchange is mid-flight
        — every accepted request has had its response flushed. True when
        idle within ``timeout_s``."""
        import time as _time

        deadline = _time.monotonic() + max(0.0, timeout_s)
        while _time.monotonic() < deadline:
            if self._active == 0:
                return True
            _time.sleep(0.01)
        return self._active == 0

    def stop(self) -> None:
        loop = self._loop
        if loop is None or not loop.is_running():
            self._pool.shutdown(wait=False, cancel_futures=True)
            self._batch_pool.shutdown(wait=False, cancel_futures=True)
            self._watch_pool.shutdown(wait=False, cancel_futures=True)
            return

        async def teardown():
            if self._server is not None:
                self._server.close()
                # idle keep-alive connections would make wait_closed()
                # (which on 3.12+ waits for EVERY connection) hang forever
                # — abort them; in-flight handlers see a reset, matching
                # what a process exit would do anyway
                for w in list(self._conns):
                    try:
                        w.transport.abort()
                    except Exception:
                        # a connection torn down concurrently by its peer;
                        # nothing to abort, but keep the trace visible
                        self.teardown_errors += 1
                        _log.debug("transport abort raced teardown", exc_info=True)
                try:
                    await asyncio.wait_for(self._server.wait_closed(), timeout=3)
                except (TimeoutError, asyncio.TimeoutError):
                    pass
            loop.stop()

        asyncio.run_coroutine_threadsafe(teardown(), loop)
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None
        self._pool.shutdown(wait=False, cancel_futures=True)
        self._batch_pool.shutdown(wait=False, cancel_futures=True)
        self._watch_pool.shutdown(wait=False, cancel_futures=True)

    # -- per-connection ------------------------------------------------------

    async def _serve_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        self._conns.add(writer)
        try:
            while True:
                head = await self._read_head(reader)
                if head is None:
                    return  # EOF / oversized / malformed — drop quietly
                method, target, version, headers = head
                if "transfer-encoding" in headers:
                    # out of protocol scope (module doc): REJECT with
                    # correct framing — parsing chunk framing as the next
                    # request head would desync the connection
                    await self._write_response(
                        writer, 501,
                        {"error": {"message": "chunked requests unsupported"}},
                        {}, True,
                    )
                    return
                if method == "HEAD":
                    # RestApp has no HEAD routes and a HEAD response must
                    # not carry a body (a client would misparse the next
                    # response) — cleanly framed 501 + close, matching the
                    # stdlib backend
                    await self._write_response(writer, 501, None, {}, True)
                    return
                length = int(headers.get("content-length") or 0)
                if length < 0 or length > _MAX_BODY:
                    await self._write_response(
                        writer, 413, {"error": {"message": "body too large"}}, {}, True
                    )
                    return
                body = await reader.readexactly(length) if length else b""
                parts = urlsplit(target)
                query = parse_qs(parts.query, keep_blank_values=True)
                close = (
                    version == "HTTP/1.0"
                    or headers.get("connection", "").lower() == "close"
                )
                is_batch = parts.path in (
                    "/check/batch",
                    "/relation-tuples/list-objects",
                    "/relation-tuples/list-subjects",
                )
                if is_batch and self._batch_pending >= self._batch_limit:
                    # listener-level shed: the batch pool's waiting line
                    # is full — refuse for microseconds on the event loop
                    # instead of queueing invisible seconds of latency
                    self.app.note_listener_shed(method, parts.path)
                    await self._write_response(
                        writer, 429, _SHED_BODY, {"Retry-After": "1"}, close
                    )
                    if close:
                        return
                    continue
                self._active += 1
                if is_batch:
                    self._batch_pending += 1
                streamed = False
                try:
                    pool = self._batch_pool if is_batch else self._pool
                    status, payload, extra = await asyncio.get_running_loop().run_in_executor(
                        pool, self.app.handle, method, parts.path, query, body,
                        headers,
                    )
                    if isinstance(payload, StreamBody):
                        streamed = True
                    else:
                        await self._write_response(writer, status, payload, extra, close)
                finally:
                    self._active -= 1
                    if is_batch:
                        self._batch_pending -= 1
                if streamed:
                    # long-lived chunked stream (GET /watch): drive the
                    # blocking generator on the dedicated watch pool so
                    # request-handler threads stay free; stream
                    # responses never keep-alive. Runs OUTSIDE _active —
                    # the SIGTERM drain must not wait on open watches
                    # (the hub's close() ends them instead).
                    await self._write_stream(writer, status, payload, extra)
                    return
                if close:
                    return
        except (
            asyncio.IncompleteReadError,
            ConnectionResetError,
            BrokenPipeError,
            asyncio.LimitOverrunError,
        ):
            pass
        except Exception:
            # handler exceptions are already mapped to 500 envelopes inside
            # RestApp; anything surfacing here is a protocol-level failure
            # — counted, and traced at debug (malformed client bytes must
            # not let a scanner spam the operator log at warning level)
            self.protocol_errors += 1
            _log.debug("protocol-level connection failure", exc_info=True)
        finally:
            self._conns.discard(writer)
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                self.teardown_errors += 1
                _log.debug("connection close raced teardown", exc_info=True)

    @staticmethod
    async def _read_head(reader: asyncio.StreamReader):
        """(method, target, version, lowercase header dict) or None."""
        try:
            # the stream limit (start_server limit=_MAX_HEAD) bounds the
            # head size: oversized heads raise LimitOverrunError here
            raw = await reader.readuntil(b"\r\n\r\n")
        except asyncio.IncompleteReadError:
            return None
        except asyncio.LimitOverrunError:
            return None
        try:
            lines = raw.decode("latin-1").split("\r\n")
            method, target, version = lines[0].split(" ", 2)
            headers: dict[str, str] = {}
            for line in lines[1:]:
                if not line:
                    continue
                k, _, v = line.partition(":")
                headers[k.strip().lower()] = v.strip()
            return method.upper(), target, version.strip(), headers
        except ValueError:
            return None

    async def _write_stream(
        self, writer: asyncio.StreamWriter, status: int, payload: StreamBody,
        extra: dict,
    ) -> None:
        """Chunked transfer of a StreamBody: each ``next()`` on the
        (blocking) generator runs on the watch pool; chunks flush as
        they arrive so subscribers see commits live."""
        head = [
            f"HTTP/1.1 {status} {_REASONS.get(status, '')}",
            f"Content-Type: {payload.content_type}",
            "Transfer-Encoding: chunked",
            "Server: keto-tpu",
        ]
        for k, v in extra.items():
            head.append(f"{k}: {v}")
        head.append("Connection: close")
        writer.write(("\r\n".join(head) + "\r\n\r\n").encode())
        await writer.drain()
        chunks = payload.chunks
        loop = asyncio.get_running_loop()
        end = object()
        try:
            while True:
                chunk = await loop.run_in_executor(self._watch_pool, next, chunks, end)
                if chunk is end:
                    writer.write(b"0\r\n\r\n")
                    await writer.drain()
                    return
                if not chunk:
                    continue
                writer.write(b"%x\r\n" % len(chunk) + chunk + b"\r\n")
                await writer.drain()
        finally:
            # client disconnects (ConnectionResetError out of drain) land
            # here: closing the generator releases its watch slot
            close = getattr(chunks, "close", None)
            if close is not None:
                await loop.run_in_executor(self._watch_pool, close)

    async def _write_response(
        self, writer: asyncio.StreamWriter, status: int, payload, extra: dict,
        close: bool,
    ) -> None:
        if isinstance(payload, RawBody):
            data, content_type = payload.data, payload.content_type
        else:
            data = b"" if payload is None else json.dumps(payload).encode()
            content_type = "application/json"
        reason = _REASONS.get(status, "")
        head = [
            f"HTTP/1.1 {status} {reason}",
            f"Content-Type: {content_type}",
            f"Content-Length: {len(data)}",
            "Server: keto-tpu",
        ]
        for k, v in extra.items():
            head.append(f"{k}: {v}")
        head.append("Connection: close" if close else "Connection: keep-alive")
        writer.write(("\r\n".join(head) + "\r\n\r\n").encode() + data)
        await writer.drain()
