"""REST handlers over the stdlib HTTP server.

Endpoint behavior is a 1:1 mapping of the reference REST surface:

- ``GET /check`` decodes the tuple from the URL query; a nil subject is a
  400 with "Subject has to be specified." (reference
  internal/check/handler.go:85-107); the *status code mirrors the
  decision*: 200 allowed / 403 denied, body ``{"allowed": bool}``.
- ``POST /check`` takes the tuple as JSON (handler.go:128-146).
- ``POST /check/batch`` takes ``{"tuples": [...]}`` and answers
  ``{"results": [bool, ...]}`` in order — big payloads ride the
  batcher's BATCH priority lane and dispatch in bounded sub-slices that
  interleave with interactive checks. An ``X-Keto-Priority`` header
  (``interactive`` | ``batch``) pins the lane on any check route;
  without it, request size classifies.
- ``GET /expand`` requires ``max-depth`` plus a subject-set query and
  returns the tree JSON (reference internal/expand/handler.go:79-92).
- ``GET /relation-tuples`` decodes a RelationQuery + ``page_token`` /
  ``page_size`` and returns ``{"relation_tuples": [...],
  "next_page_token": "..."}`` (reference
  internal/relationtuple/read_server.go:77-117).
- ``PUT /relation-tuples`` creates from a JSON body → 201 + Location
  (reference transact_server.go:130-153); ``DELETE`` by URL query → 204
  (transact_server.go:173-187); ``PATCH`` applies
  ``[{"action": "insert"|"delete", "relation_tuple": {...}}]``
  atomically → 204 (transact_server.go:217-242).
- ``GET /health/alive`` → ``{"status": "ok"}`` (process liveness, the
  reference's static answer, registry_default.go:97-103);
  ``GET /health/ready`` is *real* readiness: the health state machine
  (keto_tpu/driver/health.py) answers 200 ``{"status": "ok"}`` /
  ``{"status": "degraded", ...}`` when traffic should flow and **503 +
  JSON reason** when the snapshot is beyond its staleness budget or
  maintenance died; ``GET /version``; ``GET /metrics`` serves the
  Prometheus text exposition of the process-wide MetricsRegistry
  (keto_tpu/x/metrics.py) on BOTH API ports — one scrape config covers
  read and write processes.

Deadline propagation: an ``X-Request-Timeout-Ms`` header (or
``timeout_ms`` query parameter) on ``/check`` rides into the batcher as
an absolute deadline — expired requests shed with **504** before they
occupy a device slice, and a full check queue (or the adaptive
admission window, keto_tpu/driver/admission.py) sheds with **429 +
Retry-After** (keto_tpu/driver/batch.py). Every overload response (429,
and 503 while NOT_SERVING) carries a ``Retry-After`` header with the
server's backoff advice.

Multi-tenant serving: an ``X-Keto-Tenant`` header scopes the request to
one tenant's engine, batcher, store view, and watch hub (the TenantPool,
keto_tpu/driver/tenants.py). Absent header → the default tenant, which
IS the pre-tenancy registry — every existing contract (snaptokens,
replica gating, idempotency, watch) is untouched. Tenant-scoped sheds
echo the tenant on ``X-Keto-Tenant`` so clients can attribute 429s, and
Retry-After reflects THAT tenant's overload run, not the machine's.

Request correlation: every non-health request gets (or echoes) an
``X-Request-Id``, joins the caller's trace when a W3C ``traceparent``
header is present, and binds both ids into the logging context
(keto_tpu/x/logging.request_context) for the handler's duration — log
lines, spans, response headers, and latency exemplars all carry the same
ids. Route labels on the request metrics are cardinality-bounded: paths
outside the declared surface count as ``other``.

Errors render the herodot-style envelope from keto_tpu/x/errors.py.
"""

from __future__ import annotations

import json
import math
import re
import threading
import time
import uuid
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Optional
from urllib.parse import parse_qs, urlsplit

from keto_tpu.expand.tree import Tree
from keto_tpu.relationtuple.model import (
    RelationQuery,
    RelationTuple,
    subject_set_from_url_query,
)
from keto_tpu.x.errors import ErrBadRequest, ErrNilSubject, KetoError
from keto_tpu.x.logging import request_context
from keto_tpu.x.metrics import normalize_route
from keto_tpu.x.pagination import with_size, with_token
from keto_tpu.x.tracing import current_traceparent, parse_traceparent

#: routes whose handling is excluded from request-timeline recording —
#: scrapes and the debug surfaces themselves would otherwise churn the
#: ring the operator is trying to read
_TIMELINE_EXCLUDED = frozenset({"/metrics", "/debug/requests", "/slo"})

READ = "read"
WRITE = "write"


#: upper bound on one /check/batch payload — bigger requests should page
#: (the batcher would serve it, but a single response holding >64k bools
#: is a client bug more often than a workload)
MAX_BATCH_CHECK = 65536


def _error_headers(err: KetoError) -> dict[str, str]:
    """Overload errors carry the server's backoff advice: a Retry-After
    header (integer seconds) on 429/503/412 responses. A replica's 412
    additionally surfaces its current applied watermark as
    ``X-Keto-Watermark`` so callers can re-pin or route to the primary."""
    out: dict[str, str] = {}
    ra = getattr(err, "retry_after_s", None)
    if ra:
        out["Retry-After"] = str(max(1, math.ceil(ra)))
    wm = (getattr(err, "details", None) or {}).get("watermark")
    if wm is not None:
        out["X-Keto-Watermark"] = str(wm)
    # tenant-scoped sheds name the tenant: a client multiplexing many
    # tenants over one pool attributes the 429 without parsing the body
    tn = (getattr(err, "details", None) or {}).get("tenant")
    if tn:
        out["X-Keto-Tenant"] = str(tn)
    return out


@dataclass
class RawBody:
    """A non-JSON response payload (``/metrics`` exposition): the server
    backends write ``data`` verbatim under ``content_type`` instead of
    JSON-encoding."""

    data: bytes
    content_type: str


@dataclass
class StreamBody:
    """A chunked streaming response (``GET /watch``): the server
    backends write ``Transfer-Encoding: chunked`` and iterate ``chunks``
    (bytes per chunk) until exhaustion, then close the connection.
    Closing the iterator on client disconnect releases its resources
    (the watch hub's stream slot)."""

    chunks: Any  # iterator of bytes
    content_type: str = "application/x-ndjson"


class RestApp:
    """Routes requests for one server role against the registry."""

    def __init__(self, registry, role: str):
        self.registry = registry
        self.role = role
        self._log = registry.logger()
        # request metrics, declared once per app (creation is idempotent
        # across the two roles; recording is the per-request hot path)
        m = registry.metrics()
        self._req_count = m.counter(
            "keto_http_requests_total",
            "REST requests served, by role/method/route/status code "
            "(health endpoints excluded; undeclared routes count as 'other').",
            ("role", "method", "route", "code"),
        )
        self._req_latency = m.histogram(
            "keto_http_request_duration_seconds",
            "REST request handling latency; the slowest sample per route "
            "carries a trace_id exemplar.",
            ("role", "method", "route"),
        )

    # -- dispatch ------------------------------------------------------------

    def handle(
        self,
        method: str,
        path: str,
        query: dict[str, list[str]],
        body: bytes,
        headers: Optional[dict[str, str]] = None,
    ):
        """Returns (status, payload-dict | None, headers-dict).
        ``headers`` are the request headers, lowercase-keyed (deadline
        propagation, trace context); absent for callers that don't carry
        them."""
        # request span + usage counter + metrics (health endpoints
        # excluded), matching the reference's middleware placement
        # (registry_default.go:288-300)
        if path.startswith("/health/"):
            return self._route(method, path, query, body, headers)
        hdrs = headers or {}
        route = normalize_route(path)
        # correlation: echo the caller's request id or mint one; join the
        # caller's trace when a well-formed traceparent came in
        req_id = (hdrs.get("x-request-id") or "").strip() or uuid.uuid4().hex
        remote = parse_traceparent(hdrs.get("traceparent", ""))
        self.registry.telemetry().record(f"{self.role} {method} {route}")
        recorder = self.registry.timeline_recorder()
        t0 = time.perf_counter()
        with self.registry.tracer().span(
            f"http.{method} {route}", remote_parent=remote, role=self.role
        ) as span:
            trace_id = (
                span.trace_id if span is not None else (remote[0] if remote else "")
            )
            # the request timeline is born INSIDE the server span so the
            # stage spans it emits at finish parent under it
            tl = (
                None
                if path in _TIMELINE_EXCLUDED
                else recorder.begin(
                    f"{method} {route}", trace_id=trace_id,
                    request_id=req_id, surface="http",
                    tenant=(hdrs.get("x-keto-tenant") or "").strip() or "default",
                )
            )
            with request_context(request_id=req_id, trace_id=trace_id):
                with recorder.activate(tl):
                    status, payload, resp_headers = self._route(
                        method, path, query, body, headers
                    )
                if span is not None:
                    span.tags["status"] = status
                    span.tags["request_id"] = req_id
                # access log INSIDE the bound context: the formatters
                # stamp request_id/trace_id onto the record, same ids as
                # the span and the response headers
                self._log.debug("%s %s %s -> %d", self.role, method, path, status)
        dur_s = time.perf_counter() - t0
        self._req_count.inc((self.role, method, route, str(status)))
        self._req_latency.observe((self.role, method, route), dur_s, trace_id=trace_id)
        resp_headers = dict(resp_headers)
        resp_headers.setdefault("X-Request-Id", req_id)
        if tl is not None:
            recorder.finish(
                tl, status=status,
                snaptoken=resp_headers.get("X-Keto-Snaptoken"),
            )
            # the caller-visible stage breakdown (W3C Server-Timing);
            # streaming responses (watch) carry no timing — the exchange
            # has no end
            if not isinstance(payload, StreamBody):
                resp_headers.setdefault(
                    "Server-Timing", recorder.server_timing(tl)
                )
        return status, payload, resp_headers

    def note_listener_shed(self, method: str, path: str) -> None:
        """Record a listener-level 429 (shed on the event loop before any
        handler ran) into the request metrics, so overload refusals stay
        visible per route."""
        self._req_count.inc((self.role, method, normalize_route(path), "429"))

    def _route(
        self,
        method: str,
        path: str,
        query: dict[str, list[str]],
        body: bytes,
        headers: Optional[dict[str, str]] = None,
    ):
        try:
            route = (method, path)
            if path == "/health/alive":
                return 200, {"status": "ok"}, {}
            if path == "/health/ready":
                return self._health_ready()
            if path == "/version":
                return 200, {"version": self.registry.version()}, {}
            if route == ("GET", "/metrics"):
                return self._get_metrics(headers)
            if route == ("GET", "/debug/requests"):
                return self._get_debug_requests(query)
            if route == ("GET", "/slo"):
                return self._get_slo()
            if route == ("GET", "/fleet"):
                return self._get_fleet()

            if self.role == READ:
                if route == ("GET", "/check"):
                    return self._get_check(query, headers)
                if route == ("POST", "/check"):
                    return self._post_check(body, query, headers)
                if route == ("POST", "/check/batch"):
                    return self._post_check_batch(body, query, headers)
                if route == ("GET", "/check/explain"):
                    return self._get_explain(query, headers)
                if route == ("GET", "/expand"):
                    return self._get_expand(query, headers)
                if route == ("GET", "/relation-tuples"):
                    return self._get_relation_tuples(query, headers)
                if route == ("GET", "/relation-tuples/list-objects"):
                    return self._get_list_objects(query, headers)
                if route == ("GET", "/relation-tuples/list-subjects"):
                    return self._get_list_subjects(query, headers)
                if route == ("GET", "/watch"):
                    return self._get_watch(query, headers)
                if route == ("GET", "/snapshot/export"):
                    return self._get_snapshot_export(query)
            else:
                if self.registry.is_replica() and method in (
                    "PUT", "DELETE", "PATCH",
                ):
                    # replicas hold no authority over the tuple log:
                    # every mutation surface refuses before dispatch
                    from keto_tpu.x.errors import ErrReplicaReadOnly

                    raise ErrReplicaReadOnly()
                if route == ("PUT", "/relation-tuples"):
                    return self._put_relation_tuple(body, headers)
                if route == ("DELETE", "/relation-tuples"):
                    return self._delete_relation_tuple(query, headers)
                if route == ("PATCH", "/relation-tuples"):
                    return self._patch_relation_tuples(body, headers)

            err = KetoError("404 page not found")
            err.status_code = 404
            return 404, err.to_json(), {}
        except KetoError as e:
            return e.status_code, e.to_json(), _error_headers(e)
        except Exception as e:  # unexpected → 500 envelope
            err = KetoError(str(e) or "internal server error")
            return 500, err.to_json(), {}

    # -- observability -------------------------------------------------------

    def _get_metrics(self, headers):
        """Prometheus text exposition of every registered family. A
        scraper negotiating ``Accept: application/openmetrics-text`` (the
        way Prometheus asks for exemplars) gets the OpenMetrics rendering
        with trace-id exemplars on the latency histograms. 404 when
        ``metrics.enabled: false``."""
        m = self.registry.metrics()
        if not m.enabled:
            err = KetoError("metrics disabled by configuration")
            err.status_code = 404
            return 404, err.to_json(), {}
        openmetrics = "application/openmetrics-text" in (headers or {}).get("accept", "")
        content_type = (
            "application/openmetrics-text; version=1.0.0; charset=utf-8"
            if openmetrics
            else "text/plain; version=0.0.4; charset=utf-8"
        )
        return 200, RawBody(m.render(openmetrics=openmetrics).encode(), content_type), {}

    @staticmethod
    def _int_param(query, key: str, default: int) -> int:
        raw = (query.get(key) or [""])[0]
        if not raw:
            return default
        try:
            return max(0, int(raw))
        except ValueError:
            raise ErrBadRequest(f"invalid {key} {raw!r}") from None

    def _get_debug_requests(self, query):
        """``GET /debug/requests`` — recent + top-K-slowest request
        timelines from the bounded ring (keto_tpu/x/timeline.py),
        filterable by ``?trace_id=``, ``?snaptoken=``, and ``?tenant=``
        (noisy-neighbor forensics: one tenant's requests, isolated);
        ``?n=`` / ``?slowest=`` bound the result sizes. On a replica the
        body also carries the per-commit replication timelines."""
        rec = self.registry.timeline_recorder()
        body = rec.snapshot(
            recent=self._int_param(query, "n", 50),
            slowest=self._int_param(query, "slowest", 20),
            trace_id=(query.get("trace_id") or [""])[0] or None,
            snaptoken=(query.get("snaptoken") or [""])[0] or None,
            tenant=(query.get("tenant") or [""])[0] or None,
        )
        rep = self.registry.replica_controller()
        if rep is not None:
            body["replication"] = rep.replication_timelines()
        return 200, body, {}

    def _get_slo(self):
        """``GET /slo`` — the SLO engine's multi-window availability and
        latency burn-rate report (keto_tpu/x/slo.py); the same numbers
        the ``keto_slo_*`` families expose at scrape time. The body also
        carries the fleet coordinates (epoch, primaryship, size, reshard
        state) so one poll answers both "how are we burning" and "who is
        serving"."""
        body = self.registry.slo_engine().to_json()
        self._add_fleet_health(body)
        return 200, body, {}

    def _get_fleet(self):
        """``GET /fleet`` — the fleet control plane's view of this node:
        lease epoch, role, membership with per-replica lag/watermark,
        the lag-aware routing weights the SDK steers reads by, plus the
        autoscaler and live-reshard snapshots. Answers on both ports
        (the SDK re-resolves the primary through ANY reachable member
        after a failover). 404 without ``serve.fleet_enabled``."""
        fleet = self.registry.fleet_controller()
        if fleet is None:
            err = KetoError("fleet control plane disabled by configuration")
            err.status_code = 404
            return 404, err.to_json(), {}
        body = fleet.snapshot()
        scaler = self.registry.peek("autoscaler")
        if scaler is not None:
            body["autoscaler"] = scaler.snapshot()
        # instantiating the coordinator is closure wiring, not an engine
        # build — peek() would hide the state machine until the first
        # reshard call
        reshard = self.registry.reshard_coordinator()
        if reshard is not None:
            body["reshard"] = reshard.snapshot()
        return 200, body, {}

    def _add_fleet_health(self, body: dict) -> None:
        """Fleet coordinates every readiness/SLO answer carries when the
        control plane runs: the fence epoch this node last observed,
        whether it is the serving primary, live membership size, and the
        reshard state machine's position. Probes and the SDK both read
        these without a second round trip."""
        fleet = self.registry.peek("fleet")
        if fleet is None:
            return
        snap = fleet.snapshot()
        reshard = self.registry.peek("reshard")
        body.update(
            {
                "epoch": int(snap.get("epoch", 0)),
                "is_primary": bool(snap.get("is_primary", False)),
                "fleet_size": int(snap.get("fleet_size", 0)),
                "reshard_state": (
                    reshard.snapshot()["state"] if reshard is not None else "idle"
                ),
            }
        )

    # -- snapshot export (replica bootstrap source) ---------------------------

    #: rows per ndjson chunk of the tuple export stream
    _EXPORT_CHUNK = 2048

    _CACHE_TAG_RE = re.compile(r"^v\d+-w\d+$")
    _SEGMENT_NAME_RE = re.compile(r"^[A-Za-z0-9._-]+$")

    def _get_snapshot_export(self, query):
        """``GET /snapshot/export`` — the replica bootstrap surface.

        - bare: manifest JSON ``{watermark, format, cache}`` where
          ``cache`` lists the newest current-format snapshot-cache
          directory's segments (or null) — replicas mirror the segments
          when the cache watermark matches the export watermark, and the
          probe loop polls this for the primary's watermark;
        - ``?stream=tuples``: chunked ndjson of the FULL tuple state at
          one consistent watermark — a header line ``{"watermark",
          "count"}`` then one ``{"relation_tuple"}`` line per tuple;
        - ``?cache=<tag>&segment=<name>``: raw bytes of one cache
          segment (names validated against the manifest grammar)."""
        store = self.registry.relation_tuple_manager()
        cache_dir = str(
            self.registry.config().get("serve.snapshot_cache_dir", "") or ""
        )
        tag = (query.get("cache") or [""])[0]
        seg = (query.get("segment") or [""])[0]
        if tag or seg:
            if not (tag and seg):
                raise ErrBadRequest(
                    "segment fetch needs both ?cache=<tag> and ?segment=<name>"
                )
            if not self._CACHE_TAG_RE.match(tag):
                raise ErrBadRequest(f"malformed cache tag {tag!r}")
            if not self._SEGMENT_NAME_RE.match(seg):
                raise ErrBadRequest(f"malformed segment name {seg!r}")
            from pathlib import Path

            from keto_tpu.x.errors import ErrNotFound

            path = Path(cache_dir) / tag / seg if cache_dir else None
            if path is None or not path.is_file():
                raise ErrNotFound(f"no cache segment {tag}/{seg}")
            return 200, RawBody(path.read_bytes(), "application/octet-stream"), {}
        stream = (query.get("stream") or [""])[0]
        if stream and stream != "tuples":
            raise ErrBadRequest(f"unknown export stream {stream!r}")
        if stream == "tuples":
            from keto_tpu.replica.store import row_to_tuple

            rows, wm = store.snapshot_rows()
            nm = self.registry.namespace_manager()

            def gen():
                head = json.dumps({"watermark": str(wm), "count": len(rows)})
                buf = [head]
                for row in rows:
                    buf.append(
                        json.dumps(
                            {"relation_tuple": row_to_tuple(nm, row).to_json()}
                        )
                    )
                    if len(buf) >= self._EXPORT_CHUNK:
                        yield ("\n".join(buf) + "\n").encode()
                        buf = []
                if buf:
                    yield ("\n".join(buf) + "\n").encode()

            return 200, StreamBody(gen()), {"X-Keto-Snaptoken": str(wm)}
        wm = store.watermark()
        cache = None
        if cache_dir:
            from keto_tpu.graph.snapcache import export_manifest

            cache = export_manifest(cache_dir, max_watermark=wm)
        return 200, {"watermark": str(wm), "format": 1, "cache": cache}, {}

    # -- health --------------------------------------------------------------

    def _health_ready(self):
        """Readiness from the health state machine: ready states answer
        200 (with the state surfaced so probes can alert on ``degraded``);
        NOT_SERVING answers 503 with the machine's reason — a k8s
        readiness probe pulls the pod from rotation while the snapshot is
        beyond its staleness budget, and puts it back when maintenance
        catches up."""
        from keto_tpu.driver.health import READY_STATES, HealthState

        monitor = self.registry.health_monitor()
        state, reason = monitor.status()
        if state not in READY_STATES:
            body = {"status": "unavailable", "reason": reason or state.value}
            self._add_replica_health(body)
            self._add_fleet_health(body)
            self._add_tenant_health(body)
            # backoff advice rides the 503: probes already poll on their
            # own period, but ad-hoc clients should not hammer a server
            # that just told them its snapshot is stale
            return 503, body, {"Retry-After": "1"}
        if state is HealthState.SERVING:
            body = {"status": "ok"}
            self._add_replica_health(body)
            self._add_fleet_health(body)
            self._add_tenant_health(body)
            return 200, body, {}
        body = {"status": state.value}
        if reason:
            body["reason"] = reason
        if state is HealthState.STARTING:
            # a multi-minute streaming build narrates itself: the body
            # carries {phase, pct} from the pipeline's progress tracker
            # instead of leaving probes staring at a bare state
            body.update(monitor.starting_detail())
        self._add_replica_health(body)
        self._add_fleet_health(body)
        self._add_tenant_health(body)
        return 200, body, {}

    def _add_tenant_health(self, body: dict) -> None:
        """Per-tenant health rides readiness WITHOUT flipping it: a
        ``DEGRADED(tenant=…)`` reason names the hurting tenant so its
        operator can act, while every other tenant's traffic — and the
        machine-level status the probes act on — stays untouched."""
        pool = self.registry.peek("tenants")
        if pool is None:
            return
        out = {
            "known": pool.known_count(),
            "resident": pool.resident_count(),
        }
        degraded = pool.degraded()
        if degraded:
            out["degraded"] = degraded
        body["tenants"] = out

    def _add_replica_health(self, body: dict) -> None:
        """On a replica, every readiness answer carries the replication
        picture: role, applied watermark, lag, and primary connectivity
        — the operator's one-glance view of a read-tier member."""
        rep = self.registry.replica_controller()
        if rep is None:
            return
        body.update(
            {
                "role": "replica",
                "watermark": str(rep.watermark),
                "lag_s": round(rep.lag_s(), 3),
                "primary_connected": rep.primary_connected,
            }
        )

    # -- tenancy --------------------------------------------------------------

    @staticmethod
    def _tenant_from(headers) -> str:
        """The validated tenant id the request addressed: the
        ``X-Keto-Tenant`` header, absent/blank → the default tenant;
        a malformed id is a 400."""
        from keto_tpu.driver.tenants import validate_tenant_id

        return validate_tenant_id((headers or {}).get("x-keto-tenant", ""))

    def _scope(self, headers):
        """The registry-shaped object serving this request: the registry
        itself for the default tenant (every pre-tenancy contract stays
        byte-identical), or the tenant's pool context — its own engine,
        batcher, store view, and watch hub — when ``X-Keto-Tenant``
        addresses another tenant. Tenant-scoped requests are primary-only
        (replicas mirror only the default tenant's state) and gated on
        ``serve.tenant_enabled``."""
        from keto_tpu.driver.tenants import DEFAULT_TENANT

        tenant = self._tenant_from(headers)
        if tenant == DEFAULT_TENANT:
            return self.registry
        if not bool(self.registry.config().get("serve.tenant_enabled", True)):
            raise ErrBadRequest(
                "multi-tenant serving is disabled (serve.tenant_enabled)"
            )
        if self.registry.is_replica():
            raise ErrBadRequest(
                "tenant-scoped requests are served by the primary only"
            )
        return self.registry.tenant_pool().get(tenant)

    # -- read ----------------------------------------------------------------

    @staticmethod
    def _deadline_from(query, headers) -> Optional[float]:
        """Request deadline as absolute ``time.monotonic()`` seconds, from
        ``X-Request-Timeout-Ms`` / ``?timeout_ms=`` (whichever is
        present; malformed values are a 400, not a silent default)."""
        raw = (query.get("timeout_ms") or [""])[0]
        if not raw and headers:
            raw = headers.get("x-request-timeout-ms", "")
        if not raw:
            return None
        try:
            ms = float(raw)
        except ValueError:
            raise ErrBadRequest(f"invalid timeout_ms {raw!r}") from None
        if ms <= 0:
            raise ErrBadRequest(f"timeout_ms must be > 0, got {raw!r}")
        return time.monotonic() + ms / 1e3

    @staticmethod
    def _lane_from(headers) -> Optional[str]:
        """The optional ``X-Keto-Priority`` lane hint (``interactive`` |
        ``batch``); absent → None (the batcher classifies by size),
        anything else is a 400."""
        raw = (headers or {}).get("x-keto-priority", "").strip().lower()
        if not raw:
            return None
        if raw in ("interactive", "batch"):
            return raw
        raise ErrBadRequest(
            f"invalid X-Keto-Priority {raw!r} (expected interactive|batch)"
        )

    @staticmethod
    def _consistency_from(query):
        """(at_least, latest) from ``?snaptoken=`` / ``?latest=`` — the
        REST face of the gRPC snaptoken/latest fields; default is the
        never-stalling serving mode."""
        raw_token = (query.get("snaptoken") or [""])[0]
        at_least = None
        if raw_token:
            try:
                at_least = int(raw_token)
            except ValueError:
                raise ErrBadRequest(f"malformed snaptoken {raw_token!r}") from None
        latest = (query.get("latest") or [""])[0].lower() in ("1", "true")
        return at_least, latest

    def _check(self, tuple_: RelationTuple, query, headers=None):
        scope = self._scope(headers)
        at_least, latest = self._consistency_from(query)
        # replica mode: admit the pin against the applied watermark
        # (block-then-412 above it), then try the Watch-invalidated
        # check cache before paying a device dispatch
        rep = scope.replica_controller()
        cache = rep.checkcache if rep is not None else None
        key = None
        if rep is not None:
            rep.gate_read(at_least, latest)
            if cache is not None:
                key = str(tuple_)
                got = cache.get(key, at_least)
                if got is not None:
                    allowed, token = got
                    from keto_tpu.x.timeline import current_timeline

                    tl = current_timeline()
                    if tl is not None:
                        tl.stamp("cache_hit")
                    dl = scope.decision_log()
                    if dl is not None and dl.sampled():
                        self._record_decision(dl, tuple_, allowed, token, headers)
                    return (
                        (200 if allowed else 403),
                        {"allowed": allowed},
                        {
                            "X-Keto-Snaptoken": str(token),
                            "X-Keto-Checkcache": "hit",
                        },
                    )
        allowed, token = scope.check_batcher().check_with_token(
            tuple_, at_least=at_least, latest=latest,
            deadline=self._deadline_from(query, headers),
            lane=self._lane_from(headers),
        )
        if cache is not None and key is not None:
            cache.put(key, allowed, token)
        # sampled decision-audit record: one None check when the log is
        # off, one RNG draw when on — witness-free either way (the
        # snaptoken makes the decision re-explainable later)
        dl = scope.decision_log()
        if dl is not None and dl.sampled():
            self._record_decision(dl, tuple_, allowed, token, headers)
        resp_headers = {} if token is None else {"X-Keto-Snaptoken": str(token)}
        return (200 if allowed else 403), {"allowed": allowed}, resp_headers

    def _record_decision(self, dl, tuple_, allowed, token, headers):
        """Append one hot-path check decision to the decision log
        (keto_tpu/explain/decision_log.py). The route is read off the
        request timeline's device stamp when timelines are on; "" when
        they are off — the record stays re-explainable either way."""
        from keto_tpu.x.timeline import current_timeline

        route = ""
        trace_id = ""
        tl = current_timeline()
        if tl is not None:
            # the trace id when a traceparent joined us; the always-minted
            # request id otherwise — the record stays correlatable
            trace_id = tl.trace_id or tl.request_id
            for stage, _t, attrs in reversed(tl.stamps):
                if stage == "device" and attrs and "route" in attrs:
                    route = str(attrs["route"])
                    break
                if stage == "cache_hit":
                    route = "cache"
                    break
        dl.record(
            self._tenant_from(headers),
            {
                "kind": "check",
                "tuple": tuple_.to_json(),
                "decision": bool(allowed),
                "route": route,
                "witness": None,
                "snaptoken": str(token) if token is not None else "",
                "trace_id": trace_id,
            },
        )

    def _get_explain(self, query, headers=None):
        """``GET /check/explain``: the Check decision plus its provenance
        — a Manager-verified witness path (grant) or frontier-exhaustion
        certificate (deny), the route that decided it, and the label
        route's winning landmark (docs/concepts/explain.md). Always 200
        (the body carries ``allowed``); same 400 tuple contract and
        412 replica snaptoken gate as ``/check``; 404 when
        ``serve.explain_enabled`` is false."""
        scope = self._scope(headers)
        if not bool(scope.config().get("serve.explain_enabled", True)):
            err = KetoError("explain disabled by configuration")
            err.status_code = 404
            return 404, err.to_json(), {}
        try:
            tuple_ = RelationTuple.from_url_query(query)
        except ErrNilSubject:
            raise ErrBadRequest("Subject has to be specified.") from None
        at_least, latest = self._consistency_from(query)
        rep = scope.replica_controller()
        if rep is not None:
            rep.gate_read(at_least, latest)
        from keto_tpu.x.timeline import current_timeline

        tl = current_timeline()
        resp = scope.explain_engine().explain(
            tuple_,
            at_least=at_least,
            trace_id=tl.trace_id if tl is not None else "",
            tenant=self._tenant_from(headers),
        )
        if tl is not None:
            tl.stamp(
                "explain",
                route=resp.get("route", ""),
                verified=bool(resp.get("verified")),
            )
        resp_headers = {}
        if resp.get("snaptoken"):
            resp_headers["X-Keto-Snaptoken"] = resp["snaptoken"]
        return 200, resp, resp_headers

    def _get_check(self, query, headers=None):
        try:
            tuple_ = RelationTuple.from_url_query(query)
        except ErrNilSubject:
            raise ErrBadRequest("Subject has to be specified.") from None
        return self._check(tuple_, query, headers)

    def _post_check(self, body: bytes, query, headers=None):
        try:
            obj = json.loads(body or b"{}")
        except json.JSONDecodeError as e:
            raise ErrBadRequest(f"Unable to decode JSON payload: {e}") from None
        return self._check(RelationTuple.from_json(obj), query, headers)

    def _post_check_batch(self, body: bytes, query, headers=None):
        """Many checks in one request: ``{"tuples": [...]}`` →
        ``{"results": [bool, ...]}`` in order. Large payloads classify
        into the batcher's BATCH lane (override with ``X-Keto-Priority``)
        and dispatch in bounded sub-slices, so they never convoy
        interactive checks; shed with 429 + Retry-After past the
        admission window."""
        scope = self._scope(headers)
        lane_hint = self._lane_from(headers)
        batcher = scope.check_batcher()
        if lane_hint != "interactive":
            # pre-parse shed: an over-window batch lane refuses BEFORE
            # paying the JSON decode — during a brownout the 429s must
            # cost microseconds or the parsing itself becomes the load
            batcher.admission_precheck()
        try:
            obj = json.loads(body or b"{}")
        except json.JSONDecodeError as e:
            raise ErrBadRequest(f"Unable to decode JSON payload: {e}") from None
        raw = obj.get("tuples") if isinstance(obj, dict) else None
        if not isinstance(raw, list) or not raw:
            raise ErrBadRequest('expected a non-empty "tuples" array')
        if len(raw) > MAX_BATCH_CHECK:
            raise ErrBadRequest(
                f"too many tuples in one batch check ({len(raw)} > "
                f"{MAX_BATCH_CHECK}); split the request"
            )
        tuples = [RelationTuple.from_json(t) for t in raw]
        at_least, latest = self._consistency_from(query)
        rep = scope.replica_controller()
        if rep is not None:
            rep.gate_read(at_least, latest)
        results, token = batcher.check_batch_with_token(
            tuples, at_least=at_least, latest=latest,
            deadline=self._deadline_from(query, headers),
            lane=lane_hint,
        )
        resp_headers = {} if token is None else {"X-Keto-Snaptoken": str(token)}
        return 200, {"results": [bool(r) for r in results]}, resp_headers

    def _get_expand(self, query, headers=None):
        # the reference parses max-depth unconditionally — absent/invalid
        # is a 400 (tests/test_rest_api.py asserts this). An explicit 0
        # means "use the configured limit.max_read_depth", matching the
        # gRPC path where 0 is the proto default for an omitted field.
        scope = self._scope(headers)
        raw_depth = (query.get("max-depth") or [""])[0]
        try:
            depth = int(raw_depth)
        except ValueError:
            raise ErrBadRequest(f"invalid max-depth {raw_depth!r}") from None
        subject = subject_set_from_url_query(query)
        rep = scope.replica_controller()
        if rep is not None:
            rep.gate_read(None)  # 503 until the first bootstrap lands
        from keto_tpu.servers.grpc_api import _expand_metrics
        from keto_tpu.x.timeline import current_timeline

        counter, latency = _expand_metrics(self.registry.metrics())
        eff_depth = scope.expand_depth(depth)
        t0 = time.perf_counter()
        tree = scope.expand_engine().build_tree(subject, eff_depth)
        dur_s = time.perf_counter() - t0
        counter.inc(("http",))
        latency.observe(("http",), dur_s)
        tl = current_timeline()
        if tl is not None:
            tl.stamp("expand", depth=eff_depth)
        if tree is None:
            return 200, None, {}
        return 200, tree.to_json(), {}

    def _get_relation_tuples(self, query, headers=None):
        scope = self._scope(headers)
        rq = RelationQuery.from_url_query(query)
        rep = scope.replica_controller()
        if rep is not None:
            rep.gate_read(None)  # 503 until the first bootstrap lands
        opts = []
        token = (query.get("page_token") or [""])[0]
        if token:
            opts.append(with_token(token))
        raw_size = (query.get("page_size") or [""])[0]
        if raw_size:
            try:
                opts.append(with_size(int(raw_size)))
            except ValueError:
                raise ErrBadRequest(f"invalid page_size {raw_size!r}") from None
        rels, next_page = scope.relation_tuple_manager().get_relation_tuples(rq, *opts)
        return (
            200,
            {
                "relation_tuples": [r.to_json() for r in rels],
                "next_page_token": next_page,
            },
            {},
        )

    # -- reverse queries (keto_tpu/list/) ------------------------------------

    @staticmethod
    def _page_opts(query) -> tuple[int, str]:
        """(page_size, page_token) from the query; malformed sizes are a
        400 like the tuple-listing endpoint's."""
        token = (query.get("page_token") or [""])[0]
        raw_size = (query.get("page_size") or [""])[0]
        size = 0
        if raw_size:
            try:
                size = int(raw_size)
            except ValueError:
                raise ErrBadRequest(f"invalid page_size {raw_size!r}") from None
            if size < 0:
                raise ErrBadRequest(f"page_size must be >= 0, got {raw_size!r}")
        return size, token

    def _get_list_objects(self, query, headers=None):
        """``GET /relation-tuples/list-objects`` — every object the
        subject can (transitively) access under namespace+relation, as a
        paginated, sorted result with a snaptoken-pinned page token."""
        rq = RelationQuery.from_url_query(query)
        if rq.namespace == "":
            raise ErrBadRequest("namespace has to be specified")
        if rq.relation == "":
            raise ErrBadRequest("relation has to be specified")
        sub = rq.subject
        if sub is None:
            raise ErrBadRequest("Subject has to be specified.")
        scope = self._scope(headers)
        at_least, latest = self._consistency_from(query)
        rep = scope.replica_controller()
        if rep is not None:
            rep.gate_read(at_least, latest)
        size, token = self._page_opts(query)
        objs, nxt, snaptoken = scope.list_engine().page_objects(
            rq.namespace, rq.relation, sub,
            page_size=size, page_token=token, at_least=at_least, latest=latest,
        )
        return (
            200,
            {"objects": objs, "next_page_token": nxt, "snaptoken": str(snaptoken)},
            {"X-Keto-Snaptoken": str(snaptoken)},
        )

    def _get_list_subjects(self, query, headers=None):
        """``GET /relation-tuples/list-subjects`` — every subject id
        (transitively) allowed on namespace:object#relation."""
        rq = RelationQuery.from_url_query(query)
        if rq.namespace == "":
            raise ErrBadRequest("namespace has to be specified")
        if rq.object == "":
            raise ErrBadRequest("object has to be specified")
        if rq.relation == "":
            raise ErrBadRequest("relation has to be specified")
        scope = self._scope(headers)
        at_least, latest = self._consistency_from(query)
        rep = scope.replica_controller()
        if rep is not None:
            rep.gate_read(at_least, latest)
        size, token = self._page_opts(query)
        subs, nxt, snaptoken = scope.list_engine().page_subjects(
            rq.namespace, rq.object, rq.relation,
            page_size=size, page_token=token, at_least=at_least, latest=latest,
        )
        return (
            200,
            {
                "subject_ids": subs,
                "next_page_token": nxt,
                "snaptoken": str(snaptoken),
            },
            {"X-Keto-Snaptoken": str(snaptoken)},
        )

    def _get_watch(self, query, headers=None):
        """``GET /watch?snaptoken=N`` — chunked ndjson changefeed: one
        line per committed transaction, ``{"snaptoken", "changes":
        [{"action", "relation_tuple"}]}``, resumable from any retained
        snaptoken (410 past the horizon), ended by server drain."""
        from keto_tpu.x.errors import ErrTooManyRequests

        hub = self._scope(headers).watch_hub()
        raw = (query.get("snaptoken") or [""])[0] or "0"
        try:
            since = int(raw)
        except ValueError:
            raise ErrBadRequest(f"malformed snaptoken {raw!r}") from None
        # validate the resume horizon BEFORE committing a 200: an expired
        # token must answer 410, not die mid-stream
        hub.changes_since(since)
        if not hub.try_acquire_stream():
            raise ErrTooManyRequests(
                "too many concurrent watch streams; retry with backoff",
                retry_after_s=1.0,
            )

        def gen():
            try:
                for token, changes in hub.subscribe(since, own_slot=False):
                    msg = hub.enrich_group(
                        token,
                        {
                            "snaptoken": str(token),
                            "changes": [
                                {"action": action, "relation_tuple": rt.to_json()}
                                for action, rt in changes
                            ],
                        },
                    )
                    yield (json.dumps(msg) + "\n").encode()
            finally:
                hub.release_stream()

        return 200, StreamBody(gen()), {}

    # -- write ---------------------------------------------------------------

    @staticmethod
    def _idempotency_key_from(headers) -> Optional[str]:
        """``X-Idempotency-Key`` on a write request opts into exactly-once
        semantics: retried keys replay the original response (snaptoken +
        ``X-Keto-Idempotent-Replay: true``) instead of re-applying."""
        if not headers:
            return None
        return headers.get("x-idempotency-key") or None

    def _note_commit(self, result, scope=None) -> None:
        """Register the committed transaction's trace context with the
        watch hub (replication-aware tracing): the commit group emitted
        at this snaptoken will carry the writer's traceparent, so one
        trace spans primary transact → watch emit → replica apply.
        Idempotent replays re-answer an OLD commit — never re-register."""
        if result is None or getattr(result, "replayed", False):
            return
        token = getattr(result, "snaptoken", None)
        if token is None:
            return
        try:
            (scope or self.registry).watch_hub().note_commit_trace(
                int(token), current_traceparent()
            )
        except Exception:
            # tracing enrichment must never fail a write
            self._log.debug("commit-trace registration failed", exc_info=True)

    @staticmethod
    def _write_headers(result) -> dict[str, str]:
        """Response headers for a write: the snaptoken the transaction
        committed at (pin follow-up checks with ``?snaptoken=``; the
        durability contract says an acknowledged token survives server
        death) and the replay marker on deduplicated retries."""
        if result is None:
            return {}
        out = {"X-Keto-Snaptoken": str(result.snaptoken)}
        if result.replayed:
            out["X-Keto-Idempotent-Replay"] = "true"
        return out

    def _put_relation_tuple(self, body: bytes, headers=None):
        try:
            obj = json.loads(body or b"{}")
        except json.JSONDecodeError as e:
            raise ErrBadRequest(str(e)) from None
        rel = RelationTuple.from_json(obj)
        # routed through the group-commit coordinator when enabled (one
        # durable transaction per batch of concurrent writers, same
        # per-writer snaptoken/replay semantics)
        scope = self._scope(headers)
        result = scope.transact_writes()(
            [rel], (), idempotency_key=self._idempotency_key_from(headers)
        )
        self._note_commit(result, scope)
        resp = {"Location": "/relation-tuples?" + rel.to_url_query()}
        resp.update(self._write_headers(result))
        return 201, rel.to_json(), resp

    def _delete_relation_tuple(self, query, headers=None):
        rel = RelationTuple.from_url_query(query)
        scope = self._scope(headers)
        result = scope.transact_writes()(
            (), [rel], idempotency_key=self._idempotency_key_from(headers)
        )
        self._note_commit(result, scope)
        return 204, None, self._write_headers(result)

    def _patch_relation_tuples(self, body: bytes, headers=None):
        try:
            deltas = json.loads(body or b"[]")
        except json.JSONDecodeError as e:
            raise ErrBadRequest(str(e)) from None
        if not isinstance(deltas, list):
            raise ErrBadRequest("expected a JSON array of patch deltas")
        insert, delete = [], []
        for d in deltas:
            raw = d.get("relation_tuple") if isinstance(d, dict) else None
            if raw is None:
                raise ErrBadRequest("relation_tuple is missing")
            action = d.get("action")
            if action == "insert":
                insert.append(RelationTuple.from_json(raw))
            elif action == "delete":
                delete.append(RelationTuple.from_json(raw))
            else:
                raise ErrBadRequest(f"unknown action {action}")
        scope = self._scope(headers)
        result = scope.transact_writes()(
            insert, delete, idempotency_key=self._idempotency_key_from(headers)
        )
        self._note_commit(result, scope)
        return 204, None, self._write_headers(result)


def _make_handler(app: RestApp):
    logger = app.registry.logger()

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        server_version = "keto-tpu"

        def _serve(self, method: str):
            # in-flight accounting for the SIGTERM drain: the exchange
            # counts until the response bytes are handed to the kernel
            with self.server.active_lock:
                self.server.active_count += 1
            try:
                parts = urlsplit(self.path)
                query = parse_qs(parts.query, keep_blank_values=True)
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                req_headers = {k.lower(): v for k, v in self.headers.items()}
                status, payload, headers = app.handle(
                    method, parts.path, query, body, req_headers
                )
                if isinstance(payload, StreamBody):
                    self._serve_stream(status, payload, headers)
                    return
                if isinstance(payload, RawBody):
                    data, content_type = payload.data, payload.content_type
                else:
                    data = b"" if payload is None else json.dumps(payload).encode()
                    content_type = "application/json"
                self.send_response(status)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(data)))
                for k, v in headers.items():
                    self.send_header(k, v)
                self.end_headers()
                if data:
                    self.wfile.write(data)
            finally:
                with self.server.active_lock:
                    self.server.active_count -= 1

        def _serve_stream(self, status: int, payload: StreamBody, headers) -> None:
            """Chunked transfer: frame each generator chunk, flush so
            subscribers see events as they commit, close on exhaustion
            (stream responses never keep-alive). A client disconnect
            closes the generator, releasing its watch slot."""
            self.send_response(status)
            self.send_header("Content-Type", payload.content_type)
            self.send_header("Transfer-Encoding", "chunked")
            for k, v in headers.items():
                self.send_header(k, v)
            self.send_header("Connection", "close")
            self.end_headers()
            chunks = payload.chunks
            try:
                for chunk in chunks:
                    if not chunk:
                        continue
                    self.wfile.write(b"%x\r\n" % len(chunk) + chunk + b"\r\n")
                    self.wfile.flush()
                self.wfile.write(b"0\r\n\r\n")
            except (BrokenPipeError, ConnectionResetError):
                pass  # subscriber went away; the finally releases the slot
            finally:
                close = getattr(chunks, "close", None)
                if close is not None:
                    close()
                self.close_connection = True

        def log_message(self, fmt, *args):  # per-request logging, health excluded
            if not self.path.startswith("/health/"):
                logger.debug("%s", fmt % args)

        def do_GET(self):
            self._serve("GET")

        def do_POST(self):
            self._serve("POST")

        def do_PUT(self):
            self._serve("PUT")

        def do_DELETE(self):
            self._serve("DELETE")

        def do_PATCH(self):
            self._serve("PATCH")

    return Handler


class RestServer:
    """One role's REST server on its own port, served from a thread."""

    def __init__(self, registry, role: str, host: str = "127.0.0.1", port: int = 0):
        self.app = RestApp(registry, role)
        self.httpd = ThreadingHTTPServer((host or "0.0.0.0", port), _make_handler(self.app))
        self.httpd.daemon_threads = True
        self.httpd.active_count = 0
        self.httpd.active_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def drain(self, timeout_s: float) -> bool:
        """Wait until every accepted request has had its response written
        (the SIGTERM drain seam). True when idle within ``timeout_s``."""
        deadline = time.monotonic() + max(0.0, timeout_s)
        while time.monotonic() < deadline:
            with self.httpd.active_lock:
                if self.httpd.active_count == 0:
                    return True
            time.sleep(0.01)
        with self.httpd.active_lock:
            return self.httpd.active_count == 0

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name=f"rest-{self.app.role}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
