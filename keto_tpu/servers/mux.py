"""Single-port gRPC + REST multiplexing by connection sniffing.

The reference multiplexes gRPC and REST on one TCP port with cmux, matching
HTTP/2 connections by their client preface (reference
internal/driver/daemon.go:87-159). Python's grpc and http.server stacks
cannot share a listener, so this module reproduces cmux's trick one level
down: a front listener accepts each connection, peeks the first bytes, and
splices the socket to a loopback backend — the gRPC server for connections
opening with the HTTP/2 client preface (``PRI * HTTP/2.0``), the REST
server otherwise. Splicing is two pump threads per connection; the peeked
bytes are replayed to the backend first.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Optional

_H2_PREFACE = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"


def _pump(src: socket.socket, dst: socket.socket) -> None:
    try:
        while True:
            data = src.recv(65536)
            if not data:
                break
            dst.sendall(data)
    except OSError:
        pass
    finally:
        for s, how in ((dst, socket.SHUT_WR), (src, socket.SHUT_RD)):
            try:
                s.shutdown(how)
            except OSError:
                pass


class PortMux:
    """Front listener splicing connections to REST / gRPC loopback backends."""

    def __init__(
        self, host: str, port: int, rest_port: int, grpc_port: int,
        max_connections: int = 256,
    ):
        self._listener = socket.create_server((host or "0.0.0.0", port), reuse_port=False)
        self._listener.settimeout(0.5)
        self.rest_port = rest_port
        self.grpc_port = grpc_port
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # bounds splice threads (2 per connection): beyond the cap, accepts
        # wait briefly then shed load instead of spawning without limit
        self._slots = threading.BoundedSemaphore(max_connections)

    @property
    def port(self) -> int:
        return self._listener.getsockname()[1]

    def start(self) -> None:
        self._thread = threading.Thread(target=self._accept_loop, name="portmux", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)
        self._listener.close()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            if not self._slots.acquire(blocking=False):
                conn.close()  # at capacity: shed immediately — a blocking
                continue      # wait here would stall accepts of other clients
            threading.Thread(target=self._handle, args=(conn,), daemon=True).start()

    def _handle(self, conn: socket.socket) -> None:
        try:
            self._splice(conn)
        finally:
            self._slots.release()

    def _splice(self, conn: socket.socket) -> None:
        try:
            # read (not peek) until the method token is unambiguous
            # ("PRI " = HTTP/2 client preface = gRPC; anything else =
            # HTTP/1 REST) — blocking reads under a deadline (not select():
            # fds ≥ FD_SETSIZE would raise); the consumed prefix is
            # replayed to the backend before splicing
            head = b""
            deadline = time.monotonic() + 10
            while len(head) < 4:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    conn.close()
                    return
                conn.settimeout(remaining)
                try:
                    data = conn.recv(4 - len(head))
                except socket.timeout:
                    conn.close()
                    return
                if not data:
                    conn.close()
                    return
                head += data
            conn.settimeout(None)
            backend_port = self.grpc_port if head == b"PRI " else self.rest_port
            backend = None
            try:
                backend = socket.create_connection(("127.0.0.1", backend_port))
                backend.sendall(head)
            except OSError:
                if backend is not None:
                    backend.close()
                raise
        except OSError:
            conn.close()
            return
        t = threading.Thread(target=_pump, args=(conn, backend), daemon=True)
        t.start()
        _pump(backend, conn)
        t.join()
