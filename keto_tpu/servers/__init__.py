"""API servers: REST + gRPC with the reference's read/write split.

Read API (default :4466): ``GET/POST /check``, ``GET /expand``,
``GET /relation-tuples``; write API (default :4467): ``PUT/DELETE/PATCH
/relation-tuples`` — routes, parameters, status codes, and error envelopes
match the reference handlers (reference internal/check/handler.go:41-52,
internal/expand/handler.go:40-42, internal/relationtuple/handler.go:41-49).
Both ports also speak gRPC, multiplexed by connection sniffing
(keto_tpu/servers/mux.py) the way the reference uses cmux (reference
internal/driver/daemon.go:93-97).
"""
