"""ctypes binding for the native epoll port multiplexer.

``native/mux.cpp`` implements the cmux analog (reference
internal/driver/daemon.go:87-159) as a single epoll loop — no
per-connection threads, proxy flow control, sniff deadline, connection
cap. Build with ``make native``; loading is opportunistic and callers
fall back to the Python thread-per-connection mux
(keto_tpu/servers/mux.py) when the shared object is absent or
``KETO_TPU_NATIVE=0``.
"""

from __future__ import annotations

import ctypes
import os
from pathlib import Path
from typing import Optional

_lib: Optional[ctypes.CDLL] = None
_lib_checked = False


def load_library() -> Optional[ctypes.CDLL]:
    global _lib, _lib_checked
    if _lib_checked:
        return _lib
    _lib_checked = True
    if os.environ.get("KETO_TPU_NATIVE", "1") == "0":
        return None
    path = Path(__file__).resolve().parents[2] / "native" / "libketomux.so"
    if os.environ.get("KETO_TPU_NATIVE_MUX_LIB"):
        path = Path(os.environ["KETO_TPU_NATIVE_MUX_LIB"])
    if not path.exists():
        return None
    try:
        lib = ctypes.CDLL(str(path))
    except OSError:
        return None
    lib.mux_start.restype = ctypes.c_void_p
    lib.mux_start.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
    ]
    lib.mux_port.restype = ctypes.c_int
    lib.mux_port.argtypes = [ctypes.c_void_p]
    lib.mux_stop.argtypes = [ctypes.c_void_p]
    _lib = lib
    return _lib


class NativePortMux:
    """Drop-in for keto_tpu.servers.mux.PortMux backed by the epoll loop."""

    def __init__(
        self, host: str, port: int, rest_port: int, grpc_port: int,
        max_connections: int = 4096,
    ):
        lib = load_library()
        if lib is None:
            raise RuntimeError("libketomux.so not available")
        self._lib = lib
        self._handle = lib.mux_start(
            (host or "0.0.0.0").encode(), port, rest_port, grpc_port, max_connections
        )
        if not self._handle:
            raise OSError(f"native mux failed to bind {host}:{port}")
        self.rest_port = rest_port
        self.grpc_port = grpc_port

    @property
    def port(self) -> int:
        if not self._handle:
            raise RuntimeError("native mux is stopped")
        return int(self._lib.mux_port(self._handle))

    def start(self) -> None:
        pass  # the epoll loop starts in mux_start

    def stop(self) -> None:
        if self._handle:
            self._lib.mux_stop(self._handle)
            self._handle = None


def make_port_mux(host: str, port: int, rest_port: int, grpc_port: int):
    """The native mux when available, else the Python fallback."""
    if load_library() is not None:
        try:
            return NativePortMux(host, port, rest_port, grpc_port)
        except OSError:
            raise  # bind errors are real; surface them
        except RuntimeError:
            pass
    from keto_tpu.servers.mux import PortMux

    return PortMux(host, port, rest_port, grpc_port)
