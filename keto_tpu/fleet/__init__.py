"""Self-driving serving fleet: failover, routing, autoscale, reshard.

The replica tier (keto_tpu/replica/) and the SLO engine (keto_tpu/x/slo.py)
observe the fleet; this package ACTS on what they observe:

- ``controller`` — lease-based primary election through the SQL store's
  fenced ``keto_fleet_lease`` epoch row: the primary renews, replicas
  watch, and on primary death the most-caught-up replica promotes itself
  with a durable-watermark handoff (no acked write lost, no split brain —
  a deposed primary's in-flight transacts abort with ErrFencedEpoch).
- ``lease`` — the pure election/routing math: promotion rank and the
  lag + latency route weights the SDK and the ``/fleet`` endpoint share.
- ``autoscale`` — the SLO-burn control loop: burn rate, replica lag,
  queue depth and HBM pressure in; grow/shrink decisions with hysteresis
  out, executed by the spawner or surfaced advisory-only.
- ``spawner`` — replica subprocess lifecycle (spawn, port discovery,
  retire), the productionized form of tests/chaos_runner.py's plumbing.
- ``reshard`` — live shard split/merge on the graph mesh axis: build the
  new-geometry engine while the old serves, then an atomic install; the
  412 read gate pins correctness across the swap.
"""

# Lazy re-exports (PEP 562): the SDK imports keto_tpu.fleet.lease at
# module load, and an eager package __init__ would drag the whole
# control plane (controller → supervise, spawner → subprocess) into
# every client process — and can deadlock when two threads import
# different submodules concurrently. Submodules import each other
# directly; the package root only resolves names on demand.
_EXPORTS = {
    "Autoscaler": ("keto_tpu.fleet.autoscale", "Autoscaler"),
    "FleetController": ("keto_tpu.fleet.controller", "FleetController"),
    "ReplicaSpawner": ("keto_tpu.fleet.spawner", "ReplicaSpawner"),
    "ReshardCoordinator": ("keto_tpu.fleet.reshard", "ReshardCoordinator"),
    "SpawnedReplica": ("keto_tpu.fleet.spawner", "SpawnedReplica"),
    "promotion_rank": ("keto_tpu.fleet.lease", "promotion_rank"),
    "route_weight": ("keto_tpu.fleet.lease", "route_weight"),
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    try:
        module, attr = _EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module 'keto_tpu.fleet' has no attribute {name!r}"
        ) from None
    import importlib

    return getattr(importlib.import_module(module), attr)
