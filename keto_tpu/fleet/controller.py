"""FleetController: lease-based primary election with epoch fencing.

One supervised heartbeat loop per process drives the whole election
protocol through the shared SQL store (the fleet's only coordination
medium — no extra quorum service):

- **Primary**: heartbeat membership, then renew the lease
  (``lease-renew`` kill point sits before the renewing UPDATE — a kill
  there is a primary dying between heartbeats). A failed renewal means
  the epoch moved: try one re-acquire (clock hiccup, nobody took over),
  else mark this process DEPOSED — the fence stays at the old epoch, so
  every in-flight and future transact aborts with ErrFencedEpoch (409).
  No split brain: the fence check runs inside the write transaction,
  serialized against the usurper's epoch bump by the watermark row lock.
- **Replica**: heartbeat membership (applied watermark + lag feed the
  promotion rank and the /fleet routing weights), then watch the lease.
  When it expires, wait ``promotion_grace_s × rank`` (most-caught-up
  replica moves first), then race the CAS — exactly one contender wins
  the new epoch. The winner passes ``promote-install`` (kill point:
  epoch durably taken, promoted store not yet installed — recovery must
  be exactly-once) and runs ``on_promote(epoch)``: the registry swaps
  the replica's store for a direct SQL store at the SAME durable
  watermark the replica already applied (the device snapshot stays
  valid — that is the durable-watermark handoff) and arms the fence.
  Writes resume in under a lease TTL + grace, no acked write lost.

The controller never blocks the serving path: reads/writes consult only
its cheap in-memory flags (``is_primary``, ``deposed``, ``epoch``)."""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

from keto_tpu.fleet.lease import lease_standing, promotion_rank, route_weights
from keto_tpu.x import faults
from keto_tpu.x.supervise import SupervisedTask

_log = logging.getLogger("keto_tpu.fleet")


class FleetController:
    def __init__(
        self,
        lease_store,
        node_id: str,
        *,
        advertise_url: str = "",
        role: str = "primary",
        lease_ttl_s: float = 2.0,
        heartbeat_s: float = 0.5,
        promotion_grace_s: float = 0.5,
        lag_budget_s: float = 30.0,
        watermark_fn: Optional[Callable[[], int]] = None,
        lag_fn: Optional[Callable[[], float]] = None,
        on_promote: Optional[Callable[[int], None]] = None,
        on_deposed: Optional[Callable[[], None]] = None,
        fence_fn: Optional[Callable[[Optional[int]], None]] = None,
        stats=None,
    ):
        """``lease_store`` is anything with the fleet_* persister API
        (a dedicated SQL connection — replicas keep NO tuple-store SQL
        access; this is their one lease-only channel). ``fence_fn``
        installs the fencing epoch on the TUPLE store (primary only);
        ``on_promote`` performs the store handoff when this node wins."""
        self._store = lease_store
        self.node_id = node_id
        self.advertise_url = advertise_url.rstrip("/")
        self.role = role
        self.lease_ttl_s = float(lease_ttl_s)
        self.heartbeat_s = max(0.05, float(heartbeat_s))
        self.promotion_grace_s = max(0.0, float(promotion_grace_s))
        self.lag_budget_s = float(lag_budget_s)
        self._watermark_fn = watermark_fn or (lambda: 0)
        self._lag_fn = lag_fn or (lambda: 0.0)
        self._on_promote = on_promote
        self._on_deposed = on_deposed
        self._fence_fn = fence_fn
        self._lock = threading.Lock()  # guards: epoch, role, deposed, _members
        self.epoch = 0
        self.deposed = False
        self.promotions = 0
        self.promotions_by_reason: dict[str, int] = {}
        self.renew_failures = 0
        self._members: list[dict] = []
        self._lease_lost_at: Optional[float] = None
        self._stop = threading.Event()
        self._task = SupervisedTask(
            "fleet-heartbeat", self._run, stats=stats,
        )

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._task.kick()

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        self._task.stop(timeout=timeout)

    def alive(self) -> bool:
        return self._task.alive()

    # -- serving-path read surface (cheap flags, no SQL) ---------------------

    @property
    def is_primary(self) -> bool:
        with self._lock:
            return self.role == "primary" and not self.deposed

    def members(self) -> list[dict]:
        with self._lock:
            return list(self._members)

    def fleet_size(self) -> int:
        with self._lock:
            return len(self._members)

    def snapshot(self) -> dict:
        """Operator/metrics/SDK view — the /fleet body's fleet section."""
        with self._lock:
            members = list(self._members)
            return {
                "node_id": self.node_id,
                "role": "deposed" if self.deposed else self.role,
                "epoch": self.epoch,
                "is_primary": self.role == "primary" and not self.deposed,
                "deposed": self.deposed,
                "fleet_size": len(members),
                "members": members,
                "promotions": self.promotions,
                "promotions_by_reason": dict(self.promotions_by_reason),
                "renew_failures": self.renew_failures,
                "route_weights": route_weights(members, self.lag_budget_s),
                "lease_ttl_s": self.lease_ttl_s,
            }

    # -- the heartbeat loop --------------------------------------------------

    def _run(self) -> None:
        """One supervised-loop lifetime: tick until stop; exceptions
        raise into the supervisor's jittered-backoff retry."""
        while not self._stop.is_set():
            self.tick()
            if self._stop.wait(timeout=self.heartbeat_s):
                return

    def tick(self, now: Optional[float] = None) -> None:
        """One protocol step (public so tests drive the state machine
        with a synthetic clock)."""
        t = time.time() if now is None else now
        with self._lock:
            role, deposed, epoch = self.role, self.deposed, self.epoch
        self._store.fleet_heartbeat(
            self.node_id,
            self.advertise_url,
            "deposed" if deposed else role,
            self._watermark_fn(),
            self._lag_fn(),
            now=t,
        )
        # members age out at 3 heartbeats + slack: a SIGKILL'd node
        # drops from fleet_size (and the promotion rank) within ~2 s
        members = self._store.fleet_members(
            max_age_s=3 * self.heartbeat_s + 1.0, now=t
        )
        with self._lock:
            self._members = members
        if deposed:
            return  # fenced: heartbeat only, never contend again
        if role == "primary":
            self._primary_tick(t, epoch)
        else:
            self._replica_tick(t)

    def _primary_tick(self, now: float, epoch: int) -> None:
        faults.check("lease-renew")
        if self._store.fleet_lease_renew(
            self.node_id, epoch, self.lease_ttl_s, now=now
        ):
            return
        # epoch moved under us (or first tick, epoch still 0): one
        # re-acquire attempt — succeeds on boot and after a clock
        # hiccup nobody exploited, fails when a replica took over
        got = self._store.fleet_lease_acquire(
            self.node_id, self.lease_ttl_s, now=now
        )
        if got is not None:
            self._install_epoch(got)
            if epoch:
                self.renew_failures += 1
            return
        self.renew_failures += 1
        self._depose()

    def _replica_tick(self, now: float) -> None:
        lease = self._store.fleet_lease()
        if lease is not None:
            with self._lock:
                self.epoch = int(lease["epoch"])
        if lease is not None and lease.get("holder") == self.node_id:
            # we already hold the lease but never finished installing
            # (crash-retry after a failed on_promote): finish it now —
            # exactly-once per epoch, because the epoch is already ours
            self._promote(int(lease["epoch"]), reason="install-retry")
            return
        if lease_standing(lease, now):
            self._lease_lost_at = None
            return
        if self._lease_lost_at is None:
            self._lease_lost_at = now
        # rank-staggered contention: the most-caught-up replica moves
        # first; ties and stale ranks are harmless (the CAS picks one)
        rank = promotion_rank(self.members(), self.node_id)
        if now - self._lease_lost_at < self.promotion_grace_s * rank:
            return
        got = self._store.fleet_lease_acquire(
            self.node_id, self.lease_ttl_s, now=now
        )
        if got is None:
            return  # lost the race; the winner's lease shows next tick
        self._promote(got, reason="lease-expired")

    def _promote(self, epoch: int, reason: str) -> None:
        # epoch durably taken; a kill here (promote-install) must leave
        # recovery exactly-once — it is: the epoch stays ours, the next
        # tick's holder==me branch retries the install, and no other
        # contender can win THIS epoch
        faults.check("promote-install")
        if self._on_promote is not None:
            self._on_promote(int(epoch))
        with self._lock:
            self.role = "primary"
            self._lease_lost_at = None
            self.promotions += 1
            self.promotions_by_reason[reason] = (
                self.promotions_by_reason.get(reason, 0) + 1
            )
        self._install_epoch(epoch)
        _log.warning(
            "promoted to primary at epoch %d (%s)", epoch, reason
        )

    def _install_epoch(self, epoch: int) -> None:
        with self._lock:
            self.epoch = int(epoch)
        if self._fence_fn is not None:
            self._fence_fn(int(epoch))

    def _depose(self) -> None:
        with self._lock:
            if self.deposed:
                return
            self.deposed = True
        # the fence is NOT advanced: it stays at the old epoch, so every
        # in-flight and future transact on this process aborts with
        # ErrFencedEpoch — the usurper's history is the only history
        _log.error(
            "deposed: fleet lease epoch moved past ours (%d); writes are "
            "fenced, reads keep serving stale", self.epoch,
        )
        if self._on_deposed is not None:
            try:
                self._on_deposed()
            except Exception:
                _log.warning("on_deposed callback failed", exc_info=True)


__all__ = ["FleetController"]
