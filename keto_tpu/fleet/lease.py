"""Pure election and routing math, shared by server and SDK.

Everything here is a plain function over member dicts (the rows
``fleet_members`` returns / the ``/fleet`` body carries) so the
controller, the REST surface, the httpclient, and the unit tests all
compute the same ranks and weights from the same inputs.
"""

from __future__ import annotations

from typing import Optional, Sequence

#: latency floor for route weights: keeps 1/latency finite and stops a
#: replica with one lucky fast sample from absorbing all traffic
LATENCY_FLOOR_S = 0.001


def promotion_rank(members: Sequence[dict], node_id: str) -> int:
    """This node's position in the promotion order: replicas ranked
    most-caught-up first (highest applied watermark; node_id breaks
    ties deterministically so every observer agrees). Rank 0 promotes
    immediately on lease expiry; rank k waits k grace periods — the
    stagger that keeps N contenders from storming the CAS at once
    (exactly one would win anyway; the stagger just makes the winner
    the most-caught-up one in the common case). Nodes not present (or
    not replicas) rank after everyone."""
    replicas = sorted(
        (m for m in members if m.get("role") == "replica"),
        key=lambda m: (-int(m.get("watermark", 0)), str(m.get("node_id", ""))),
    )
    for i, m in enumerate(replicas):
        if m.get("node_id") == node_id:
            return i
    return len(replicas)


def lease_standing(lease: Optional[dict], now: float) -> bool:
    """Whether a live (unexpired, held) lease stands."""
    return (
        lease is not None
        and bool(lease.get("holder"))
        and float(lease.get("expires_at", 0.0)) > now
    )


def route_weight(
    lag_s: float,
    lag_budget_s: float,
    latency_s: float = 0.0,
    latency_floor_s: float = LATENCY_FLOOR_S,
) -> float:
    """Read-routing weight for one replica: 0 once its replication lag
    reaches the budget (drain it BEFORE the 412 gate starts firing),
    otherwise a lag-discounted inverse of its latency EWMA — fresher
    and faster replicas absorb proportionally more reads than blind
    round-robin would give them."""
    lag_s = max(0.0, float(lag_s))
    if lag_budget_s > 0 and lag_s >= lag_budget_s:
        return 0.0
    lag_factor = 1.0 - (lag_s / lag_budget_s if lag_budget_s > 0 else 0.0)
    return max(0.0, lag_factor) / (max(0.0, float(latency_s)) + latency_floor_s)


def route_weights(
    members: Sequence[dict],
    lag_budget_s: float,
    latency_ewma_s: Optional[dict] = None,
) -> dict[str, float]:
    """Per-replica weights over a membership listing. ``latency_ewma_s``
    maps node_id (or url) to the caller's observed latency EWMA; absent
    entries weigh by lag alone (the server's /fleet view has no client
    latencies)."""
    ewma = latency_ewma_s or {}
    out: dict[str, float] = {}
    for m in members:
        if m.get("role") != "replica":
            continue
        nid = str(m.get("node_id", ""))
        lat = ewma.get(nid, ewma.get(str(m.get("url", "")), 0.0))
        out[nid] = route_weight(
            float(m.get("lag_s", 0.0)), lag_budget_s, float(lat or 0.0)
        )
    return out


__all__ = [
    "LATENCY_FLOOR_S",
    "lease_standing",
    "promotion_rank",
    "route_weight",
    "route_weights",
]
