"""SLO-burn autoscaler: the control loop that closes PR 14's observations.

Signals in (a dict the registry assembles from live components — no
component learns about the autoscaler):

- ``availability_burn_rate`` / ``latency_burn_rate`` — the SLO engine's
  fast-window burn rates; sustained > 1 means the error budget is being
  spent faster than the objective allows.
- ``lag_s``        — replica replication lag (a saturated primary starves
  its own changefeed before it starts failing requests).
- ``queue_depth``  — the check batcher's unpacked backlog, normalized by
  its shed ceiling (sustained near 1.0 = sheds are imminent).
- ``hbm_rung``     — the HBM governor's eviction-ladder depth (capacity
  pressure of a different kind: more replicas spread read load, they do
  not shrink a snapshot — rung pressure only VETOES shrinking).

Decisions out, with hysteresis in both directions:

- **grow** when overload (any burn > 1, or queue saturation) has held
  CONTINUOUSLY for ``sustain_s`` — a one-scrape spike never spawns.
- **shrink** when everything has been calm for ``quiet_s`` AND the last
  action is at least ``cooldown_s`` old — a 10× diurnal swell ramps up
  without oscillating on the way down.
- after ANY action, ``cooldown_s`` must pass before the next — the loop
  never outruns a replica's bootstrap.

The pure decision core (``decide``) takes an explicit clock so the
hysteresis regression tests replay synthetic timelines without
sleeping. Wired to a ``ReplicaSpawner`` it acts; with ``spawner=None``
it runs advisory-only (decisions surface on /fleet and the
``keto_fleet_replicas`` metric, nothing spawns — the safe default for
a daemon whose operator did not hand it a replica argv)."""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

_log = logging.getLogger("keto_tpu.fleet")


class Autoscaler:
    def __init__(
        self,
        signals_fn: Callable[[], dict],
        *,
        spawner=None,
        min_replicas: int = 0,
        max_replicas: int = 4,
        sustain_s: float = 5.0,
        cooldown_s: float = 30.0,
        quiet_s: Optional[float] = None,
        burn_threshold: float = 1.0,
        queue_threshold: float = 0.8,
    ):
        self._signals_fn = signals_fn
        self.spawner = spawner
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.sustain_s = float(sustain_s)
        self.cooldown_s = float(cooldown_s)
        # calm must hold notably longer than overload before shrinking:
        # asymmetric hysteresis is what stops spawn/retire oscillation
        self.quiet_s = float(quiet_s) if quiet_s is not None else 4.0 * float(sustain_s)
        self.burn_threshold = float(burn_threshold)
        self.queue_threshold = float(queue_threshold)
        self._lock = threading.Lock()
        self._overload_since: Optional[float] = None
        self._calm_since: Optional[float] = None
        self._last_action_at: Optional[float] = None
        #: advisory-mode replica count (acts as the virtual fleet size
        #: when no spawner is attached; tests drive it directly)
        self.advised = int(min_replicas)
        self.grow_actions = 0
        self.shrink_actions = 0
        self.last_decision = "hold"
        self.last_signals: dict = {}
        self._task = None
        self._stop_evt = threading.Event()

    # -- the pure decision core ----------------------------------------------

    def _overloaded(self, s: dict) -> bool:
        burn = max(
            float(s.get("availability_burn_rate", 0.0) or 0.0),
            float(s.get("latency_burn_rate", 0.0) or 0.0),
        )
        queue = float(s.get("queue_depth_ratio", 0.0) or 0.0)
        return burn > self.burn_threshold or queue >= self.queue_threshold

    def _calm(self, s: dict) -> bool:
        burn = max(
            float(s.get("availability_burn_rate", 0.0) or 0.0),
            float(s.get("latency_burn_rate", 0.0) or 0.0),
        )
        queue = float(s.get("queue_depth_ratio", 0.0) or 0.0)
        # stricter than "not overloaded": shrink only well inside budget
        return burn <= 0.5 * self.burn_threshold and queue < 0.5 * self.queue_threshold

    def decide(self, signals: dict, now: float, current: int) -> str:
        """'grow' / 'shrink' / 'hold' for one step — pure over
        (signals, clock, fleet size); all hysteresis state lives on
        self and advances deterministically with the supplied clock."""
        with self._lock:
            overloaded = self._overloaded(signals)
            calm = self._calm(signals)
            if overloaded:
                if self._overload_since is None:
                    self._overload_since = now
                self._calm_since = None
            elif calm:
                if self._calm_since is None:
                    self._calm_since = now
                self._overload_since = None
            else:
                # the dead band between grow and shrink pressure: reset
                # BOTH timers — neither action may accumulate toward
                # firing while the signal is ambiguous
                self._overload_since = None
                self._calm_since = None
            cooling = (
                self._last_action_at is not None
                and now - self._last_action_at < self.cooldown_s
            )
            if (
                overloaded
                and not cooling
                and current < self.max_replicas
                and now - self._overload_since >= self.sustain_s
            ):
                self._last_action_at = now
                self._overload_since = None
                return "grow"
            if (
                calm
                and not cooling
                and current > self.min_replicas
                # HBM pressure vetoes shrink: fewer replicas concentrate
                # read load onto processes already shedding residency
                and int(signals.get("hbm_rung", 0) or 0) == 0
                and now - self._calm_since >= self.quiet_s
            ):
                self._last_action_at = now
                self._calm_since = None
                return "shrink"
            return "hold"

    # -- the acting step -----------------------------------------------------

    def current(self) -> int:
        if self.spawner is not None:
            return self.spawner.count()
        return self.advised

    def step(self, now: Optional[float] = None) -> str:
        """One control-loop pass: read signals, decide, act. Returns
        the decision (the supervised fleet loop calls this; the smoke
        harness and tests call it directly)."""
        t = time.time() if now is None else now
        signals = self._signals_fn()
        self.last_signals = signals
        decision = self.decide(signals, t, self.current())
        self.last_decision = decision
        if decision == "grow":
            self.grow_actions += 1
            if self.spawner is not None:
                self.spawner.spawn()
            else:
                self.advised += 1
            _log.warning(
                "autoscale grow -> %d replicas (burn=%.2f/%.2f queue=%.2f)",
                self.current(),
                float(signals.get("availability_burn_rate", 0) or 0),
                float(signals.get("latency_burn_rate", 0) or 0),
                float(signals.get("queue_depth_ratio", 0) or 0),
            )
        elif decision == "shrink":
            self.shrink_actions += 1
            if self.spawner is not None:
                self.spawner.retire_one()
            else:
                self.advised = max(self.min_replicas, self.advised - 1)
            _log.info("autoscale shrink -> %d replicas", self.current())
        return decision

    def start(self, period_s: float = 1.0) -> None:
        """Run the control loop supervised (crashes restart with
        backoff, like every other background loop in the daemon)."""
        from keto_tpu.x.supervise import SupervisedTask

        if self._task is not None:
            return
        self._stop_evt.clear()

        def run():
            while not self._stop_evt.is_set():
                self.step()
                self._stop_evt.wait(timeout=period_s)

        self._task = SupervisedTask("fleet-autoscale", run)
        self._task.kick()

    def stop(self, timeout: float = 2.0) -> None:
        if self._task is None:
            return
        self._stop_evt.set()
        self._task.stop(timeout)
        self._task = None

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "replicas": self.current(),
                "min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas,
                "grow_actions": self.grow_actions,
                "shrink_actions": self.shrink_actions,
                "last_decision": self.last_decision,
                "advisory": self.spawner is None,
                "signals": dict(self.last_signals),
            }


__all__ = ["Autoscaler"]
