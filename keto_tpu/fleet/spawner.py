"""Replica subprocess lifecycle: the autoscaler's actuator.

The chaos harness (tests/chaos_runner.py + tests/test_chaos.py) grew
battle-tested process plumbing — spawn a serving daemon, discover its
ports through an atomically renamed port file, retire it with a drain
grace before escalating to SIGKILL. This module is that plumbing
productionized: the autoscale loop calls ``spawn()``/``retire()`` and
never touches Popen directly, and the fleet smoke harness drives the
same code the control plane runs.

The spawner is deliberately argv-agnostic: the caller provides
``argv_fn(index, port_file) -> list[str]`` building the replica's
command line (pointing ``--port-file`` at the handed path), plus the
env. Nothing here knows about config schemas or roles — that keeps the
same spawner usable by the daemon's autoscaler, the smoke script, and
the tests without three forks of Popen handling."""

from __future__ import annotations

import json
import logging
import os
import signal
import subprocess
import time
from pathlib import Path
from typing import Callable, Optional

_log = logging.getLogger("keto_tpu.fleet")


class SpawnedReplica:
    """One spawned serving subprocess plus its published ports."""

    def __init__(self, index: int, proc, port_file: Path, log_file=None):
        self.index = index
        self.proc = proc
        self.port_file = port_file
        self._log_file = log_file
        self.ports: Optional[dict] = None
        self.spawned_at = time.monotonic()

    @property
    def pid(self) -> int:
        return self.proc.pid

    def alive(self) -> bool:
        return self.proc.poll() is None

    def wait_ports(self, timeout: float = 90.0) -> Optional[dict]:
        """Ports once the daemon published them (atomic rename → a
        half-written file only ever loses the race once), or None when
        the process died first."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.port_file.is_file():
                try:
                    self.ports = json.loads(self.port_file.read_text())
                except json.JSONDecodeError:
                    pass  # mid-rename race; retry
                else:
                    return self.ports
            if self.proc.poll() is not None:
                return None
            time.sleep(0.05)
        return None

    def url(self) -> str:
        if not self.ports:
            return ""
        return f"http://127.0.0.1:{self.ports['read']}"

    def terminate(self, grace_s: float = 10.0) -> int:
        """Drain-retire: SIGTERM (the daemon's drain path finishes
        in-flight work and exits 0), escalating to SIGKILL past the
        grace. Returns the exit status."""
        if self.proc.poll() is None:
            try:
                self.proc.send_signal(signal.SIGTERM)
            except ProcessLookupError:
                pass
            try:
                self.proc.wait(timeout=grace_s)
            except subprocess.TimeoutExpired:
                _log.warning(
                    "replica %d did not drain within %.1fs; SIGKILL",
                    self.index, grace_s,
                )
                self.kill()
        status = self._wait_reaped()
        self._close()
        return status

    def kill(self) -> None:
        if self.proc.poll() is None:
            try:
                self.proc.kill()
            except ProcessLookupError:
                pass
        self._wait_reaped()
        self._close()

    def _wait_reaped(self) -> int:
        """Reap the (already signalled) child. Bounded + looped rather
        than a bare wait(): a SIGKILL'd process can only linger as an
        unreaped zombie, and a missed wakeup must not park the control
        loop forever."""
        while True:
            try:
                return self.proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                _log.warning(
                    "replica %d (pid %d) not reaped yet; waiting",
                    self.index, self.proc.pid,
                )

    def _close(self) -> None:
        if self._log_file is not None:
            try:
                self._log_file.close()
            except Exception:
                _log.warning(
                    "replica %d log file close failed", self.index,
                    exc_info=True,
                )
            self._log_file = None


class ReplicaSpawner:
    def __init__(
        self,
        argv_fn: Callable[[int, Path], list],
        workdir: str,
        *,
        env: Optional[dict] = None,
        drain_grace_s: float = 10.0,
    ):
        """``argv_fn(index, port_file)`` builds one replica's command
        line; ``workdir`` holds port files and per-process logs."""
        self._argv_fn = argv_fn
        self._workdir = Path(workdir)
        self._env = env
        self.drain_grace_s = float(drain_grace_s)
        self._next_index = 0
        self.children: list[SpawnedReplica] = []
        self.spawned_total = 0
        self.retired_total = 0

    def alive_children(self) -> list[SpawnedReplica]:
        self.children = [c for c in self.children if c.alive()]
        return self.children

    def count(self) -> int:
        return len(self.alive_children())

    def spawn(self) -> SpawnedReplica:
        self._workdir.mkdir(parents=True, exist_ok=True)
        idx = self._next_index
        self._next_index += 1
        port_file = self._workdir / f"replica-{idx}-ports.json"
        port_file.unlink(missing_ok=True)
        log_path = self._workdir / f"replica-{idx}.log"
        log_file = open(log_path, "ab")
        env = dict(self._env if self._env is not None else os.environ)
        proc = subprocess.Popen(
            [str(a) for a in self._argv_fn(idx, port_file)],
            env=env,
            stdout=log_file,
            stderr=log_file,
        )
        child = SpawnedReplica(idx, proc, port_file, log_file)
        self.children.append(child)
        self.spawned_total += 1
        _log.info("spawned replica %d (pid %d)", idx, proc.pid)
        return child

    def retire_one(self) -> Optional[SpawnedReplica]:
        """Drain-retire the youngest replica (spawn order is retire
        order reversed: the longest-lived replica has the warmest
        caches and the most applied history — keep it)."""
        live = self.alive_children()
        if not live:
            return None
        child = live[-1]
        child.terminate(grace_s=self.drain_grace_s)
        self.children.remove(child)
        self.retired_total += 1
        _log.info("retired replica %d (pid %d)", child.index, child.pid)
        return child

    def stop_all(self, grace_s: Optional[float] = None) -> None:
        for child in list(self.children):
            child.terminate(
                grace_s=self.drain_grace_s if grace_s is None else grace_s
            )
        self.children.clear()


__all__ = ["ReplicaSpawner", "SpawnedReplica"]
