"""Live resharding: shard split/merge on the graph mesh axis under traffic.

The mechanism is **engine substitution**, not in-place mutation: the
coordinator builds a COMPLETE second check engine at the target mesh
geometry (its own device snapshot over the same store, sharded on the
new axis, reusing snapcache v6's per-shard stripes where the geometry
matches) while the old engine keeps serving every request. Only when
the new engine has a live snapshot does the atomic install swap it into
the registry singleton and the check batcher — one reference assignment
each, no request ever observes a half-resharded engine.

Correctness across the swap comes from the store, not the geometry:
both engines answer bit-identically at any snaptoken because both
derive from the same watermark-ordered tuple history, and the 412 read
gate pins a caller's snaptoken exactly as before. A kill between build
and install (the ``reshard-handoff`` point) leaves the old geometry
serving — zero wrong answers by construction, proven by the chaos suite
and the fleet smoke's 3-way parity sweep.

States (the ``keto_reshard_state`` metric's code space):

    idle(0) → preparing(1) → handoff(2) → idle(0)
                    └──────────→ failed(3) → idle on the next attempt
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

from keto_tpu.x import faults

_log = logging.getLogger("keto_tpu.fleet")

#: reshard state machine → the keto_reshard_state gauge's code space
STATE_CODES = {"idle": 0, "preparing": 1, "handoff": 2, "failed": 3}


class ReshardCoordinator:
    def __init__(
        self,
        build_fn: Callable[[int], object],
        install_fn: Callable[[object, int], None],
        *,
        current_fn: Optional[Callable[[], int]] = None,
    ):
        """``build_fn(target)`` constructs a fully warmed engine at the
        target graph-shard count (expensive, runs while the old engine
        serves); ``install_fn(engine, target)`` performs the atomic
        swap; ``current_fn`` reports the serving geometry."""
        self._build_fn = build_fn
        self._install_fn = install_fn
        self._current_fn = current_fn or (lambda: 1)
        self._lock = threading.Lock()  # guards: state, _busy
        self.state = "idle"
        self._busy = False
        self.reshards_total = 0
        self.failures = 0
        self.last_error: Optional[str] = None
        self.last_target: Optional[int] = None
        self.last_duration_s: Optional[float] = None

    def state_code(self) -> int:
        with self._lock:
            return STATE_CODES.get(self.state, 0)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "state": self.state,
                "state_code": STATE_CODES.get(self.state, 0),
                "current_shards": int(self._current_fn()),
                "reshards_total": self.reshards_total,
                "failures": self.failures,
                "last_target": self.last_target,
                "last_error": self.last_error,
                "last_duration_s": self.last_duration_s,
            }

    def reshard(self, target: int) -> dict:
        """Split/merge to ``target`` graph shards under traffic. Blocks
        the CALLING thread for the build (callers run it off the serving
        path — the daemon would use a maintenance thread); the serving
        path is never blocked, only briefly contended at the install.
        Raises on overlap (one reshard at a time) and build failure; the
        old geometry keeps serving in every failure mode."""
        target = int(target)
        if target < 1:
            raise ValueError(f"reshard target must be >= 1, got {target}")
        with self._lock:
            if self._busy:
                raise RuntimeError(
                    f"reshard already in flight (state={self.state})"
                )
            self._busy = True
            self.state = "preparing"
            self.last_target = target
            self.last_error = None
        t0 = time.monotonic()
        try:
            if target == int(self._current_fn()):
                # no-op split: report success without churning devices
                with self._lock:
                    self.state = "idle"
                    self._busy = False
                return self.snapshot()
            new_engine = self._build_fn(target)
            # the handoff kill point: the new geometry exists, the old
            # one still serves — a kill here must leave zero wrong
            # answers (it does: nothing was installed)
            faults.check("reshard-handoff")
            with self._lock:
                self.state = "handoff"
            self._install_fn(new_engine, target)
            with self._lock:
                self.state = "idle"
                self.reshards_total += 1
                self.last_duration_s = time.monotonic() - t0
                self._busy = False
            _log.warning(
                "resharded to %d graph shards in %.2fs (live, zero "
                "downtime)", target, time.monotonic() - t0,
            )
            return self.snapshot()
        except Exception as e:
            with self._lock:
                self.state = "failed"
                self.failures += 1
                self.last_error = f"{type(e).__name__}: {e}"
                self._busy = False
            _log.error(
                "reshard to %d shards failed; old geometry keeps serving",
                target, exc_info=True,
            )
            raise


__all__ = ["ReshardCoordinator", "STATE_CODES"]
