"""Batched check engine: multi-source bit-packed BFS on TPU.

Where the reference answers one ``Check`` by a recursive traversal issuing
one SQL query per subject-set node per page (reference
internal/check/engine.go:33-95), this engine answers **thousands of checks
in one device program**:

- up to 32·W queries are packed into a ``uint32[n_live+1, W]`` reached
  bitmap ``R`` — bit ``q%32`` of word ``q//32`` in row ``v`` means "query q
  has reached node v". Only nodes *with in-edges* ("live") get bitmap rows;
  zero-in-degree ("static") nodes never change and are handled by
  propagating their start bits one hop on the host at batch setup
  (``pack_chunk``), which both seeds ``R`` and pre-computes their
  contribution to the answer;
- one BFS step is a **pull**: ``P[v] = OR over live in-neighbors s of
  R[s]``, computed per degree bucket as a gather + OR-reduction over
  *live→live* edges only (see keto_tpu/graph/snapshot.py for the layout
  rationale). Rows that can change ("active") form a prefix of the bitmap;
  the loop updates them in place via an aliased carry — nothing the size of
  the full graph is ever copied per step;
- ``lax.while_loop`` iterates to the reachability fixpoint (the analog of
  the reference's visited-set cycle guard — monotone bitmaps make cycles
  terminate for free);
- the answer for query q is the target-row bit of ``pull(fixpoint) ∪
  one-hop-term``, i.e. "reached via ≥ 1 edge", reproducing the reference's
  rule that a subject only matches via an actual tuple, never by being the
  queried set itself. The fixpoint pull is carried out of the loop (the
  converging iteration already computed it) — no extra answer pass.

Decision parity with the reference engine:
- unknown namespace → denied, not an error (engine.go:76-77): host
  resolution of a literal unknown namespace contributes no start nodes and
  the query's answer bit can never be set;
- empty namespace/object/relation fields wildcard the expansion exactly like
  the reference's tuple query (relationtuples.go:218-235) — a wildcard
  pattern resolves to *all* matching set nodes as BFS sources
  (GraphSnapshot.resolve_starts);
- pagination transparency: BFS has no pages, and reachability is
  independent of the reference's page-at-a-time visit order;
- the ``...``/empty-relation subtlety (engine_test.go:257-295): an empty
  relation wildcards only the *expansion* of that subject set; it never
  fabricates a transitive grant because matching stays literal.
"""

from __future__ import annotations

import collections
import itertools
import logging
import os
import random
import threading
import time
from functools import partial
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from keto_tpu import namespace as namespace_pkg
from keto_tpu.check import native_pack
from keto_tpu.driver.hbm import HbmGovernor, MemoryPressure, is_resource_exhausted
from keto_tpu.graph.snapshot import WILDCARD, GraphSnapshot
from keto_tpu.relationtuple.model import RelationTuple, SubjectID, SubjectSet
from keto_tpu.x import faults
from keto_tpu.x.errors import ErrNamespaceUnknown, KetoError
from keto_tpu.x.retry import retry_call
from keto_tpu.x.supervise import SupervisedTask
from keto_tpu.x.telemetry import DurationStats, MaintenanceStats

_log = logging.getLogger("keto_tpu.check")

#: distinct-from-None cache sentinel for namespace resolution
_UNSET = object()
#: wildcard-namespace marker in the native resolve cache
_WILD = object()
#: native-format record whose result is overwritten on the Python side
_PLACEHOLDER = b"0\x1f\x1f\x1f1\x1f\x1f\x1f\x1e"

# batch widths (in 32-query words) the engine compiles for; a request is
# padded up to the smallest fitting width so jit caches stay small
_WORD_WIDTHS = (1, 8, 64, 256, 1024, 2048, 4096)
# cap on the [rows, chunk, W] gather intermediate per bucket
_DEGREE_CHUNK = 1024


def _pull(
    bucket_nbrs: Sequence[jnp.ndarray], bucket_valid_rows: Sequence[int], R: jnp.ndarray
) -> jnp.ndarray:
    """One BFS pull step over the active rows.

    R: uint32[n_live+1, W] → uint32[n_active, W]. Buckets hold live→live
    edges and are contiguous in device-id order — concatenating per-bucket
    OR-reductions yields the active prefix with no scatter.
    """
    outs = []
    for nbrs, n_valid in zip(bucket_nbrs, bucket_valid_rows):
        n_pad, cap = nbrs.shape
        acc = None
        for c0 in range(0, cap, _DEGREE_CHUNK):
            gathered = R[nbrs[:, c0 : c0 + _DEGREE_CHUNK]]  # [n_pad, chunk, W]
            part = lax.reduce(gathered, np.uint32(0), lax.bitwise_or, (1,))
            acc = part if acc is None else lax.bitwise_or(acc, part)
        outs.append(acc[:n_valid])
    return jnp.concatenate(outs, axis=0) if len(outs) > 1 else outs[0]


def check_step(
    bucket_nbrs: tuple[jnp.ndarray, ...],
    entries: jnp.ndarray,  # int32[2·S1+2·S2+2·SA+B] packed entry arrays
    ov_nbrs: Optional[jnp.ndarray] = None,  # int32[K, C] overlay-ELL gather
    ov_dst: Optional[jnp.ndarray] = None,  # int32[K] unique active rows (pad → n_active)
    *,
    sizes: tuple[int, int, int, int],  # (S1, S2, SA, B)
    n_active: int,
    n_int: int,
    valid_rows: tuple[int, ...],
    it_cap: int,
    block_iters: int = 8,
    bitmap_sharding=None,  # NamedSharding for the [rows, words] bitmaps
) -> jnp.ndarray:
    # ``entries`` ships every per-batch host-built array in ONE H2D
    # transfer — on tunneled devices transfer count pays round trips and
    # transfer BYTES pay the tunnel's thin bandwidth, so seeds travel as
    # 8-byte (row, query) pairs and the word index / bit mask derive on
    # device. The layout (concatenated int32) is produced by
    # pack_entries(); split points are static per kernel geometry:
    #   e1_rows  int32[S1] interior start rows (padding → n_int+1)
    #   e1_q     int32[S1] owning query index (padding → 0)
    #   e2_*               same pair for host-propagated seeds
    #   a_rows   int32[SA] interior in-neighbors of sink targets
    #   a_q      int32[SA] owning query index (padding → 0 w/ row n_int)
    #   targets  int32[B]  interior target rows, n_int = none
    S1, S2, SA, B = sizes
    o = 0
    e1_rows = entries[o : o + S1]; o += S1
    e1_q = entries[o : o + S1]; o += S1
    e2_rows = entries[o : o + S2]; o += S2
    e2_q = entries[o : o + S2]; o += S2
    a_rows = entries[o : o + SA]; o += SA
    a_q = entries[o : o + SA]; o += SA
    targets = entries[o : o + B]
    e1_words = e1_q >> 5
    e1_masks = jnp.uint32(1) << (e1_q & 31).astype(jnp.uint32)
    e2_words = e2_q >> 5
    e2_masks = jnp.uint32(1) << (e2_q & 31).astype(jnp.uint32)
    W = B // 32
    q = jnp.arange(B)
    words = q // 32
    bits = (q % 32).astype(jnp.uint32)
    # per (row, word) slot, masks from distinct queries occupy distinct bits
    # and per-query row lists are deduplicated on host, so scatter-add
    # never carries — add on disjoint bits is bitwise OR
    zero = jnp.zeros((n_int + 1, W), jnp.uint32)
    # the one-hop term: start bits of static (zero-in-degree) nodes
    # propagated to their interior out-neighbors on host. These bits are
    # "reached via ≥ 1 edge" by construction, so they feed R0 and answers.
    ans_base = zero.at[e2_rows, e2_words].add(e2_masks, mode="drop")
    R0 = zero.at[e1_rows, e1_words].add(e1_masks, mode="drop") | ans_base
    if bitmap_sharding is not None:
        # "data" shards words (embarrassingly parallel); "graph" shards rows
        # and lets the SPMD partitioner insert the per-step all-gather the
        # pull's cross-shard row gathers need
        R0 = lax.with_sharding_constraint(R0, bitmap_sharding)
        ans_base = lax.with_sharding_constraint(ans_base, bitmap_sharding)

    if n_active == 0 or not bucket_nbrs:
        # no interior→interior edges: the fixpoint is R0 itself
        R_fix = R0
        pull_p = jnp.zeros((n_active + 1, W), jnp.uint32)
        iters = jnp.int32(0)
        truncated = jnp.bool_(False)
    else:
        # Only the active prefix R[:n_active] can change; the in-place .set
        # on the while-loop carry aliases, so passive rows are never copied.
        def step(st):
            R, _, _, it = st
            p = _pull(bucket_nbrs, valid_rows, R)
            if ov_nbrs is not None:
                # delta-overlay edges (inserts since the base snapshot
                # build, keto_tpu/graph/overlay.py): OR the overlay
                # in-neighbors into their unique destination rows. Inside
                # the loop, so multi-hop paths through delta edges converge
                # exactly like base edges.
                ovo = lax.reduce(R[ov_nbrs], np.uint32(0), lax.bitwise_or, (1,))
                p = p.at[ov_dst].set(p[ov_dst] | ovo, mode="drop")
            act = R[:n_active]
            nxt = lax.bitwise_or(p, act)
            return R.at[:n_active].set(nxt), p, jnp.any(nxt != act), it + 1

        # The while cond is the only point the runtime must observe a device
        # value, which costs a full round trip on tunneled devices — so each
        # while iteration runs a *block* of pulls, each skipped via lax.cond
        # once the fixpoint is reached (monotone bitmaps: converged stays
        # converged). Steady state: one observation per batch.
        def block(st):
            return lax.fori_loop(
                0, block_iters, lambda _, s: lax.cond(s[2], step, lambda x: x, s), st
            )

        # p0 is shape-placeholder only: changed=True and it_cap ≥ 1 (enforced
        # by the engine) guarantee ≥ 1 real step replaces it. All-zero — not
        # an R0 alias — so even a degenerate caller can't leak start bits
        # (which must never count as "reached via ≥ 1 edge") into answers.
        p0 = jnp.zeros((n_active, W), jnp.uint32)
        R_fix, p_fix, truncated, iters = lax.while_loop(
            lambda st: st[2] & (st[3] < it_cap),
            block,
            (R0, p0, jnp.bool_(True), jnp.int32(0)),
        )
        pull_p = jnp.concatenate([p_fix, jnp.zeros((1, W), jnp.uint32)], axis=0)

    # interior targets: "reached via ≥ 1 edge" = the pull of the fixpoint —
    # already computed by the converging iteration and carried out of the
    # loop — plus the one-hop term. Passive/absent targets read the padded
    # all-zero rows.
    t_act = jnp.where(targets < n_active, targets, n_active)
    a = pull_p[t_act, words] | ans_base[targets, words]
    hit = (a >> bits) & jnp.uint32(1)

    # sink targets: gather each entry's (interior in-neighbor row, query
    # word) from the fixpoint — start bits of the neighbor DO count here
    # (the neighbor is not the target) — and scatter-OR per query.
    # Collisions only combine entries of distinct (row, query) pairs: max
    # on {0,1} is exact.
    aw = a_q // 32
    ab = (a_q % 32).astype(jnp.uint32)
    vals = (R_fix[a_rows, aw] >> ab) & jnp.uint32(1)
    hit = hit.at[a_q].max(vals)

    # Single packed output ``uint32[W+2]``: per-query decision bits, then
    # the iteration count, then the truncation flag (the loop stopped on the
    # cap while the frontier still grew — converging in exactly it_cap steps
    # is NOT truncation). Device-side bit packing matters: D2H fetch is the
    # serving path's scarcest resource on tunneled devices, so ship 1 bit
    # per query in one transfer, not 1 byte in three.
    packed_bits = lax.reduce(
        (hit << bits).reshape(W, 32), np.uint32(0), lax.bitwise_or, (1,)
    )
    tail = jnp.stack([iters.astype(jnp.uint32), truncated.astype(jnp.uint32)])
    out = jnp.concatenate([packed_bits, tail])
    if bitmap_sharding is not None:
        # fully replicate the packed result so every host of a
        # multi-controller mesh can fetch it directly (W+2 words — cheap)
        from jax.sharding import NamedSharding, PartitionSpec

        out = lax.with_sharding_constraint(
            out, NamedSharding(bitmap_sharding.mesh, PartitionSpec())
        )
    return out


#: jitted entrypoint used by the engine; ``check_step`` stays un-jitted for
#: ahead-of-time compile checks (__graft_entry__.py)
_check_kernel = partial(
    jax.jit,
    static_argnames=(
        "sizes", "n_active", "n_int", "valid_rows", "it_cap", "block_iters",
        "bitmap_sharding",
    ),
)(check_step)

#: donated variant: the ``entries`` staging buffer (arg 1) is donated to
#: the computation, so XLA aliases its device memory into the (much
#: smaller) packed output instead of allocating a fresh result buffer —
#: and the staging allocation is released the moment the kernel consumes
#: it, not when Python GC finds the array. Per-slice churn on the hot
#: path drops to: one H2D copy into memory the allocator just got back
#: from slice k-1. The engine only routes here when the backend actually
#: implements donation (``_donation_default``); elsewhere donation is a
#: silent no-op plus a warning, so the plain kernel is used instead.
_check_kernel_donated = partial(
    jax.jit,
    static_argnames=(
        "sizes", "n_active", "n_int", "valid_rows", "it_cap", "block_iters",
        "bitmap_sharding",
    ),
    donate_argnums=(1,),
)(check_step)


def _donation_default() -> bool:
    """Donate entry buffers? ``KETO_TPU_DONATE`` forces (1/0); default is
    platform-derived — XLA implements input-output aliasing for
    device-memory backends (TPU/GPU), while the CPU backend ignores the
    donation and warns."""
    env = os.environ.get("KETO_TPU_DONATE", "")
    if env == "0":
        return False
    if env == "1":
        # forced on (tests exercise the donated call path on CPU, where
        # XLA ignores the donation): suppress the per-geometry warning
        import warnings

        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable"
        )
        return True
    try:
        return jax.local_devices()[0].platform in ("tpu", "gpu")
    except Exception:
        return False

#: cap on the [pairs, W_out, W_in] compare intermediate per chunk
_LABEL_PAIR_CHUNK = 2048


def label_step(
    out_lab: jnp.ndarray,  # int32 [n_int+1, Wo], OUT_PAD-padded (row n_int all pad)
    in_lab: jnp.ndarray,  # int32 [n_int+1, Wi], IN_PAD-padded
    entries: jnp.ndarray,  # int32 [3·P]: pair a-rows, pair b-rows, owning query
    *,
    n_pairs: int,
    B: int,
) -> jnp.ndarray:
    """2-hop label-intersection check: ONE device step at any depth.

    Each pair (a, b) asks reach0(a, b) over the interior subgraph — does
    ``OUT(a)`` share a landmark with ``IN(b)``? The two sides pad with
    distinct sentinels (labels.OUT_PAD / IN_PAD), so padded slots (and
    the all-pad row ``n_int`` the pair padding gathers) can never
    witness an intersection. Pair hits OR into their owning query and
    the decisions pack to 1 bit per query, same transfer shape as
    ``check_step`` minus the iteration tail — there is no iteration.

    This is the O(1)-step fast path the BFS kernel's depth tax motivates
    (keto_tpu/graph/labels.py); the engine routes only label-certifiable
    queries here and everything else to ``check_step`` bit-identically.
    """
    P = n_pairs
    pa = entries[:P]
    pb = entries[P : 2 * P]
    pq = entries[2 * P : 3 * P]
    hits = []
    for c0 in range(0, P, _LABEL_PAIR_CHUNK):
        oa = out_lab[pa[c0 : c0 + _LABEL_PAIR_CHUNK]]  # [chunk, Wo]
        ib = in_lab[pb[c0 : c0 + _LABEL_PAIR_CHUNK]]  # [chunk, Wi]
        hits.append(jnp.any(oa[:, :, None] == ib[:, None, :], axis=(1, 2)))
    hit = jnp.concatenate(hits) if len(hits) > 1 else hits[0]
    W = B // 32
    q = jnp.arange(B)
    bits = (q % 32).astype(jnp.uint32)
    # pair hits from one query land on the same bit — max, never add
    ans = jnp.zeros(B, jnp.uint32).at[pq].max(hit.astype(jnp.uint32))
    return lax.reduce(
        (ans << bits).reshape(W, 32), np.uint32(0), lax.bitwise_or, (1,)
    )


def label_step_witness(
    out_lab: jnp.ndarray,  # int32 [n_int+1, Wo], OUT_PAD-padded
    in_lab: jnp.ndarray,  # int32 [n_int+1, Wi], IN_PAD-padded
    pa: jnp.ndarray,  # int32 [P] pair a-rows
    pb: jnp.ndarray,  # int32 [P] pair b-rows
) -> jnp.ndarray:
    """Explain path (keto_tpu/explain): the WINNING entry of each pair's
    label intersection — argmin over the same packed compare ``label_step``
    reduces to one decision bit, one extra output word per pair. The
    distinct pad sentinels keep padded slots out of the argmin exactly as
    they keep them out of the hit reduction. Dispatched only by
    ``label_witness_info`` on explain requests — never on the check hot
    path."""
    oa = out_lab[pa]  # [P, Wo]
    ib = in_lab[pb]  # [P, Wi]
    entry_hit = jnp.any(oa[:, :, None] == ib[:, None, :], axis=2)  # [P, Wo]
    big = jnp.int32(np.iinfo(np.int32).max)
    lm = jnp.min(jnp.where(entry_hit, oa, big), axis=1)
    return jnp.where(jnp.any(entry_hit, axis=1), lm, jnp.int32(-1))


_label_witness_kernel = jax.jit(label_step_witness)

_label_kernel = partial(jax.jit, static_argnames=("n_pairs", "B"))(label_step)

#: donated variant (see _check_kernel_donated): the pair-entry staging
#: buffer (arg 2) aliases into the packed uint32[W] output
_label_kernel_donated = partial(
    jax.jit, static_argnames=("n_pairs", "B"), donate_argnums=(2,)
)(label_step)


class _HybridSlice:
    """Device output(s) of one label-routed slice: the label kernel's
    packed bits for the whole slice, plus — when some queries fell back —
    a BFS sub-batch output and the slice positions it answers. Quacks
    like a device array where the streaming pipeline needs it
    (``copy_to_host_async`` / ``is_ready``)."""

    __slots__ = ("label_dev", "bfs_dev", "bfs_pos")

    def __init__(self, label_dev, bfs_dev=None, bfs_pos=None):
        self.label_dev = label_dev
        self.bfs_dev = bfs_dev
        self.bfs_pos = bfs_pos

    def parts(self) -> list:
        # label_dev is None when every query in the chunk fell back to
        # the BFS sub-batch (no certifiable pair survived routing)
        out = [] if self.label_dev is None else [self.label_dev]
        if self.bfs_dev is not None:
            out.append(self.bfs_dev)
        return out

    def copy_to_host_async(self) -> None:
        for p in self.parts():
            p.copy_to_host_async()

    def is_ready(self) -> bool:
        return all(
            bool(r()) for p in self.parts()
            for r in (getattr(p, "is_ready", None),) if r is not None
        )


class _ShardedSlice:
    """Device output of one explicitly-sharded dispatch
    (keto_tpu/parallel/sharded.py): the packed ``uint32[W+3]`` kernel
    result (decision bits, iterations, truncation, frontier-bit
    population) plus the halo-exchange byte cost of one round — what the
    engine turns into the ``keto_shard_*`` counters at unpack time.
    Quacks like a device array where the streaming pipeline needs it."""

    __slots__ = ("dev", "halo_bytes_per_round")

    def __init__(self, dev, halo_bytes_per_round: int):
        self.dev = dev
        self.halo_bytes_per_round = int(halo_bytes_per_round)

    def copy_to_host_async(self) -> None:
        self.dev.copy_to_host_async()

    def is_ready(self) -> bool:
        r = getattr(self.dev, "is_ready", None)
        return True if r is None else bool(r())


def pack_entries(
    packed, out: Optional[np.ndarray] = None
) -> tuple[np.ndarray, tuple[int, int, int, int]]:
    """Concatenate pack_chunk's seven arrays into check_step's single
    int32 ``entries`` buffer + static split sizes. ``out`` (a staging
    buffer of exactly the total size, from the engine's ``_StagingPool``)
    receives the concatenation in place — no per-slice host allocation;
    the pool only re-leases it after the slice that shipped it lands."""
    (e1r, e1q, e2r, e2q, ar, aq, targets) = packed
    arrays = [e1r, e1q, e2r, e2q, ar, aq, targets]
    if (
        out is not None
        and out.shape[0] == sum(a.shape[0] for a in arrays)
        and all(a.dtype == np.int32 for a in arrays)
    ):
        buf = np.concatenate(arrays, out=out)
    else:
        buf = np.concatenate(arrays)
    return buf, (e1r.shape[0], e2r.shape[0], ar.shape[0], targets.shape[0])


class _StagingPool:
    """Reusable int32 host staging buffers for the packed entry arrays,
    keyed by exact element count (entry geometries are pow2-padded, so a
    serving process sees a handful of distinct sizes per width rung).

    The aliasing discipline that makes reuse safe: ``acquire`` hands a
    buffer out ON LEASE, and the engine only ``release``s it after the
    slice that shipped it has LANDED (its device output fetched) — the
    H2D copy behind ``jnp.asarray``/``device_put`` may complete
    asynchronously (and on CPU backends may alias the host memory
    outright), so writing the next slice's entries into the buffer any
    earlier could corrupt an in-flight one. tests/test_slice_tail.py
    fuzzes exactly that contract.

    Pool growth is PLANNED: ``on_grow`` (the engine's governor seam)
    may refuse a new buffer, in which case the caller falls back to a
    per-slice allocation — the eviction ladder's "staging" rung drops
    the whole pool the same way. ``bytes()`` is the figure the HBM
    ledger's ``staging`` tag carries, reconciled at scrape."""

    #: free buffers kept per distinct size (beyond the lease depth this
    #: only caches geometry churn, so keep it shallow)
    MAX_FREE_PER_SIZE = 8

    def __init__(self, on_change: Optional[Callable[[int], None]] = None):
        self._lock = threading.Lock()  # guards: _free, _bytes, _leased
        self._free: dict[int, list] = {}
        self._bytes = 0  # free + leased, the ledger figure
        self._leased = 0
        self._on_change = on_change

    def _notify(self, nbytes: int) -> None:
        # ALWAYS outside self._lock: the callback takes the governor's
        # lock, and the governor's staging rung calls back into drop()
        # while holding it — publishing under the pool lock would be a
        # lock-order inversion (the sharded-smoke sanitizer caught
        # exactly that). Concurrent publishes may land out of order; the
        # ledger is reconciled at scrape, not per-update.
        cb = self._on_change
        if cb is not None:
            cb(nbytes)

    def acquire(self, n: int, plan=None) -> Optional[np.ndarray]:
        """An int32 buffer of exactly ``n`` elements, or None when a new
        buffer would be needed and ``plan`` (bytes -> bool) refuses it."""
        with self._lock:
            free = self._free.get(n)
            if free:
                self._leased += 1
                return free.pop()
        if plan is not None and not plan(4 * n):
            return None
        with self._lock:
            self._bytes += 4 * n
            self._leased += 1
            total = self._bytes
        self._notify(total)
        return np.empty(n, np.int32)

    def release(self, buf: np.ndarray) -> None:
        total = None
        with self._lock:
            self._leased = max(0, self._leased - 1)
            free = self._free.setdefault(buf.shape[0], [])
            if len(free) < self.MAX_FREE_PER_SIZE:
                free.append(buf)
            else:
                self._bytes = max(0, self._bytes - 4 * buf.shape[0])
                total = self._bytes
        if total is not None:
            self._notify(total)

    def drop(self) -> int:
        """Evict: clear every free buffer and forget leased accounting
        (outstanding leases release into a fresh pool). Returns the
        bytes freed from the ledger."""
        with self._lock:
            freed = self._bytes
            self._free.clear()
            self._bytes = 0
            self._leased = 0
        self._notify(0)
        return freed

    def bytes(self) -> int:
        with self._lock:
            return self._bytes

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "bytes": self._bytes,
                "leased": self._leased,
                "free_buffers": sum(len(v) for v in self._free.values()),
                "sizes": sorted(self._free),
            }


class _SortedSeen:
    """Sorted-key membership set with amortized O(log n) inserts: keys
    live in a list of sorted runs whose lengths form a (loosely)
    geometric sequence — an insert batch merges equal-or-smaller runs
    (each element participates in O(log n) merges total), replacing the
    ``np.insert``-into-one-array scheme whose per-hop O(n) memmove made
    a long walk quadratic. ``work`` counts elements moved by merges;
    tests/test_native_pack.py asserts the O(n log n) bound."""

    __slots__ = ("_runs", "work")

    def __init__(self):
        self._runs: list[np.ndarray] = []
        self.work = 0

    def contains(self, keys: np.ndarray) -> np.ndarray:
        """bool mask: which of ``keys`` are present (any order)."""
        mask = np.zeros(keys.shape[0], dtype=bool)
        for run in self._runs:
            pos = np.clip(np.searchsorted(run, keys), 0, run.size - 1)
            mask |= run[pos] == keys
        return mask

    def add(self, ks: np.ndarray) -> None:
        """Insert a SORTED batch of keys not currently present."""
        if not ks.size:
            return
        run = ks
        while self._runs and self._runs[-1].size <= run.size:
            prev = self._runs.pop()
            merged = np.concatenate([prev, run])
            merged.sort(kind="stable")
            self.work += merged.size
            run = merged
        self._runs.append(run)


def _ceil_pow2(x: int) -> int:
    return 1 if x <= 1 else 1 << (int(x) - 1).bit_length()


def _csr_gather(indptr: np.ndarray, indices: np.ndarray, nodes: np.ndarray):
    """(all out-neighbors of ``nodes`` concatenated, per-node counts)."""
    cnts = indptr[nodes + 1] - indptr[nodes]
    total = int(cnts.sum())
    if not total:
        return np.zeros(0, indices.dtype), cnts
    base = np.repeat(indptr[nodes], cnts)
    within = np.arange(total) - np.repeat(np.cumsum(cnts) - cnts, cnts)
    return indices[base + within], cnts


def _entry_pad(B: int, size: int) -> int:
    """Scatter/gather entry arrays pad to B·2^k — a couple of geometries per
    batch width, so chunks of one request hit the same jit cache entry."""
    sp = B
    while sp < size:
        sp *= 2
    return sp


def _pad_entries(rows_l, qs_l, B: int, drop_row: int):
    if rows_l:
        rows = np.concatenate(rows_l).astype(np.int32)
        qs = np.concatenate(qs_l).astype(np.int32)
    else:
        rows = np.zeros(0, np.int32)
        qs = np.zeros(0, np.int32)
    pad = _entry_pad(B, rows.size) - rows.size
    rows = np.concatenate([rows, np.full(pad, drop_row, np.int32)])
    qs = np.concatenate([qs, np.zeros(pad, np.int32)])
    return rows, qs


def pack_chunk(
    snap: GraphSnapshot,
    sd: np.ndarray,
    tg: np.ndarray,
    multi: dict,
    i0: int,
    i1: int,
    force_W: Optional[int] = None,
    native: bool = True,
):
    """Pack queries ``[i0, i1)`` of a bulk-resolved batch into kernel
    arguments — vectorized numpy throughout (the host side of the hot path,
    replacing the reference's per-traversal-step SQL round trips).

    ``sd``/``tg``/``multi`` come from ``TpuCheckEngine._resolve_bulk``.
    Starts in the host-propagated classes (static, or peeled interior —
    see the peel note in keto_tpu/graph/snapshot.py) expand here through
    the forward CSR, one vectorized gather per hop over the whole chunk's
    frontier: reached bitmap rows become device seeds (e2), reached
    query targets are decided on host, and reached peeled rows continue
    the frontier (the peeled subgraph is a DAG among base nodes; the
    per-(query, row) visited filter also terminates cycles a delta
    overlay may close). Sink targets get answer-gather entries from the
    snapshot's sink reverse CSR.

    Returns ``(packed, host_ans)`` where ``packed`` is ``(e1_rows, e1_q,
    e2_rows, e2_q, a_rows, a_q, targets)`` numpy arrays (None when no
    query has any device entry; pack_entries concatenates them into the
    kernel's single buffer) and ``host_ans`` is a bool[nq] of
    host-decided grants to OR into the device answers.
    """
    nq = i1 - i0
    W = force_W or next(w for w in _WORD_WIDTHS if 32 * w >= nq)
    B = 32 * W
    ni = snap.num_int
    sb = snap.sink_base
    nl = snap.num_live
    qi = np.arange(nq)
    tgc = tg[i0:i1]
    sdc = sd[i0:i1]
    host_ans = np.zeros(nq, dtype=bool)
    targets = np.full(B, ni, dtype=np.int32)
    targets[:nq] = np.where((tgc >= 0) & (tgc < ni), tgc, ni)

    e1: tuple[list, list] = ([], [])
    e2: tuple[list, list] = ([], [])
    m_int = (sdc >= 0) & (sdc < ni)
    if m_int.any():
        e1[0].append(sdc[m_int])
        e1[1].append(qi[m_int])
    # host-propagated starts: peeled interior, static, and overlay nodes
    # (an overlay sink start has no out-edges and yields nothing). Base
    # sink starts [sb, nl) have no out-edges: nothing to seed.
    m_host = ((sdc >= ni) & (sdc < sb)) | (sdc >= nl)
    prop_rows = [sdc[m_host]] if m_host.any() else []
    prop_q = [qi[m_host]] if m_host.any() else []
    for i, (live, hostp) in multi.items():
        if not (i0 <= i < i1):
            continue
        li = i - i0
        if live.size:
            e1[0].append(live)
            e1[1].append(np.full(live.size, li, np.int64))
        if hostp.size:
            prop_rows.append(hostp)
            prop_q.append(np.full(hostp.size, li, np.int64))

    use_native = (
        native and native_pack.available() and native_pack.walk_eligible(snap)
    )
    native_pack.COUNTERS["native" if use_native else "numpy"] += 1
    if prop_rows:
        rows = np.concatenate(prop_rows).astype(np.int64)
        pq = np.concatenate(prop_q).astype(np.int64)
        if use_native:
            # one GIL-released C++ call walks the whole frontier
            # (native/pack.cpp): threaded CSR gathers, hash-set
            # seen/seed dedup, bit-identical output by contract
            # (fuzz-compared in tests/test_native_pack.py)
            srows, sq, hits = native_pack.pack_walk(snap, rows, pq, tgc)
            if hits is not None:
                host_ans |= hits
            if srows.size:
                e2[0].append(srows)
                e2[1].append(sq)
        else:
            # numpy fallback: multi-hop frontier propagation, (query,
            # row)-deduplicated. The visited set lives in merged sorted
            # runs (_SortedSeen) — membership stays one searchsorted pass
            # per run, and inserts amortize to O(log n) instead of the
            # O(n) np.insert memmove that made long walks quadratic.
            seen = _SortedSeen()
            seed_rows: list = []
            seed_q: list = []
            while rows.size:
                key = (pq << 32) | rows
                _, first = np.unique(key, return_index=True)
                keep = np.sort(first)
                rows, pq, key = rows[keep], pq[keep], key[keep]
                fresh = ~seen.contains(key)
                rows, pq, key = rows[fresh], pq[fresh], key[fresh]
                if not rows.size:
                    break
                seen.add(np.sort(key))
                nbrs, cnts = snap.out_neighbors_bulk(rows)
                if not nbrs.size:
                    break
                gq = np.repeat(pq, cnts)
                nbrs = nbrs.astype(np.int64)
                # a traversed edge landing on the query's target decides
                # it ("reached via ≥ 1 edge" — real edges only). The -1
                # no-target sentinel can never match a neighbor id.
                hit = nbrs == tgc[gq]
                if hit.any():
                    host_ans[gq[hit]] = True
                m_seed = nbrs < ni
                if m_seed.any():
                    seed_rows.append(nbrs[m_seed])
                    seed_q.append(gq[m_seed])
                m_next = (nbrs >= ni) & (nbrs < sb)
                rows, pq = nbrs[m_next], gq[m_next]
            if seed_rows:
                # global (query, row) dedup: e2 scatter-adds per-bit, so
                # a row seeded twice for one query would carry into the
                # next bit
                srows = np.concatenate(seed_rows)
                sq = np.concatenate(seed_q)
                skey = (sq << 32) | srows
                _, sfirst = np.unique(skey, return_index=True)
                keep = np.sort(sfirst)
                e2[0].append(srows[keep])
                e2[1].append(sq[keep])

    # answer-gather entries for sink targets of queries that have any start
    has_start = m_int | m_host
    for i in multi:
        if i0 <= i < i1:
            has_start[i - i0] = multi[i][0].size > 0 or multi[i][1].size > 0
    ans: tuple[list, list] = ([], [])
    m_sink_t = (tgc >= sb) & (tgc < nl)
    if snap.ov_sink_in:
        # overlay targets (ids ≥ n_base) and base sinks with overlay
        # in-edges both answer through sink_in_rows_bulk
        m_sink_t = m_sink_t | np.isin(
            tgc, np.fromiter(snap.ov_sink_in.keys(), np.int64)
        )
    m_ans = has_start & m_sink_t
    if m_ans.any():
        if use_native:
            # overlay-free by eligibility: the native gather mirrors
            # sink_in_rows_bulk's plain-CSR arm off the GIL
            rows, cnts = native_pack.sink_gather(snap, tgc[m_ans])
        else:
            rows, cnts = snap.sink_in_rows_bulk(tgc[m_ans])
        if rows.size:
            ans[0].append(rows)
            ans[1].append(np.repeat(qi[m_ans], cnts).astype(np.int32))

    if not e1[0] and not e2[0]:
        return None, host_ans
    if ans[0]:
        a_rows = np.concatenate(ans[0]).astype(np.int32)
        a_q = np.concatenate(ans[1])
    else:
        a_rows = np.zeros(0, np.int32)
        a_q = np.zeros(0, np.int32)
    pad = _entry_pad(B, a_rows.size) - a_rows.size
    # answer padding: in-range all-zero row ni with query 0 — max(0) is a no-op
    a_rows = np.concatenate([a_rows, np.full(pad, ni, np.int32)])
    a_q = np.concatenate([a_q, np.zeros(pad, np.int32)])
    # seed padding row ni+1 is out of range for the [ni+1, W] bitmap → dropped
    return (
        _pad_entries(*e1, B, ni + 1) + _pad_entries(*e2, B, ni + 1)
        + (a_rows, a_q, targets),
        host_ans,
    )


class StreamSliceController:
    """Service-time-aware slice scheduler for the streaming pipeline.

    The memory-derived ``_slice_cap`` optimizes pure throughput — the
    widest bitmap the workspace budget allows — which on a tunneled device
    means multi-hundred-ms service time per slice. Per-slice timelines
    (PR 14) showed the residual p99 tail is ROUTE-shaped: label slices
    finish in single-digit ms while a BFS slice of the same width pays
    tens of hops, so one reactive width shared by all routes lets the
    occasional deep slice blow a 10–25× p99/p50 spread. This controller
    therefore keeps a **predicted-service-time model** fit online from
    the per-slice ``(width, route, bfs_steps, entries, service_ms)``
    stats the stream already records, and schedules with it three ways:

    - **width planning** (``cap()``): the widest compiled ladder width
      (``32·_WORD_WIDTHS`` — adapting never compiles a new kernel) whose
      PREDICTED service time stays at or below ``target_ms``, where the
      prediction is pessimistic over the routes seen recently — one slow
      BFS observation immediately narrows the next slices instead of
      waiting for the shared EWMA to catch up. The original reactive
      narrow-fast / re-widen-slow ladder walk is retained underneath as
      a safety net for cost regimes the model has not seen;
    - **pre-dispatch splitting** (``entry_budget()``): the model's
      ms-per-device-entry estimate converts ``target_ms`` into a device
      entry budget, and ``_dispatch_slices`` splits a predicted-slow
      chunk (wildcard fanout, deep host walks) into sub-slices BEFORE
      dispatch — the ready-order window then interleaves them with fast
      slices, so a monster chunk never serializes the stream;
    - **tail guard**: the observed p99/p50 ratio of recent slices is
      checked against ``tail_ratio`` (config ``serve.stream_tail_ratio``)
      and a multiplicative guard scales both the planned width and the
      entry budget down while the tail is blown, recovering gradually —
      the direct control loop for the bench's slice-tail gate.

    ``floor`` bounds narrowing so a latency spike cannot collapse
    throughput (2048 queries/slice keeps > 50k checks/s even at 25
    slices/s).
    """

    #: widen when observed ms < WIDEN_FRAC · target, ``patience`` times in a row
    WIDEN_FRAC = 0.5
    #: narrow when observed ms > NARROW_FRAC · target
    NARROW_FRAC = 1.25
    #: a route binds the pessimistic prediction for this many slices
    #: after it was last observed
    ROUTE_RECENCY = 64
    #: recompute the tail guard every this many observations
    TAIL_EVERY = 32

    def __init__(
        self,
        target_ms: float = 40.0,
        floor: int = 2048,
        patience: int = 2,
        tail_ratio: float = 5.0,
    ):
        self._ladder = [32 * w for w in _WORD_WIDTHS]
        self.target_ms = float(target_ms)
        self.tail_ratio = float(tail_ratio)
        self._lo = next(
            (i for i, c in enumerate(self._ladder) if c >= floor),
            len(self._ladder) - 1,
        )
        self._patience = patience
        self._lock = threading.Lock()
        # start two rungs under the top: wide enough that a fast link is
        # near peak throughput from slice one, narrow enough that the
        # first observations on a slow link land near the target
        self._i = max(self._lo, len(self._ladder) - 3)
        self._good = 0
        self._ewma_ms_per_q: Optional[float] = None
        #: per-route cost model: route → {per_q, per_entry, bfs_steps,
        #: last_seen} (EWMAs; last_seen is a slice counter)
        self._routes: dict[str, dict] = {}
        self._slices = 0
        self._ring: collections.deque = collections.deque(maxlen=256)
        self._guard = 1.0
        self._tail_p50 = 0.0
        self._tail_p99 = 0.0

    def _recent_locked(self):
        horizon = self._slices - self.ROUTE_RECENCY
        return [
            st for st in self._routes.values() if st["last_seen"] >= horizon
        ]

    def _model_cap_locked(self) -> Optional[int]:
        """Widest ladder width whose predicted service time (pessimistic
        per-query cost over recently seen routes, scaled by the tail
        guard) fits the target; None before any observation."""
        recent = self._recent_locked()
        per_q = max((st["per_q"] for st in recent), default=None)
        if per_q is None or per_q <= 0:
            return None
        limit = self.target_ms * self._guard / per_q
        want = self._ladder[self._lo]
        for c in self._ladder:
            if c <= limit:
                want = max(want, c)
        return want

    def cap(self) -> int:
        """Per-slice query cap for the NEXT slice: the reactive ladder
        rung bounded by the model's predicted-service-time width (always
        a compiled ladder width)."""
        with self._lock:
            cap = self._ladder[self._i]
            m = self._model_cap_locked()
            return cap if m is None else max(self._ladder[self._lo], min(cap, m))

    def entry_budget(self) -> Optional[int]:
        """Device entries one sub-chunk may carry before its predicted
        service time overshoots the target — the pre-dispatch split
        bound ``_dispatch_slices`` applies. None before the model has an
        entry-cost estimate."""
        with self._lock:
            recent = self._recent_locked()
            per_e = max(
                (st["per_entry"] for st in recent if st["per_entry"] > 0),
                default=None,
            )
            if per_e is None:
                return None
            return max(256, int(self.target_ms * self._guard / per_e))

    def observe(
        self,
        nq: int,
        ms: float,
        route: str = "bfs",
        bfs_steps: int = 0,
        entries: Optional[int] = None,
    ) -> None:
        """Feed one slice's service time: dispatch→ready when the pipeline
        ran dry, ready→ready interval when saturated. ``route``/
        ``bfs_steps``/``entries`` (from the stream's per-slice info) fit
        the per-route model; plain ``observe(nq, ms)`` still steers the
        reactive ladder alone."""
        if nq <= 0:
            return
        per_q = ms / nq
        with self._lock:
            self._slices += 1
            st = self._routes.get(route)
            if st is None:
                st = {"per_q": per_q, "per_entry": 0.0, "bfs_steps": 0.0,
                      "last_seen": 0, "n": 0}
                self._routes[route] = st
            else:
                # asymmetric EWMA: a slowdown bumps the predicted cost
                # HARD (the very next cap()/entry_budget() narrows —
                # that is the tail control), while a speedup also decays
                # fast so a cleared spike doesn't pin throughput low
                old = st["per_q"]
                st["per_q"] = (
                    0.5 * old + 0.5 * per_q
                    if per_q >= old
                    else 0.3 * old + 0.7 * per_q
                )
            if entries:
                pe = ms / max(1, entries)
                old = st["per_entry"]
                if old <= 0:
                    st["per_entry"] = pe
                else:
                    st["per_entry"] = (
                        0.5 * old + 0.5 * pe
                        if pe >= old
                        else 0.3 * old + 0.7 * pe
                    )
            st["bfs_steps"] = 0.7 * st["bfs_steps"] + 0.3 * float(bfs_steps)
            st["last_seen"] = self._slices
            st["n"] += 1
            self._ring.append(ms)
            if self._slices % self.TAIL_EVERY == 0:
                self._retune_tail_locked()
            e = self._ewma_ms_per_q
            self._ewma_ms_per_q = per_q if e is None else 0.7 * e + 0.3 * per_q
            cap = self._ladder[self._i]
            if ms > self.NARROW_FRAC * self.target_ms:
                want = self._lo
                for k in range(self._i, self._lo - 1, -1):
                    if self._ladder[k] * per_q <= self.target_ms:
                        want = k
                        break
                self._i = min(self._i, max(self._lo, want))
                self._good = 0
            elif ms < self.WIDEN_FRAC * self.target_ms and nq >= cap:
                self._good += 1
                if self._good >= self._patience and self._i + 1 < len(self._ladder):
                    self._i += 1
                    self._good = 0
            else:
                self._good = 0

    def _retune_tail_locked(self) -> None:
        vals = sorted(self._ring)
        if len(vals) < 8:
            return
        self._tail_p50 = vals[len(vals) // 2]
        self._tail_p99 = vals[min(len(vals) - 1, int(len(vals) * 0.99))]
        blown = (
            self._tail_p50 > 0
            and self._tail_p99 > self.tail_ratio * self._tail_p50
            and self._tail_p99 > self.target_ms
        )
        if blown:
            self._guard = max(0.25, self._guard * 0.5)
        else:
            self._guard = min(1.0, self._guard * 1.1)

    def snapshot(self) -> dict:
        """Controller state for introspection (bench, /debug)."""
        with self._lock:
            return {
                "cap": self._ladder[self._i],
                "target_ms": self.target_ms,
                "ewma_ms_per_query": self._ewma_ms_per_q,
                "model_cap": self._model_cap_locked(),
                "tail_ratio": self.tail_ratio,
                "tail_guard": self._guard,
                "tail_p50_ms": round(self._tail_p50, 3),
                "tail_p99_ms": round(self._tail_p99, 3),
                "routes": {
                    r: {
                        "per_q_ms": round(st["per_q"], 6),
                        "per_entry_ms": round(st["per_entry"], 6),
                        "bfs_steps": round(st["bfs_steps"], 2),
                        "slices": st["n"],
                    }
                    for r, st in self._routes.items()
                },
            }


class TpuCheckEngine:
    """Drop-in check engine answering batched queries on the device graph.

    ``store`` must expose ``snapshot_rows() -> (rows, watermark)`` and
    ``watermark()`` (keto_tpu/persistence/memory.py); ``namespaces`` is a
    namespace.Manager or a zero-arg callable returning the current one
    (hot-reload safe). This object is the TPU implementation behind the
    registry's ``PermissionEngine()`` seam (reference
    internal/driver/registry_default.go:158-163).

    **Multi-controller (multi-host mesh) lockstep contract:** when
    ``mesh`` spans more than one process, every host executes one SPMD
    program — so every host must call ``batch_check``/``snapshot`` with
    identical inputs in identical order over identical store contents
    (same batches, same write points). This is ENFORCED, not assumed:
    route traffic through ``parallel.lockstep.LockstepFrontend`` (host 0
    replicates every op to all hosts before execution), and the engine
    itself all-gathers a per-batch (snapshot, batch) fingerprint before
    every multi-process dispatch (``lockstep_verify``, default on),
    failing loudly on divergence instead of hanging mismatched
    collectives or corrupting results.
    """

    #: capability flag: ``batch_check_stream_with_token`` accepts
    #: ``with_info=True`` (ordered=False only) and yields
    #: ``(offset, decisions, slice_info)`` — per-slice width / BFS steps
    #: / label-vs-BFS route / halo rounds+bytes, what the CheckBatcher
    #: stamps onto each rider's request timeline (keto_tpu/x/timeline.py)
    STREAM_INFO = True

    def __init__(
        self,
        store,
        namespaces,
        *,
        it_cap: int = 4096,
        max_batch: int = 32 * _WORD_WIDTHS[-1],
        mesh=None,
        shard_rows: bool = False,
        sharded: bool = False,
        mem_budget_bytes: int = 10 << 30,
        compact_after_s: float = 5.0,
        peel_seed_cap: float = 4.0,
        sync_rebuild_budget_s: float = 0.25,
        lockstep_verify: bool = True,
        stream_slice_target_ms: float = 40.0,
        overlay_edge_budget: int = 4096,
        fold_segment_edges: int = 2048,
        snapshot_cache_dir: Optional[str] = None,
        degraded_probe_s: float = 5.0,
        device_error_threshold: int = 3,
        refresh_retry_max_wait_s: float = 2.0,
        labels_enabled: bool = True,
        labels_max_width: int = 64,
        labels_landmarks: int = 0,
        labels_device_build: bool = True,
        labels_min_gain: float = 0.0,
        labels_batch: int = 64,
        labels_device_min_edges: int = 65536,
        hbm_budget_bytes: int = 0,
        audit_sample_rate: float = 0.0,
        device_build_enabled: bool = True,
        build_chunk_rows: int = 262144,
        native_pack_enabled: bool = True,
        staging_enabled: bool = True,
        stream_tail_ratio: float = 5.0,
    ):
        if it_cap < 1:
            raise ValueError("it_cap must be >= 1 (the answer pull needs one step)")
        self._store = store
        if isinstance(namespaces, namespace_pkg.Manager):
            self._nm: Callable[[], namespace_pkg.Manager] = lambda: namespaces
        else:
            self._nm = namespaces
        self._it_cap = it_cap
        self._max_batch = max_batch
        # bound on the BFS workspace (~3 W-wide uint32 bitmaps over interior
        # rows); batch width narrows automatically on huge graphs so the
        # default max_batch can never ask for more HBM than this
        self._mem_budget = mem_budget_bytes
        # pulls per convergence observation, adapted to the workload's
        # traversal depth from the iteration counts kernels report back
        self._block_iters = 8
        # concurrently in-flight chunks (bounds device bitmap workspaces)
        self._dispatch_window = 16
        # streaming pipeline: the latency-adaptive width controller is
        # shared across streams so a serving process stays converged, and
        # per-slice service times land in stream_slice_stats — the
        # controller, bench.py, and operators all read the same numbers
        self.stream_ctrl = StreamSliceController(
            target_ms=stream_slice_target_ms, tail_ratio=stream_tail_ratio
        )
        self.stream_slice_stats = DurationStats()
        #: per-route slice service times + query/slice counts (route =
        #: label | hybrid | bfs | host | cpu): the stream's landing path
        #: records them, bench's per-route breakdown and the
        #: keto_stream_route_slices_total family read them
        self._route_stats: dict[str, DurationStats] = {}
        self._route_slices: collections.Counter = collections.Counter()
        self._route_queries: collections.Counter = collections.Counter()
        # native pack path (native/pack.cpp via keto_tpu/check/
        # native_pack.py): the host walk runs GIL-released when the
        # library is present and the snapshot is overlay-eligible;
        # False pins the numpy reference path
        self._native_pack = bool(native_pack_enabled)
        #: device BFS iteration counts per dispatched slice (values are
        #: step counts, not ms) — bench reports bfs_steps_p50/p99 from
        #: here so the label win is attributable to killed frontier hops
        self.bfs_steps_stats = DurationStats()
        # 2-hop reachability labels (keto_tpu/graph/labels.py): built at
        # snapshot-build time, served as the O(1)-step fast path for
        # deep checks; BFS stays the fallback for everything the labels
        # can't certify (wildcards, self-queries, overlay-dirtied
        # interior edges, width/landmark coverage gaps)
        self._labels_enabled = bool(labels_enabled)
        self._labels_max_width = int(labels_max_width)
        self._labels_landmarks = int(labels_landmarks)
        # device label construction (keto_tpu/graph/label_build.py):
        # batched frontier sweeps replace the per-landmark host BFS on
        # graphs past labels_device_min_edges interior edge slots —
        # entry-identical by contract, landmark cap LIFTED (the
        # min_gain early exit bounds the build instead), and the build
        # overlaps the snapshot pipeline's host phases (cache_save)
        self._labels_device_build = bool(labels_device_build)
        self._labels_min_gain = float(labels_min_gain)
        self._labels_batch = int(labels_batch)
        self._labels_device_min_edges = int(labels_device_min_edges)
        #: the in-flight background label build (full-rebuild overlap);
        #: snapcache.save_snapshot joins it via labels_wait just before
        #: writing the label segments, so bulk segment writing and the
        #: device sweeps genuinely overlap
        self._label_build_thread: Optional[threading.Thread] = None
        # snapshot id last counted as a label invalidation (overlay
        # mutated the interior subgraph) — one count per transition
        self._label_blocked_snap: Optional[int] = None
        self._mesh = mesh
        self._shard_rows = shard_rows
        # EXPLICIT sharding (keto_tpu/parallel/sharded.py): partition the
        # bucket/bitmap/label rows by interior-row range over the mesh's
        # graph axis and run the BFS step as a shard_map kernel with an
        # explicit per-hop halo exchange, instead of handing GSPMD a
        # globally-addressed program. Queries replicate along the data
        # axis; decisions are bit-identical to the single-device kernels.
        self._sharded = bool(sharded and mesh is not None)
        self._shard_count = (
            int(mesh.shape.get("graph", 1)) if self._sharded else 0
        )
        self._multiprocess = mesh is not None and jax.process_count() > 1
        # per-batch (snapshot, batch) fingerprint agreement across hosts:
        # divergence fails loudly instead of hanging mismatched collectives
        # or corrupting decisions (keto_tpu/parallel/lockstep.py)
        self._lockstep_verify = lockstep_verify and self._multiprocess
        self._bitmap_sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from keto_tpu.parallel.mesh import DATA_AXIS, GRAPH_AXIS

            row_axis = GRAPH_AXIS if shard_rows else None
            self._bitmap_sharding = NamedSharding(mesh, P(row_axis, DATA_AXIS))
            # narrow batches (W words < data-axis size) can't meaningfully
            # split words: constraining them anyway sends SPMD down its
            # replicate-then-partition fallback on every BFS-step gather
            self._bitmap_sharding_rows_only = NamedSharding(mesh, P(row_axis))
            self._bucket_sharding = NamedSharding(mesh, P(GRAPH_AXIS, None))
            self._ov_dst_sharding = NamedSharding(mesh, P(GRAPH_AXIS))
            # sharded mode: stacked [n_shards, ...] arrays split over the
            # graph axis (leading dim), replicated over data; per-dispatch
            # label pair entries replicate everywhere
            self._shard_stack_sharding = NamedSharding(mesh, P(GRAPH_AXIS))
            self._shard_repl_sharding = NamedSharding(mesh, P())
        self._lock = threading.Lock()
        self._snapshot: Optional[GraphSnapshot] = None
        # delta overlays beyond this edge count trigger COMPACTION — the
        # overlay folds into the base layout by segment
        # (keto_tpu/graph/compaction.py) in seconds instead of the old
        # full-rebuild fallback; only overlays past the hard cap (or
        # shapes compaction can't fold) still rebuild from scratch
        self._max_overlay_edges = int(overlay_edge_budget)
        # an overlay older than this compacts in the background: without
        # it an insert-only workload would keep a small overlay — and
        # everything gated on it, e.g. expand's Manager delegation —
        # alive forever
        self._compact_after_s = compact_after_s
        # persistent snapshot cache (keto_tpu/graph/snapcache.py): reload
        # on cold start, save in the background after every full build
        self._cache_dir = snapshot_cache_dir or None
        #: maintenance counters operators + bench read (overlay occupancy,
        #: compaction/rebuild counts and durations, cache save/reload)
        self.maintenance = MaintenanceStats()
        self.maintenance.set_gauge("overlay_budget", self._max_overlay_edges)
        self.maintenance.set_gauge("overlay_edges", 0)
        self._peel_seed_cap = peel_seed_cap
        self._overlay_born: Optional[float] = None
        # log-structured snapshot maintenance: the engine keeps the last
        # overlay-free snapshot (_fold_base) plus the ordered delta
        # segments applied since ((base_id, wm, ops) triples) — a fold
        # pass replays the OLDEST segments into the base and compacts
        # just those, bounded per pass by fold_segment_edges, while new
        # writes keep landing in the newest segment. Overlay occupancy
        # is bounded by fold rate instead of a hard budget trip, and the
        # serving path never pays a compaction wall.
        self._fold_segment_edges = max(1, int(fold_segment_edges))
        self._fold_base: Optional[GraphSnapshot] = None
        self._seg_log: list = []
        self._pending_seg = None
        # host mirror of the device-resident overlay pack ([K, C] gather
        # matrix + dst vector, slot map, per-row fill): committed delta
        # edges scatter into the resident arrays (functional .at[].set)
        # instead of re-packing and re-uploading the whole matrix; a
        # delta that outgrows the capacity falls back to a full re-pack
        # with pow2 headroom. Single-device path only — mesh/sharded
        # placements re-route and re-upload (their stacked layouts are
        # rebuilt host-side anyway).
        self._ov_pack: Optional[dict] = None
        # supervised maintenance (x/supervise.py): refresh and cache-save
        # run under crash-containing workers with jittered backoff and
        # crash counters instead of ad-hoc threads that die silently;
        # persistence reads inside a pass retry through x/retry.py for up
        # to refresh_retry_max_wait_s before the pass counts as failed
        self._refresh_retry_max_wait_s = refresh_retry_max_wait_s
        self._refresh_force_full = False
        # close() flips this; long cooperative loops (warm_compile) check
        # it between kernels so teardown never races an in-flight compile
        self._closing = False
        self._refresh_task = SupervisedTask(
            "refresh", self._refresh_pass, stats=self.maintenance
        )
        self._cache_pending: Optional[GraphSnapshot] = None
        self._cache_task = SupervisedTask(
            "cache-save", self._cache_save_pass, stats=self.maintenance,
            base_backoff_s=0.1, max_backoff_s=5.0,
        )
        # degraded mode: repeated device-path failures flip checks to the
        # CPU reference engine (bit-identical decisions, reference
        # throughput); the device path is re-probed every
        # degraded_probe_s and recovery is automatic on success
        self._degraded_probe_s = degraded_probe_s
        self._device_error_threshold = device_error_threshold
        self._consec_device_errors = 0
        self._degraded = False
        self._probe_after = 0.0
        self._fallback_lock = threading.Lock()
        self._fallback_engine_obj = None
        # staleness clock for the health state machine: monotonic instant
        # the serving snapshot was last known current with the store
        self._behind_since: Optional[float] = None
        # serving-mode policy: when the last full rebuild cost more than
        # this, the serving path never rebuilds inline — it serves the
        # current snapshot and catches up in the background (deltas still
        # apply synchronously; they are milliseconds)
        self._sync_rebuild_budget_s = sync_rebuild_budget_s
        self._last_full_build_s = 0.0
        # HBM budget governor (keto_tpu/driver/hbm.py): a ledger of every
        # device allocation this engine makes, plan-before-upload against
        # serve.hbm_budget_bytes, and the graceful eviction ladder —
        # labels → warm compile-width ladder → overlay budget → refuse
        # the refresh and serve stale. Lockstep meshes get deterministic
        # mode: auto-budget probing and reactive (OOM-triggered) eviction
        # are per-host signals and must never diverge the ladder.
        self.hbm = HbmGovernor(
            budget_bytes=int(hbm_budget_bytes),
            stats=self.maintenance,
            deterministic=self._multiprocess,
        )
        if self._sharded:
            # per-shard ledger: the governor tracks each shard's owned
            # residency so the mesh-wide plan binds on the hottest shard
            self.hbm.set_shard_count(self._shard_count)
            self.maintenance.set_gauge("shard_count", self._shard_count)
        # the reverse-query list engine (keto_tpu/list/tpu_engine.py)
        # registers its eviction hooks here once constructed; until then
        # the rung is a no-op (nothing resident to drop)
        self._reverse_evict_cb: Optional[Callable[[], int]] = None
        self._reverse_restore_cb: Optional[Callable[[], None]] = None
        # persistent entry staging (donated device buffers' host half):
        # packed entry arrays concatenate into pooled per-geometry
        # buffers leased until their slice LANDS, and — where the
        # backend implements donation — ship through the donated kernel
        # variants so the device-side staging allocation aliases into
        # the output. The pool's bytes ride the governor's "staging"
        # ledger tag; its rung is FIRST on the ladder (dropping it costs
        # per-slice allocation churn, never coverage or correctness).
        self._staging_enabled = bool(staging_enabled)
        self._staging_suspended = False
        self._staging = _StagingPool(
            on_change=lambda b: self.hbm.register("staging", b)
        )
        self._donate_entries = self._staging_enabled and _donation_default()
        self.hbm.attach_rungs([
            ("staging", self._evict_staging, self._restore_staging),
            ("labels", self._evict_labels, self._restore_labels),
            ("reverse", self._evict_reverse, self._restore_reverse),
            ("warm-ladder", self._evict_warm_ladder, self._restore_warm_ladder),
            ("overlay-budget", self._evict_overlay_budget,
             self._restore_overlay_budget),
        ])
        # ladder state the rungs flip (all derive from replicated inputs)
        self._labels_suspended = False
        self._width_trim = 0
        self._configured_overlay_budget = self._max_overlay_edges
        self._memory_pressure = False
        self._last_label_bytes = 0
        self._last_warm_bytes = 0
        # sampled shadow-parity auditor: serve.audit_sample_rate of live
        # check decisions re-verify against the CPU reference oracle in a
        # supervised background worker — continuous proof that eviction
        # rungs (and everything else) never change answers. Divergence
        # counts audit_mismatches and flips health to DEGRADED.
        self._audit_rate = max(0.0, float(audit_sample_rate))
        self._audit_rng = random.Random(0xA0D17)
        self._audit_pending: collections.deque = collections.deque(maxlen=4096)
        self._audit_checks = 0
        self._audit_mismatches = 0
        #: evidence for recent shadow-parity divergences — both witnesses
        #: (the store-closure back-trace vs the CPU oracle's traversal);
        #: read by the flight recorder's ``audit_divergences`` section
        self.audit_divergences: collections.deque = collections.deque(maxlen=8)
        self._audit_task = SupervisedTask(
            "audit", self._audit_pass, stats=self.maintenance
        )
        # True while the supervised refresh worker owns the pass — the
        # seam where ladder restores and deferred label rebuilds run
        # without adding work to inline (serving-thread) refreshes
        self._in_maintenance_pass = False
        # streaming snapshot pipeline (keto_tpu/graph/stream_build.py):
        # build progress feeds health ({phase, pct} while STARTING) and
        # the keto_build_* metric families; the governed sorter runs the
        # build's edge-scale stable sorts on the device when the HBM
        # governor's transient plan fits, host bit-identically otherwise
        from keto_tpu.graph.device_build import GovernedSorter
        from keto_tpu.graph.stream_build import BuildProgress

        self.build_progress = BuildProgress(stats=self.maintenance)
        self._build_chunk_rows = max(1, int(build_chunk_rows))
        self._build_sorter = (
            GovernedSorter(hbm=self.hbm, stats=self.maintenance)
            if device_build_enabled
            else None
        )

    @property
    def shard_count(self) -> int:
        """Graph-axis shards the explicit sharded mode partitions over
        (0 = not sharded) — bench and the metrics bridge read this."""
        return self._shard_count

    # -- snapshot lifecycle --------------------------------------------------

    def snapshot(self, at_least: Optional[int] = None) -> GraphSnapshot:
        """Device snapshot current with the store's watermark.

        Freshness contract (the real implementation of what the reference
        stubs as "snaptoken", internal/check/handler.go:162):

        - ``at_least=None`` — read-your-writes: blocks until the snapshot
          reflects every acknowledged write. Inserts apply as a delta
          overlay and deletes as tombstones (milliseconds — no re-intern,
          no relayout, keto_tpu/graph/overlay.py); class transitions and
          wildcard-graph deletes rebuild fully.
        - ``at_least=w`` — bounded staleness: any snapshot with id ≥ ``w``
          serves immediately. If the store has moved on, a background
          rebuild is kicked off and *this* call returns the old snapshot —
          checks issued mid-rebuild are served from the old snapshot
          (Zanzibar zookie semantics).
        """
        snap = self._snapshot
        wm = self._store.watermark()
        if snap is not None and snap.snapshot_id == wm:
            self._maybe_kick_compaction(snap)
            return snap
        if (
            at_least is not None
            and snap is not None
            and snap.snapshot_id >= at_least
        ):
            self._kick_background_refresh()
            return snap
        with self._lock:
            return self._refresh_locked()

    def snapshot_serving(self) -> GraphSnapshot:
        """Serving-path snapshot: NEVER stalls the read plane on an
        expensive rebuild (VERDICT r4 weak #1 — a single delta-ineligible
        write used to freeze checks for the full rebuild time).

        - the store hasn't moved → current snapshot (plus the usual
          background compaction kick);
        - watermark advanced and a delta applies → synchronous catch-up
          (milliseconds: inserts extend the overlay, deletes tombstone —
          effectively read-your-writes);
        - only a full rebuild can reach the watermark → if the last build
          was cheap (≤ sync_rebuild_budget_s), just do it; otherwise serve
          the current snapshot (bounded staleness, Zanzibar default) and
          let the background refresh catch up.

        Callers needing hard read-your-writes use ``snapshot()`` /
        ``mode="latest"``; callers holding a write's snaptoken use
        ``snapshot(at_least=token)``.
        """
        snap = self._snapshot
        if snap is None or self._last_full_build_s <= self._sync_rebuild_budget_s:
            try:
                return self.snapshot()
            except Exception:
                if self._snapshot is None:
                    raise  # nothing to serve stale from — STARTING territory
                # refresh is broken but the read plane holds: serve the
                # last snapshot, count the failure, retry in the
                # supervised background worker (the health state machine
                # flips NOT_SERVING once staleness crosses its budget)
                self.maintenance.incr("refresh_failures")
                _log.warning(
                    "inline refresh failed; serving stale snapshot", exc_info=True
                )
                self._kick_background_refresh()
                return self._snapshot
        wm = self._store.watermark()
        if snap.snapshot_id >= wm:
            # current — return it directly (NOT via snapshot(): a write
            # landing between the two watermark reads would send that
            # call into an inline rebuild), with the usual compaction kick
            self._maybe_kick_compaction(snap)
            return snap
        if self._lock.acquire(blocking=False):
            try:
                try:
                    got = self._refresh_locked(delta_only=True)
                except Exception:
                    # the serving path NEVER stalls or fails on refresh
                    # trouble: count it, serve the current snapshot
                    # (bounded staleness — the health state machine turns
                    # budget overruns into NOT_SERVING), and let the
                    # supervised background worker retry with backoff
                    self.maintenance.incr("refresh_failures")
                    _log.warning(
                        "inline delta refresh failed; serving stale snapshot",
                        exc_info=True,
                    )
                    got = None
                if got is not None:
                    if self._overlay_edge_count(got) > self._max_overlay_edges:
                        # serve fresh NOW; the supervised worker folds the
                        # oldest overlay segments off the serving path
                        # (bounded per pass — occupancy is governed by
                        # fold rate, not a synchronous compaction wall)
                        self._kick_background_refresh()
                    return got
            finally:
                self._lock.release()
        # rebuild territory (or a rebuild is already holding the lock):
        # serve stale, catch up off the serving path
        self._kick_background_refresh()
        return self._snapshot

    def _snapshot_for(self, at_least, mode: str) -> GraphSnapshot:
        if at_least is not None:
            return self.snapshot(at_least=at_least)
        if mode == "serving":
            return self.snapshot_serving()
        return self.snapshot()

    def _read_store(self, fn, *args):
        """A persistence read on the refresh path: transient failures
        retry through the shared jittered-backoff policy (x/retry.py) for
        up to ``refresh_retry_max_wait_s`` before the maintenance pass is
        declared failed. ``refresh-read`` is the fault-injection seam
        (x/faults.py) the resilience suite arms to kill refresh."""

        def attempt():
            faults.check("refresh-read")
            return fn(*args)

        return retry_call(
            attempt,
            max_wait_s=self._refresh_retry_max_wait_s,
            base_s=0.05,
            max_s=0.5,
            on_retry=lambda e, d: self.maintenance.incr("refresh_read_retries"),
        )

    # -- health (keto_tpu/driver/health.py reads this surface) ---------------

    def staleness_s(self) -> float:
        """Seconds the serving snapshot has been behind the store
        watermark (0.0 while current, or before the first build — a cold
        engine builds inline on first check, it is not stale). Observing
        a gap also kicks the supervised catch-up, so a health poll is
        itself a self-healing probe."""
        snap = self._snapshot
        if snap is None:
            return 0.0
        try:
            wm = self._store.watermark()
        except Exception:
            wm = None  # store unreadable: keep (or start) the behind clock
        now = time.monotonic()
        if wm is not None and snap.snapshot_id >= wm:
            self._behind_since = None
            return 0.0
        if self._behind_since is None:
            self._behind_since = now
        self._kick_background_refresh()
        return now - self._behind_since

    def health(self) -> dict:
        """Live inputs for the health state machine
        (keto_tpu/driver/health.py): snapshot presence and staleness vs
        the store watermark, maintenance-thread liveness and crash
        counters, and the degraded-mode flag."""
        rt = self._refresh_task
        return {
            "has_snapshot": self._snapshot is not None,
            "staleness_s": self.staleness_s(),
            "maintenance_alive": (
                rt.alive() and self._cache_task.alive() and self._audit_task.alive()
            ),
            "refresh_failures": rt.crashes,
            "refresh_consecutive_failures": rt.consecutive_failures,
            "refresh_last_error": rt.last_error,
            "degraded": self._degraded,
            "consecutive_device_errors": self._consec_device_errors,
            # HBM budget governor (keto_tpu/driver/hbm.py): refusing a
            # refresh for memory reports DEGRADED(memory_pressure)
            "memory_pressure": self._memory_pressure,
            "hbm_resident_bytes": self.hbm.resident_bytes(),
            "hbm_budget_bytes": self.hbm.budget_bytes,
            "hbm_rung": self.hbm.rung_depth,
            # shadow-parity auditor: any divergence flips DEGRADED
            "audit_checks": self._audit_checks,
            "audit_mismatches": self._audit_mismatches,
            # streaming build pipeline: a multi-minute STARTING boot is
            # visibly alive — health surfaces the live phase and a
            # coarse completion estimate (keto_tpu/graph/stream_build.py)
            "build_phase": self.build_progress.current_phase,
            "build_pct": self.build_progress.pct(),
            "build_rows_ingested": self.build_progress.rows_ingested,
        }

    def close(self) -> None:
        """Stop the supervised maintenance workers (daemon threads — this
        is shutdown hygiene, not a liveness requirement) and abort any
        cooperative warmup loop."""
        self._closing = True
        self._refresh_task.stop()
        self._cache_task.stop()
        self._audit_task.stop()
        self._label_build_wait()

    # -- HBM budget governor (keto_tpu/driver/hbm.py) ------------------------

    def _plan_or_refuse(self, what: str, need: int, per_shard=None) -> None:
        """Plan ``need`` device bytes before an upload. The governor walks
        the eviction ladder until it fits; with every rung spent the
        refresh is REFUSED — unless there is no snapshot at all (cold
        boot: nothing to serve stale from, so the upload proceeds over
        budget and is merely accounted). ``per_shard`` additionally holds
        the plan against each shard's slice of the budget (sharded mode:
        the hottest shard is the binding constraint, and any eviction the
        walk takes is mesh-wide — one ladder, every shard)."""
        if self.hbm.plan(need, what=what, per_shard=per_shard):
            return
        if self._snapshot is None:
            self.hbm.note_forced(what, need)
            return
        self.hbm.note_refused()
        self._memory_pressure = True
        self.maintenance.set_gauge("memory_pressure", 1)
        raise MemoryPressure(
            f"HBM budget refused {what}: need {need} bytes with "
            f"{self.hbm.resident_bytes()} resident of "
            f"{self.hbm.budget_bytes} budgeted and every eviction rung "
            "spent — serving the current snapshot stale"
        )

    def _guard_alloc(self, what: str, fn):
        """Run one device-put / compiled-call seam with OOM containment:
        a classified RESOURCE_EXHAUSTED (real XLA, or the injected
        ``device-alloc`` oom fault) evicts one ladder rung and retries
        ONCE, then escalates to the caller — check paths land on the
        existing bit-identical CPU fallback, refresh paths count a
        supervised failure and serve stale. Never a crash."""

        def attempt():
            faults.check("device-alloc")
            return fn()

        try:
            return attempt()
        except Exception as e:
            if self._multiprocess or not is_resource_exhausted(e):
                raise
            self.hbm.note_oom(what)
            setattr(e, "_keto_oom_handled", True)
            rung = self.hbm.evict_one(reason=f"oom at {what}")
            if rung is None:
                raise
            _log.warning(
                "device OOM at %s: evicted rung %r, retrying once", what, rung
            )
            try:
                out = attempt()
            except Exception as e2:
                if is_resource_exhausted(e2):
                    setattr(e2, "_keto_oom_handled", True)
                raise
            self.hbm.note_oom_recovered()
            return out

    def _restore_plan_bytes(self) -> int:
        """Bytes a full walk back up the ladder would re-place on device
        — the ``planned`` margin ``maybe_restore`` holds against, so the
        ladder doesn't oscillate (restore labels → over budget → evict
        labels → ...)."""
        est = 0
        if self._labels_suspended:
            est += self._last_label_bytes
        if self._width_trim:
            est += self._last_warm_bytes
        return est

    def attach_reverse_rung(
        self, evict: Callable[[], int], restore: Callable[[], None]
    ) -> None:
        """The list engine's hooks behind the governor's ``reverse``
        rung (eviction drops the list layouts' device arrays; reverse
        queries fall back to the CPU-reference lister bit-identically).
        Called once at list-engine construction."""
        self._reverse_evict_cb = evict
        self._reverse_restore_cb = restore

    def _evict_reverse(self) -> int:
        cb = self._reverse_evict_cb
        return int(cb()) if cb is not None else 0

    def _restore_reverse(self) -> None:
        cb = self._reverse_restore_cb
        if cb is not None:
            cb()

    def _evict_staging(self) -> int:
        """Rung 0 — drop the persistent entry staging pool and fall back
        to per-slice allocation + device_put: pure churn cost, zero
        coverage or correctness impact, which is why it is the first
        thing pressure sheds. Outstanding leases release into the empty
        pool harmlessly."""
        self._staging_suspended = True
        freed = self._staging.drop()
        self.hbm.release("staging")
        return freed

    def _restore_staging(self) -> None:
        # the pool refills lazily as slices dispatch
        self._staging_suspended = False

    def _staging_on(self) -> bool:
        return self._staging_enabled and not self._staging_suspended

    def _stage_acquire(self, n: int) -> Optional[np.ndarray]:
        """Lease an ``n``-element int32 staging buffer, planning pool
        growth against the HBM governor (``evict=False`` — staging never
        evicts other families; an unplannable buffer just means this
        slice allocates per-slice). None when staging is off/refused."""
        if not self._staging_on():
            return None
        return self._staging.acquire(
            n, plan=lambda b: self.hbm.plan(b, what="staging", evict=False)
        )

    def _stage_release(self, leases) -> None:
        """Return a landed slice's staging buffers to the pool. Empties
        the lease list, so releasing a record twice (land() plus a
        stream-teardown sweep) can never hand the same buffer to the
        free list twice."""
        if not leases:
            return
        for buf in leases:
            self._staging.release(buf)
        del leases[:]

    def staging_snapshot(self) -> dict:
        """Pool introspection (bench, /debug, ledger reconciliation)."""
        out = self._staging.snapshot()
        out["enabled"] = self._staging_enabled
        out["suspended"] = self._staging_suspended
        out["donating"] = self._donate_entries
        return out

    def _evict_labels(self) -> int:
        """Rung 1 — drop the 2-hop label arrays: coverage loss only (the
        router falls back to BFS bit-identically), and typically the
        largest discretionary resident family."""
        self._labels_suspended = True
        freed = self.hbm.release("labels")
        self._last_label_bytes = max(self._last_label_bytes, freed)
        snap = self._snapshot
        if snap is not None:
            snap.device_labels = None
            snap.device_shard_labels = None
            snap.labels = None
        self.maintenance.set_gauge("label_coverage", 0.0)
        self.maintenance.set_gauge("label_entries", 0)
        return freed

    def _restore_labels(self) -> None:
        self._labels_suspended = False
        # the next refresh pass rebuilds + re-uploads via _ensure_labels
        self._kick_background_refresh()

    def _evict_warm_ladder(self) -> int:
        """Rung 3 (after labels and the list engine's reverse rung) —
        trim the compile-width ladder to its lower rungs and
        drop the warm-compiled executables: wide-slice throughput falls,
        decisions do not change (the same kernels at narrower widths)."""
        self._width_trim = max(self._width_trim, len(_WORD_WIDTHS) - 4)
        freed = self.hbm.release("warmup")
        self._last_warm_bytes = max(self._last_warm_bytes, freed)
        kerns: list = [
            _check_kernel, _label_kernel,
            _check_kernel_donated, _label_kernel_donated,
        ]
        if self._sharded:
            from keto_tpu.parallel import sharded as shard_mod

            kerns += [
                shard_mod.check_kernel(self._mesh),
                shard_mod.label_kernel(self._mesh),
            ]
        for kern in kerns:
            clear = getattr(kern, "clear_cache", None)
            if clear is not None:
                try:
                    clear()
                except Exception:
                    # trimming still bounds future widths even when this
                    # jax build can't drop already-compiled executables
                    _log.warning(
                        "compiled-kernel cache clear failed during "
                        "warm-ladder eviction", exc_info=True,
                    )
        return freed

    def _restore_warm_ladder(self) -> None:
        self._width_trim = 0

    def _evict_overlay_budget(self) -> int:
        """Rung 4 (last) — shrink the overlay edge budget so pending deltas fold
        into the base layout (compaction retires the overlay's device
        arrays and keeps future overlays small)."""
        self._max_overlay_edges = max(64, self._configured_overlay_budget // 8)
        self.maintenance.set_gauge("overlay_budget", self._max_overlay_edges)
        snap = self._snapshot
        freed = 0
        if snap is not None and snap.has_overlay:
            from keto_tpu.graph.overlay import overlay_device_bytes

            freed = overlay_device_bytes(snap)  # retired when the fold lands
            self._kick_background_refresh(force_full=True)
        return freed

    def _restore_overlay_budget(self) -> None:
        self._max_overlay_edges = self._configured_overlay_budget
        self.maintenance.set_gauge("overlay_budget", self._max_overlay_edges)

    def _word_widths(self) -> tuple[int, ...]:
        """The compile-width ladder currently in service: the full
        ``_WORD_WIDTHS`` normally, its lower rungs while the governor's
        warm-ladder rung is evicted (never fewer than two widths)."""
        n = len(_WORD_WIDTHS) - self._width_trim
        return _WORD_WIDTHS[: max(2, n)]

    # -- sampled shadow-parity auditor ---------------------------------------

    def _audit_sample(self, tuples, decisions, token: Optional[int]) -> None:
        """Queue a random ``audit_sample_rate`` sample of live decisions
        for re-verification against the CPU reference oracle (supervised
        background worker — never on the serving path)."""
        if self._audit_rate <= 0.0 or token is None:
            return
        rng = self._audit_rng
        rate = self._audit_rate
        picked = False
        for i, rt in enumerate(tuples):
            if rng.random() < rate:
                self._audit_pending.append((rt, bool(decisions[i]), token))
                picked = True
        if picked:
            self._audit_task.kick()

    def _audit_pass(self) -> None:
        """One supervised audit pass: drain the sample queue, re-check
        each decision on the CPU oracle. Samples whose snaptoken no
        longer matches the store watermark are skipped (the oracle reads
        the live store — comparing across a write would fabricate
        divergence). A real mismatch is the one alarm that must never be
        rationalized away: count it and flip DEGRADED via health()."""
        while True:
            try:
                rt, decision, token = self._audit_pending.popleft()
            except IndexError:
                return
            try:
                # resilience seam: arming ``audit-flip`` (x/faults.py)
                # corrupts the device's recorded decision, forcing the
                # auditor to see a divergence — how the witness-diff
                # capture below is regression-tested without a real bug
                faults.check("audit-flip")
            except faults.FaultInjected:
                decision = not decision
            try:
                wm = self._store.watermark()
            except Exception:
                continue  # store unreadable: the health machine owns that
            if wm != token:
                self.maintenance.incr("audit_skipped_stale")
                continue
            got = self._fallback().subject_is_allowed(rt)
            self._audit_checks += 1
            self.maintenance.incr("audit_checks")
            if got != decision:
                self._audit_mismatches += 1
                self.maintenance.incr("audit_mismatches")
                self._note_audit_divergence(rt, decision, got, token)
                _log.error(
                    "shadow-parity audit MISMATCH: %r decided %s on device, "
                    "%s on the CPU oracle (snaptoken %d) — flipping DEGRADED",
                    rt, decision, got, token,
                )

    def _note_audit_divergence(
        self, rt: RelationTuple, device: bool, oracle: bool, token: int
    ) -> None:
        """Capture the evidence for one shadow-parity divergence: the
        store-closure back-trace the device route should have witnessed
        (BFS shortest path) next to the CPU oracle's own traversal. The
        deque rides into flight-recorder bundles (driver/registry.py
        ``audit_divergences`` section) — the debugging artifact for the
        one alarm that must never be rationalized away."""
        try:
            from keto_tpu.explain.witness import build_witness, oracle_witness

            _, dev_path, certificate = build_witness(self._store, rt)
            orc_path = oracle_witness(self._store, rt)
            self.audit_divergences.append(
                {
                    "tuple": str(rt),
                    "device_decision": device,
                    "oracle_decision": oracle,
                    "snaptoken": token,
                    "device_witness": (
                        [str(t) for t in dev_path] if dev_path else None
                    ),
                    "oracle_witness": (
                        [str(t) for t in orc_path] if orc_path else None
                    ),
                    "certificate": certificate,
                }
            )
        except Exception:  # keto-analyze: ignore[KTA401] evidence capture is best-effort; the mismatch counter + DEGRADED flip above already raised the alarm
            pass

    # -- degraded mode (CPU fallback) ----------------------------------------

    def _should_fallback(self) -> bool:
        """Route checks to the CPU reference engine? True while degraded,
        except once per ``degraded_probe_s`` — then one batch tries the
        device path again and recovery is automatic on success."""
        if not self._degraded or self._multiprocess:
            return False
        return time.monotonic() < self._probe_after

    def _note_device_error(self, exc: BaseException) -> None:
        # a RESOURCE_EXHAUSTED that escaped the _guard_alloc seams (e.g.
        # raised at transfer/collect time) still counts as memory
        # pressure and descends one rung before the CPU fallback serves
        # the batch — the ladder, not just the fallback, is the answer
        if (
            not self._multiprocess
            and is_resource_exhausted(exc)
            and not getattr(exc, "_keto_oom_handled", False)
        ):
            self.hbm.note_oom("check-path")
            self.hbm.evict_one(reason="oom on the check path")
        self.maintenance.incr("device_errors")
        self._consec_device_errors += 1
        self._probe_after = time.monotonic() + self._degraded_probe_s
        if (
            not self._degraded
            and self._consec_device_errors >= self._device_error_threshold
        ):
            self._degraded = True
            self.maintenance.set_gauge("degraded", 1)
            _log.error(
                "device check path failed %d times in a row (%s); entering "
                "DEGRADED mode — checks served by the CPU reference engine "
                "until the device path heals",
                self._consec_device_errors, exc,
            )
        else:
            _log.warning(
                "device check failed (%s); serving this batch from the CPU "
                "reference engine", exc,
            )

    def _note_device_ok(self) -> None:
        if self._consec_device_errors or self._degraded:
            if self._degraded:
                _log.warning("device check path healthy; leaving DEGRADED mode")
                self.maintenance.set_gauge("degraded", 0)
            self._degraded = False
            self._consec_device_errors = 0

    def _fallback(self):
        with self._fallback_lock:
            if self._fallback_engine_obj is None:
                from keto_tpu.check.engine import CheckEngine

                self._fallback_engine_obj = CheckEngine(self._store)
            return self._fallback_engine_obj

    def set_store(self, store) -> None:
        """Fleet promotion handoff: swap the backing store WITHOUT
        rebuilding the device snapshot. Valid precisely because the
        durable-watermark handoff guarantees the new store's watermark
        >= the snapshot's id over the same tuple history — the resident
        snapshot stays a correct prefix, and the very next refresh pass
        catches up through the ordinary delta path. Also resets the CPU
        fallback engine (it holds a store reference of its own)."""
        self._store = store
        with self._fallback_lock:
            self._fallback_engine_obj = None

    def _fallback_check(self, tuples) -> tuple[list[bool], Optional[int]]:
        """Answer on the CPU reference engine (keto_tpu/check/engine.py)
        — the differential-testing oracle the device path is fuzz-tested
        against, so decisions are bit-identical by construction. It reads
        the live store (read-your-writes fresh); the returned snaptoken is
        the store watermark when readable."""
        eng = self._fallback()
        out = [eng.subject_is_allowed(t) for t in tuples]
        self.maintenance.incr("fallback_checks", by=len(out))
        try:
            token = self._store.watermark()
        except Exception:
            token = None
        return out, token

    def _fallback_stream(
        self, tuples_iter, *, ordered: bool, chunk: int = 1024,
        with_info: bool = False,
    ):
        """Streaming surface of the CPU fallback — same yield contract as
        ``_stream`` (bool arrays in order, or ``(offset, array)`` pairs
        with ``ordered=False``; ``with_info`` adds the per-slice info
        dict with route ``cpu``). Returns ``(generator, token)``."""
        try:
            token = self._store.watermark()
        except Exception:
            token = None
        eng = self._fallback()

        def gen():
            it = iter(tuples_iter)
            off = 0
            while True:
                t0 = time.perf_counter()
                batch = list(itertools.islice(it, chunk))
                if not batch:
                    return
                out = np.fromiter(
                    (eng.subject_is_allowed(t) for t in batch), dtype=bool,
                    count=len(batch),
                )
                self.maintenance.incr("fallback_checks", by=len(batch))
                ms = (time.perf_counter() - t0) * 1e3
                self._note_route("cpu", len(batch), ms)
                if ordered:
                    yield out
                elif with_info:
                    yield off, out, {
                        "width": len(batch),
                        "bfs_steps": 0,
                        "route": "cpu",
                        "service_ms": round(ms, 3),
                    }
                else:
                    yield off, out
                off += len(batch)

        return gen(), token

    def _guard_stream(self, inner):
        """Device-error accounting around a streaming generator: a failed
        stream counts toward degraded mode — the caller (CheckBatcher)
        retries its unresolved futures through ``batch_check_with_token``,
        which then routes to the CPU fallback — and a completed stream
        marks the device path healthy."""

        def gen():
            try:
                yield from inner
            except Exception as e:
                if not self._multiprocess and not isinstance(e, KetoError):
                    self._note_device_error(e)
                raise
            self._note_device_ok()

        return gen()

    def _maybe_kick_compaction(self, snap: GraphSnapshot) -> None:
        """Fold an overlay that has been quiet for compact_after_s into a
        fresh base layout, off the serving path (one policy, shared by
        snapshot() and snapshot_serving())."""
        if (
            snap.has_overlay
            and self._overlay_born is not None
            and time.monotonic() - self._overlay_born > self._compact_after_s
        ):
            self._kick_background_refresh(force_full=True)

    def _kick_background_refresh(self, force_full: bool = False) -> None:
        """Schedule a supervised background pass bringing the snapshot up
        to the store's watermark — or, with ``force_full``, compacting a
        pending overlay into a fresh base layout — so readers never pay
        the rebuild. Crashes are counted, logged, and retried with
        jittered backoff (x/supervise.py) instead of silently killing the
        maintenance thread."""
        if force_full:
            self._refresh_force_full = True
        self._refresh_task.kick()

    def _refresh_pass(self) -> None:
        """One supervised refresh pass (the SupervisedTask target)."""
        force_full, self._refresh_force_full = self._refresh_force_full, False
        self._in_maintenance_pass = True
        try:
            with self._lock:
                self._refresh_locked(force_full=force_full)
        except Exception:
            if force_full:
                # the failed pass still owes a compaction — retry as one
                self._refresh_force_full = True
            raise
        finally:
            self._in_maintenance_pass = False

    def _refresh_locked(
        self, force_full: bool = False, delta_only: bool = False
    ) -> Optional[GraphSnapshot]:
        """Bring the snapshot to the current watermark (caller holds the
        lock): delta overlay when possible; an overlay past the edge
        budget (or a quiet one, via ``force_full``) folds into the base
        layout by segment (keto_tpu/graph/compaction.py); a full rebuild
        is the fallback for shapes compaction can't express. With
        ``delta_only``, returns None instead of rebuilding (the serving
        path's never-stall contract — snapshot_serving falls back to
        stale; oversized overlays still apply and compact off-path)."""
        snap = self._snapshot
        wm = self._store.watermark()
        fold_failed = False
        if snap is None and self._cache_dir is not None and not delta_only:
            snap = self._load_cache_locked(wm)
        # an over-budget overlay owes a fold even when the snapshot is
        # already current: the maintenance pass falls through to the
        # delta path (an empty delta) so the fold below runs — serving
        # callers keep the early return and never pay it
        needs_fold = (
            snap is not None
            and snap.has_overlay
            and self._in_maintenance_pass
            and not delta_only
            and self._overlay_edge_count(snap) > self._max_overlay_edges
        )
        if snap is not None and snap.snapshot_id == wm and not (
            force_full and snap.has_overlay
        ) and not needs_fold:
            self._behind_since = None
            if self._in_maintenance_pass and not delta_only:
                # an already-current engine has no install step, so the
                # supervised pass is where the eviction ladder walks back
                # up once pressure clears — and where labels dropped by
                # the ladder get rebuilt after their rung restores
                self.hbm.maybe_restore(planned=self._restore_plan_bytes())
                if not snap.has_overlay and snap.labels is None:
                    self._ensure_labels(snap)
            return snap
        wild_ns_ids = frozenset(
            n.id for n in self._nm().namespaces() if n.name == ""
        )
        new = None
        if snap is not None:
            new = self._try_delta(snap, wild_ns_ids)
            if new is not None:
                # segment log: record the delta for the background fold
                # (append-at-install would be cleaner, but the fold below
                # needs the newest segment already on the log; a failed
                # install leaves a dangling entry the continuity check in
                # _fold_locked detects and discards)
                seg, self._pending_seg = self._pending_seg, None
                if seg is not None and (seg[2] or seg[0] != seg[1]):
                    self._seg_log.append(seg)
                if len(self._seg_log) > 4096:
                    # runaway log (fold persistently losing to the write
                    # rate): drop the replay history; the next fold runs
                    # as one full compaction
                    self._fold_base, self._seg_log = None, []
                self.maintenance.incr("delta_applies")
                n_ov = self._overlay_edge_count(new)
                self.maintenance.set_gauge("overlay_edges", n_ov)
                over = force_full or n_ov > self._max_overlay_edges
                if over and new.has_overlay and not delta_only:
                    if not self._in_maintenance_pass:
                        # serving caller tripped the budget: NEVER fold on
                        # the caller's thread — install the oversized
                        # overlay (the hard cap in _try_delta still bounds
                        # it) and let the supervised worker fold it
                        self._refresh_task.kick()
                    else:
                        try:
                            folded = self._fold_locked(new, full=force_full)
                        except Exception:
                            # a broken fold must not kill the refresh: log
                            # it and let the full rebuild below re-establish
                            # a clean base layout. The failure counter is
                            # deferred until the rebuild is recorded so an
                            # unlocked reader never observes the failure
                            # without its fallback
                            fold_failed = True
                            _log.warning(
                                "overlay fold failed; falling back to a full rebuild",
                                exc_info=True,
                            )
                            folded = None
                        if folded is not None:
                            new = folded
                        elif force_full or n_ov > self._max_overlay_edges:
                            new = None  # fold requires a real re-layout
        if new is None:
            if delta_only:
                return None
            from keto_tpu.graph.stream_build import full_build

            t0 = time.monotonic()
            # streaming, overlapped, device-accelerated pipeline: chunked
            # store scan feeds the native intern pool, the layout's
            # stable sorts run on the device when the governor's plan
            # fits, and build_progress narrates phases into health() and
            # the keto_build_* families the whole way
            new = full_build(
                self._store, wild_ns_ids,
                peel_seed_cap=self._peel_seed_cap,
                sorter=self._build_sorter,
                progress=self.build_progress,
                read_retry=self._read_store,
                chunk_rows=self._build_chunk_rows,
            )
            self._upload_buckets(new)
            # labels phase overlaps the rest of the pipeline: the device
            # sweeps run on a background thread while cache_save and the
            # remaining host work proceed; BFS serves the gap
            self._start_label_build(new)
            self._last_full_build_s = time.monotonic() - t0
            self.maintenance.incr("full_rebuilds")
            self.maintenance.observe_ms(
                "full_rebuild", self._last_full_build_s * 1e3
            )
            if fold_failed:
                self.maintenance.incr("compaction_failures")
        self._apply_ell_patch(new)
        self._upload_overlay(new)
        self._snapshot = new
        # freshness clock: reaching the watermark this pass read counts as
        # current even if the store moved again meanwhile (the next pass
        # is kicked by whoever observes the new gap)
        self._behind_since = None
        # the refresh landed within budget: memory pressure (if any) has
        # cleared, and the governor may walk back UP the eviction ladder
        # — holding the restore against what the restored rungs would
        # re-place so the ladder cannot oscillate at the budget edge
        if self._memory_pressure:
            self._memory_pressure = False
            self.maintenance.set_gauge("memory_pressure", 0)
        self.hbm.maybe_restore(planned=self._restore_plan_bytes())
        if new.has_overlay:
            if self._overlay_born is None:
                self._overlay_born = time.monotonic()
            if (
                self._in_maintenance_pass
                and self._overlay_edge_count(new) > self._max_overlay_edges
            ):
                # a bounded fold left the overlay over budget: fold more
                # next pass (each pass retires at least one segment, so
                # this converges whenever writes pause)
                self._refresh_task.kick()
        else:
            # overlay-free install: this snapshot is the new fold base
            # and the segment history behind it is retired
            self._fold_base, self._seg_log = new, []
            self._overlay_born = None
            self.maintenance.set_gauge("overlay_edges", 0)
            self._kick_cache_save(new)
        return new

    def _overlay_edge_count(self, snap: GraphSnapshot) -> int:
        """Overlay occupancy: pending delta edges + tombstones (the number
        the budget gauges)."""
        n = 0
        if snap.ov_ell is not None:
            n += int(snap.ov_ell.shape[0])
        if snap.ov_removed is not None:
            n += int(snap.ov_removed.size)
        if snap.ov_out:
            n += sum(int(np.asarray(v).size) for v in snap.ov_out.values())
        if snap.ov_sink_in:
            n += sum(int(np.asarray(v).size) for v in snap.ov_sink_in.values())
        return n

    def _try_delta(
        self, base: GraphSnapshot, wild_ns_ids
    ) -> Optional[GraphSnapshot]:
        """Apply a watermark advance as an overlay (no re-intern, no
        relayout; inserts extend the overlay, deletes tombstone —
        keto_tpu/graph/overlay.py). None when the store can't produce a
        delta (log overflow, no support), the delta needs a class change,
        or the overlay would exceed the hard cap (budget overflows below
        the cap now COMPACT instead of rebuilding — _refresh_locked)."""
        from keto_tpu.graph.overlay import apply_delta, rows_as_ops

        changes_since = getattr(self._store, "changes_since", None)
        if changes_since is not None:
            got = self._read_store(changes_since, base.snapshot_id)
            if got is None:
                return None
            ops, new_wm = got
        else:
            rows_since = getattr(self._store, "rows_since", None)
            if rows_since is None:
                return None
            got = self._read_store(rows_since, base.snapshot_id)
            if got is None:
                return None
            rows, new_wm = got
            ops = rows_as_ops(rows)
        n_ov = len(ops) + (base.ov_ell.shape[0] if base.ov_ell is not None else 0)
        if base.ov_removed is not None:
            n_ov += int(base.ov_removed.size)
        # hard cap: past this, per-delta overlay merge costs outgrow even
        # a rebuild; the budget itself is a compaction trigger, not a
        # bail. The 64k floor keeps small-budget configs from rebuilding
        # on bursts compaction absorbs in milliseconds.
        if n_ov > max(4 * self._max_overlay_edges, 65536):
            return None
        faults.check("overlay-apply")
        got = apply_delta(base, ops, new_wm, wild_ns_ids)
        if got is not None:
            # stash the raw segment for the log-structured fold: the
            # caller appends it to the segment log with the delta
            self._pending_seg = (int(base.snapshot_id), int(new_wm), list(ops))
        return got

    def _compact_locked(self, snap: GraphSnapshot) -> Optional[GraphSnapshot]:
        """Fold ``snap``'s overlay into its base layout (caller holds the
        lock). Only the touched buckets re-upload; everything else —
        device arrays, interner, kernel geometries — is reused. None when
        the overlay's shape needs the full-rebuild fallback."""
        from keto_tpu.graph.compaction import compact_snapshot

        faults.check("compaction")
        # the compacted snapshot gets a fresh (usually empty) overlay —
        # the resident device pack no longer matches any lineage
        self._ov_pack = None
        t0 = time.monotonic()
        # flush pending device-bucket patches first: compaction reuses
        # untouched device buckets, which is only sound when they agree
        # with the host arrays modulo the tombstones it re-uploads (an
        # unapplied restore patch would otherwise leave a stale sentinel)
        self._apply_ell_patch(snap)
        # device splice: the fold's transposed-CSR / list-layout
        # re-derivation sorts run on the device under the same governed
        # policy as full builds — write-heavy tenants stop paying the
        # host-side rebuild tail (keto_tpu/graph/device_build.py)
        got = compact_snapshot(
            snap, sorter=self._build_sorter, label_patcher=self._label_patcher
        )
        if got is None:
            return None
        new = got.snapshot
        if got.touched_buckets or new.device_buckets is None:
            if new.device_buckets is None:
                self._upload_buckets(new)
            else:
                # old + new copies of every touched bucket are co-resident
                # while in-flight batches still gather the old ones: plan
                # the re-upload like any other swap before placing it
                self._plan_or_refuse("compaction bucket re-upload", got.touched_bytes)
                bufs = list(new.device_buckets)
                for bi in got.touched_buckets:
                    bufs[bi] = self._guard_alloc(
                        "compaction-upload",
                        lambda b=new.buckets[bi]: self._put_bucket(b.nbrs, new.num_int),
                    )
                new.device_buckets = tuple(bufs)
                self.hbm.register("snapshot", new.bucket_device_bytes())
        # label index maintenance: compaction patched incrementally,
        # kept the index, or left it for a rebuild here (folded ELL
        # deletions / patch budget) — either way the compacted snapshot
        # serves with labels matching its interior subgraph exactly
        if got.labels == "patched":
            self.maintenance.incr("label_patches")
            self.maintenance.observe_ms("label_patch", new.labels.build_ms)
        elif got.labels == "patch_abort":
            # the incremental patch ran past its visit budget (or the
            # resume sets were truncated) — no longer invisible: counted,
            # bridged to /metrics, and the device rebuild below (this IS
            # the supervised maintenance pass) replaces the stale index
            self.maintenance.incr("label_patch_aborts")
            self.maintenance.incr("label_rebuilds")
        elif got.labels == "rebuild":
            self.maintenance.incr("label_rebuilds")
        self._ensure_labels(new)
        ms = (time.monotonic() - t0) * 1e3
        self.maintenance.incr("compactions")
        self.maintenance.observe_ms("compaction", ms)
        _log.info(
            "overlay compacted in %.1f ms (%d buckets re-uploaded)",
            ms, len(got.touched_buckets),
        )
        return new

    def _fold_locked(
        self, snap: GraphSnapshot, full: bool = False
    ) -> Optional[GraphSnapshot]:
        """Log-structured fold (caller holds the lock): replay the OLDEST
        delta segments onto the last overlay-free base, compact just
        those, then re-apply the remaining segments — so a fold pass
        costs ``fold_segment_edges`` worth of work no matter how large
        the overlay has grown, and new writes keep landing in the newest
        segment meanwhile. With ``full`` (the quiet-overlay timer path)
        every segment folds in one pass. Returns the refreshed snapshot
        (which may still carry the newest segments' overlay), or None
        when the overlay's shape needs the full-rebuild fallback."""
        from keto_tpu.graph.overlay import apply_delta

        fb, log = self._fold_base, self._seg_log
        # continuity: the log must replay fb → snap exactly (a crashed
        # install or a direct _snapshot swap leaves gaps — detect, drop
        # the history, and fold everything at once)
        intact = (
            fb is not None
            and log
            and log[0][0] == fb.snapshot_id
            and log[-1][1] == snap.snapshot_id
            and all(log[i][1] == log[i + 1][0] for i in range(len(log) - 1))
        )
        if not intact:
            got = self._compact_locked(snap)
            if got is not None and not got.has_overlay:
                self._fold_base, self._seg_log = got, []
            return got
        if full:
            take = len(log)
        else:
            take, tot = 0, 0
            while take < len(log) and (
                take == 0 or tot + len(log[take][2]) <= self._fold_segment_edges
            ):
                tot += len(log[take][2])
                take += 1
        prefix, rest = log[:take], log[take:]
        t0 = time.monotonic()
        wild_ns_ids = frozenset(
            n.id for n in self._nm().namespaces() if n.name == ""
        )
        mid = fb
        for _base_id, seg_wm, ops in prefix:
            mid = apply_delta(mid, ops, seg_wm, wild_ns_ids)
            if mid is None:
                return None  # segment needs a re-layout — full rebuild
            # flush each segment's device-bucket patches before stacking
            # the next (apply_delta replaces, not extends, ell_patch)
            self._apply_ell_patch(mid)
        new_base = self._compact_locked(mid) if mid.has_overlay else mid
        if new_base is None or new_base.has_overlay:
            return None
        cur = new_base
        for _base_id, seg_wm, ops in rest:
            cur = apply_delta(cur, ops, seg_wm, wild_ns_ids)
            if cur is None:
                return None
            self._apply_ell_patch(cur)
        self._fold_base, self._seg_log = new_base, rest
        # the replayed overlay is a different lineage than the resident
        # device pack — force a re-pack on the upload below
        self._ov_pack = None
        self.maintenance.incr("fold_runs")
        self.maintenance.observe_ms("fold", (time.monotonic() - t0) * 1e3)
        _log.info(
            "overlay fold: %d/%d segments folded in %.1f ms (%d remain)",
            take, take + len(rest), (time.monotonic() - t0) * 1e3, len(rest),
        )
        return cur

    # -- snapshot cache ------------------------------------------------------

    def _load_cache_locked(self, store_wm: int) -> Optional[GraphSnapshot]:
        """Cold start: reload the newest usable cached snapshot
        (keto_tpu/graph/snapcache.py) and install it; the caller then
        catches up to the store watermark through the ordinary delta
        path. None when no cache fits (wrong watermark range, wildcard
        config drift, unreadable)."""
        from keto_tpu.graph import snapcache

        t0 = time.monotonic()
        # transient read failures (NFS blips, a save racing the reload)
        # retry through the shared backoff before cold start falls back
        # to the full ingest+build path
        snap = retry_call(
            lambda: snapcache.load_latest(
                self._cache_dir, max_watermark=store_wm, stats=self.maintenance,
                sorter=self._build_sorter,
            ),
            max_wait_s=2.0,
            base_s=0.05,
            max_s=0.5,
            on_retry=lambda e, d: self.maintenance.incr("cache_reload_retries"),
        )
        if snap is None:
            return None
        wild_now = frozenset(
            n.id for n in self._nm().namespaces() if n.name == ""
        )
        if snap.wild_ns_ids != wild_now:
            return None  # namespace config changed — expansion differs
        self._upload_buckets(snap)
        if snap.labels is not None and not self._labels_enabled:
            snap.labels = None  # cached labels ignored when disabled
        self._ensure_labels(snap)
        self._snapshot = snap
        ms = (time.monotonic() - t0) * 1e3
        self.maintenance.incr("cache_loads")
        self.maintenance.observe_ms("cache_reload", ms)
        _log.info(
            "snapshot cache reloaded (watermark %d) in %.1f ms",
            snap.snapshot_id, ms,
        )
        return snap

    def _kick_cache_save(self, snap: GraphSnapshot) -> None:
        """Persist an overlay-free snapshot via the supervised cache-save
        worker. Failures are no longer a silent drop: the supervisor logs
        them, counts ``cache_save_failures`` into ``maintenance``, and
        retries with jittered backoff; kicks coalesce so only the newest
        pending snapshot is saved. Serving is never affected."""
        if self._cache_dir is None or snap.has_overlay:
            return
        self._cache_pending = snap
        self._cache_task.kick()

    def _cache_save_pass(self) -> None:
        """One supervised cache-save pass (the SupervisedTask target)."""
        snap = self._cache_pending
        if snap is None:
            return
        from keto_tpu.graph import snapcache

        faults.check("cache-save")
        t0 = time.monotonic()
        with self.build_progress.phase("cache_save"):
            path = snapcache.save_snapshot(
                snap, self._cache_dir, shards=max(1, self._shard_count),
                labels_wait=self._label_build_wait,
            )
        if path is not None:
            self.maintenance.incr("cache_saves")
            self.maintenance.observe_ms(
                "cache_save", (time.monotonic() - t0) * 1e3
            )
        self._cache_pending = None

    def save_snapshot_cache(self) -> Optional[str]:
        """Synchronously persist the current snapshot (bench/operator
        seam); returns the cache path or None when not cacheable."""
        if self._cache_dir is None:
            return None
        snap = self.snapshot()
        from keto_tpu.graph import snapcache

        t0 = time.monotonic()
        path = snapcache.save_snapshot(
            snap, self._cache_dir, shards=max(1, self._shard_count),
            labels_wait=self._label_build_wait,
        )
        if path is not None:
            self.maintenance.incr("cache_saves")
            self.maintenance.observe_ms("cache_save", (time.monotonic() - t0) * 1e3)
        return path

    def _apply_ell_patch(self, snap: GraphSnapshot) -> None:
        """Apply a delta's pending device-bucket patches (tombstoned /
        restored iterated edges, keto_tpu/graph/overlay.py) to the device
        buckets inherited from the base snapshot. Functional updates: the
        base snapshot's arrays are untouched, in-flight batches keep
        gathering the old state. The patch is a handful of (row, col)
        slots — one tiny device scatter, no bucket re-upload."""
        patch = snap.ell_patch
        snap.ell_patch = None
        if self._sharded:
            if patch and snap.device_shards is not None:
                self._apply_ell_patch_sharded(snap, patch)
            return
        if not patch or snap.device_buckets is None:
            return
        by_bucket: dict[int, list] = {}
        for bi, row, col, val in patch:
            by_bucket.setdefault(bi, []).append((row, col, val))
        bufs = list(snap.device_buckets)
        for bi, entries in by_bucket.items():
            rows = np.asarray([e[0] for e in entries], np.int32)
            cols = np.asarray([e[1] for e in entries], np.int32)
            vals = np.asarray([e[2] for e in entries], np.int32)

            def patch(buf=bufs[bi], rows=rows, cols=cols, vals=vals):
                # functional update: old + new bucket transiently
                # co-resident — an OOM here evicts a rung and retries
                # through the device-alloc seam like every other site
                out = buf.at[rows, cols].set(jnp.asarray(vals))
                if self._mesh is not None:
                    out = jax.device_put(out, self._bucket_sharding)
                return out

            bufs[bi] = self._guard_alloc("ell-patch", patch)
        snap.device_buckets = tuple(bufs)

    def _apply_ell_patch_sharded(self, snap: GraphSnapshot, patch) -> None:
        """Route pending device-bucket patches to the OWNING SHARD's slot
        of the stacked arrays: each (bucket, row) maps to exactly one
        shard by the spec's row-range assignment, the host stacked array
        updates in place (it is the upload-truth the next re-upload
        reuses), and only touched buckets' stacks re-upload — a handful
        of slots, never a full snapshot."""
        spec = snap.shard_spec
        by_bucket: dict[int, list] = {}
        for bi, row, col, val in patch:
            s, pos = spec.patch_pos(snap.buckets[bi].offset, bi, row)
            by_bucket.setdefault(bi, []).append((s, pos, col, val))
        nbrs_dev = list(snap.device_shards[0])
        for bi, entries in by_bucket.items():
            host = spec.nbrs_sh[bi]
            for s, pos, col, val in entries:
                host[s, pos, col] = val
            nbrs_dev[bi] = self._guard_alloc(
                "ell-patch",
                lambda h=host: jax.device_put(h, self._shard_stack_sharding),
            )
        snap.device_shards = (tuple(nbrs_dev), snap.device_shards[1])

    def _put_bucket(self, nbrs: np.ndarray, num_int: int):
        """Place one bucket matrix on device. On a mesh, rows pad up to a
        multiple of the graph axis with sentinel rows (gathered from the
        all-zero bitmap row, discarded by the _pull valid-row slice) and
        shard over it — replicating instead (the old fallback for
        non-divisible buckets) made SPMD materialize cross-shard gathers
        via select+all-reduce with an "Involuntary full rematerialization"
        on every BFS step."""
        if self._mesh is None:
            return jax.device_put(np.ascontiguousarray(nbrs))
        g = self._mesh.shape.get("graph", 1)
        rem = (-nbrs.shape[0]) % g
        if rem:
            pad = np.full((rem, nbrs.shape[1]), num_int, np.int32)
            nbrs = np.concatenate([nbrs, pad], axis=0)
        return jax.device_put(np.ascontiguousarray(nbrs), self._bucket_sharding)

    def _upload_buckets(self, snap: GraphSnapshot) -> None:
        if self._sharded:
            return self._upload_buckets_sharded(snap)
        # plan BEFORE uploading: during a swap the old snapshot's buckets
        # are still resident (in-flight batches gather them), so the plan
        # runs against live residency; the governor walks the eviction
        # ladder when over, and only a spent ladder refuses the refresh
        need = snap.bucket_device_bytes()
        self._plan_or_refuse("snapshot buckets", need)
        snap.device_buckets = self._guard_alloc(
            "snapshot-upload",
            lambda: tuple(
                self._put_bucket(b.nbrs, snap.num_int) for b in snap.buckets
            ),
        )
        self.hbm.register("snapshot", need)

    def _upload_buckets_sharded(self, snap: GraphSnapshot) -> None:
        """Sharded mode: partition the buckets into row-range shards
        (keto_tpu/parallel/sharded.py) and place the stacked per-shard
        arrays split over the graph axis. The per-shard owned bytes land
        in the governor's per-shard ledger, so one hot shard is visible
        — and binding — in the mesh-wide plan."""
        from keto_tpu.parallel import sharded as shard_mod

        spec = shard_mod.make_shard_spec(snap, self._shard_count)
        need = spec.padded_bucket_bytes()
        self._plan_or_refuse(
            "snapshot buckets", need, per_shard=spec.owned_bucket_bytes
        )
        snap.shard_spec = spec
        snap.device_shards = self._guard_alloc(
            "snapshot-upload",
            lambda: (
                tuple(
                    jax.device_put(a, self._shard_stack_sharding)
                    for a in spec.nbrs_sh
                ),
                tuple(
                    jax.device_put(a, self._shard_stack_sharding)
                    for a in spec.dst_sh
                ),
            ),
        )
        self.hbm.register("snapshot", need)
        self.hbm.register_shards("snapshot", spec.owned_bucket_bytes)

    def _apply_overlay_delta(self, snap: GraphSnapshot, delta) -> bool:
        """Scatter one delta's added/dropped overlay-ELL edges into the
        device-RESIDENT gather matrix (functional ``.at[].set`` — the
        base snapshot's arrays stay untouched for in-flight batches).
        True when the delta landed; False when it can't (no resident
        pack, lineage mismatch, or capacity outgrown) and the caller
        must re-pack from scratch. Layout invariants the kernel needs:
        one row per destination, holes are the ``num_int`` sentinel
        (all-zero bitmap row, OR-neutral), pad rows scatter-drop via
        ``num_active`` — row order is irrelevant to the OR-gather."""
        pack = self._ov_pack
        if pack is None or delta is None:
            return False
        base_id, added, dropped = delta
        if pack["snap_id"] != base_id:
            return False
        nbrs, dst = pack["nbrs"], pack["dst"]
        K, C = nbrs.shape
        slot, row_of, fill = pack["slot"], pack["row_of"], pack["fill"]
        rows: list = []
        cols: list = []
        vals: list = []
        drows: list = []
        dvals: list = []
        num_int = snap.num_int
        # host mirror mutates as we go: any bail past this point must
        # invalidate the pack (the re-pack rebuilds it from ov_ell)
        for s, d in dropped:
            rc = slot.pop((s, d), None)
            if rc is None:
                self._ov_pack = None
                return False
            r, c = rc
            nbrs[r, c] = num_int
            rows.append(r)
            cols.append(c)
            vals.append(num_int)
        for s, d in added:
            r = row_of.get(d)
            if r is None:
                r = pack["rows_used"]
                if r >= K:
                    self._ov_pack = None
                    return False  # destination rows outgrew capacity
                pack["rows_used"] = r + 1
                row_of[d] = r
                fill[r] = 0
                dst[r] = d
                drows.append(r)
                dvals.append(d)
            c = int(fill[r])
            if c >= C:
                self._ov_pack = None
                return False  # a row outgrew its column capacity
            fill[r] = c + 1
            nbrs[r, c] = s
            slot[(s, d)] = (r, c)
            rows.append(r)
            cols.append(c)
            vals.append(s)
        dev_n, dev_d = pack["dev"]

        def patch():
            out_n, out_d = dev_n, dev_d
            if rows:
                out_n = out_n.at[
                    np.asarray(rows, np.int32), np.asarray(cols, np.int32)
                ].set(jnp.asarray(np.asarray(vals, np.int32)))
            if drows:
                out_d = out_d.at[np.asarray(drows, np.int32)].set(
                    jnp.asarray(np.asarray(dvals, np.int32))
                )
            return out_n, out_d

        try:
            got = self._guard_alloc("overlay-apply", patch)
        except Exception:
            # host mirror already moved — never reuse it
            self._ov_pack = None
            raise
        pack["dev"] = got
        pack["snap_id"] = int(snap.snapshot_id)
        snap.device_overlay = got
        snap.device_shard_overlay = None
        self.hbm.register("overlay", int(nbrs.nbytes + dst.nbytes))
        self.maintenance.incr("overlay_device_applies")
        return True

    def _upload_overlay(self, snap: GraphSnapshot) -> None:
        """Group overlay-ELL edges by destination into a [K, C] gather
        matrix (pow2-padded so repeated small deltas reuse compiled
        geometries) and place it on device. On the single-device path a
        delta whose edges fit the resident matrix's capacity scatters
        into it in place (one tiny ``.at[].set`` — no host re-pack, no
        full re-upload): the group-commit write path applies committed
        edges device-resident instead of mirroring every group through
        host numpy."""
        delta = snap.ov_ell_delta
        snap.ov_ell_delta = None
        if snap.ov_ell is None or snap.ov_ell.shape[0] == 0:
            self._ov_pack = None
            snap.device_overlay = None
            snap.device_shard_overlay = None
            self.hbm.register("overlay", 0)
            if self._sharded:
                self.hbm.register_shards("overlay", [0] * self._shard_count)
            return
        if (
            not self._sharded
            and self._mesh is None
            and self._apply_overlay_delta(snap, delta)
        ):
            return
        from keto_tpu.graph.overlay import overlay_device_bytes

        need = overlay_device_bytes(snap)
        self._plan_or_refuse("overlay ELL", need)
        src = snap.ov_ell[:, 0]
        dst = snap.ov_ell[:, 1]
        order = np.argsort(dst, kind="stable")
        src, dst = src[order], dst[order]
        uniq, starts = np.unique(dst, return_index=True)
        counts = np.diff(np.append(starts, dst.shape[0]))
        K = _ceil_pow2(uniq.shape[0])
        C = _ceil_pow2(int(counts.max()))
        if self._sharded:
            # route overlay rows to the shard owning their destination —
            # the same row-range ownership the buckets partition by, so
            # the kernel's overlay stage is local to each shard's slab
            from keto_tpu.parallel import sharded as shard_mod

            nbrs = np.full((uniq.shape[0], C), snap.num_int, np.int32)
            for i, (s0, c) in enumerate(zip(starts, counts)):
                nbrs[i, :c] = src[s0 : s0 + c]
            ovn, ovd, owned = shard_mod.route_overlay(
                snap.shard_spec, nbrs, uniq, snap.num_active
            )
            snap.device_overlay = None
            snap.device_shard_overlay = self._guard_alloc(
                "overlay-upload",
                lambda: (
                    jax.device_put(ovn, self._shard_stack_sharding),
                    jax.device_put(ovd, self._shard_stack_sharding),
                ),
            )
            self.hbm.register("overlay", int(ovn.nbytes + ovd.nbytes))
            self.hbm.register_shards("overlay", owned)
            return
        if self._mesh is not None:
            # overlay rows shard over the graph axis exactly like buckets
            # (replicated indices into the row-sharded bitmap would trip
            # SPMD's full-rematerialization fallback every BFS step)
            g = self._mesh.shape.get("graph", 1)
            K += (-K) % g
        nbrs = np.full((K, C), snap.num_int, np.int32)  # all-zero bitmap row
        for i, (s0, c) in enumerate(zip(starts, counts)):
            nbrs[i, :c] = src[s0 : s0 + c]
        dst_pad = np.full(K, snap.num_active, np.int32)  # scatter-dropped
        dst_pad[: uniq.shape[0]] = uniq
        if self._mesh is None:
            snap.device_overlay = self._guard_alloc(
                "overlay-upload",
                lambda: (jax.device_put(nbrs), jax.device_put(dst_pad)),
            )
            # host mirror of the resident pack: later deltas scatter into
            # the spare pow2 capacity instead of re-packing (fill is the
            # next free column per row — tombstoned slots become sentinel
            # holes, harmless to the OR-gather, reclaimed at the next
            # re-pack or fold)
            fill = np.zeros(K, np.int64)
            fill[: counts.shape[0]] = counts
            self._ov_pack = {
                "snap_id": int(snap.snapshot_id),
                "nbrs": nbrs,
                "dst": dst_pad,
                "dev": snap.device_overlay,
                "row_of": {int(d): i for i, d in enumerate(uniq)},
                "fill": fill,
                "rows_used": int(uniq.shape[0]),
                "slot": {
                    (int(src[s0 + j]), int(uniq[i])): (i, j)
                    for i, (s0, c) in enumerate(zip(starts, counts))
                    for j in range(int(c))
                },
            }
        else:
            snap.device_overlay = self._guard_alloc(
                "overlay-upload",
                lambda: (
                    jax.device_put(nbrs, self._bucket_sharding),
                    jax.device_put(dst_pad, self._ov_dst_sharding),
                ),
            )
        self.hbm.register("overlay", need)

    # -- 2-hop labels (keto_tpu/graph/labels.py) -----------------------------

    #: landmark auto-cap: with ``labels_landmarks == 0`` the engine
    #: processes min(num_int, this) nodes — full coverage on every graph
    #: the depth tax actually hurts, bounded build time on huge shallow
    #: ones (coverage misses just fall back to BFS, bit-identically)
    LABELS_AUTO_CAP = 131072

    def _ensure_labels(self, snap: GraphSnapshot) -> None:
        """Build (or rebuild) the label index for ``snap`` when enabled
        and missing, and place it on device. Called wherever a fresh
        base layout appears: full rebuild, cache load without labels,
        compaction that couldn't patch. Skipped entirely while the HBM
        governor's labels rung is evicted — the index is the FIRST
        pressure valve because dropping it costs coverage, never
        correctness (the router falls back to BFS)."""
        if not self._labels_enabled or self._labels_suspended:
            return
        if snap.labels is None:
            snap.labels = self._build_label_index(snap)
            if snap.labels is None:
                return
            self.maintenance.incr("label_builds")
            self.maintenance.observe_ms("label_build", snap.labels.build_ms)
        if self._labels_dev(snap) is None:
            # plan before uploading; a plan that evicts the labels rung
            # itself (suspension) means the ladder chose to shed this
            # very family — honor it and drop the fresh build
            need = snap.labels.device_bytes()
            self._last_label_bytes = max(self._last_label_bytes, need)
            fits = self.hbm.plan(need, what="label arrays")
            if not fits or self._labels_suspended or snap.labels is None:
                snap.labels = None
                snap.device_labels = None
                snap.device_shard_labels = None
                return
            self._upload_labels(snap)
            if self._labels_suspended:
                # the labels rung evicted during this upload's own OOM
                # retry: the freshly placed arrays are already shed
                snap.labels = None
                snap.device_labels = None
                snap.device_shard_labels = None
                self.hbm.release("labels")
                return
        idx = snap.labels
        self.maintenance.set_gauge("label_coverage", round(idx.coverage, 4))
        self.maintenance.set_gauge("label_entries", idx.n_entries)

    def _labels_dev(self, snap: GraphSnapshot):
        """The device label arrays this engine's dispatch mode reads —
        the row-striped stacks in sharded mode, the replicated pair
        otherwise."""
        return snap.device_shard_labels if self._sharded else snap.device_labels

    def _interior_ell_slots(self, snap: GraphSnapshot) -> int:
        """Padded interior ELL edge slots — the cheap size signal the
        device-build gate compares against labels_device_min_edges
        (below it, dispatch + transfer overhead beats the host BFS)."""
        return sum(
            int(b.n) * int(np.asarray(b.nbrs).shape[1]) for b in snap.buckets
        )

    def _build_label_index(self, snap: GraphSnapshot):
        """Construct the 2-hop index for ``snap`` through the configured
        path. Device (keto_tpu/graph/label_build.py): batched frontier
        sweeps, NO landmark auto-cap — the ``labels_min_gain`` early
        exit bounds the build — with the transient sweep footprint
        planned ``evict=False`` under the governor's ``build`` tag like
        every other device-build transient (a label build must never
        push serving state off the chip). Host: the original
        per-landmark BFS with the 128k auto-cap, the fallback for tiny
        graphs, missing backends, plan refusals, and device errors —
        entry-identical by the builder's contract either way. Any
        truncation (cap or min_gain) is now LOUD: a structured warning
        with the achieved coverage plus the
        ``keto_label_build_truncated_total`` family."""
        from keto_tpu.graph.labels import build_labels

        n = snap.num_int
        landmarks = self._labels_landmarks
        if self._labels_device_build and n > 0:
            from keto_tpu.graph import label_build
            from keto_tpu.graph.device_build import device_available

            eligible = (
                device_available()
                and self._interior_ell_slots(snap) >= self._labels_device_min_edges
            )
            if eligible:
                need = label_build.estimate_build_bytes(
                    n, self._labels_max_width, self._labels_batch
                )
                if not self.hbm.plan(need, what="label build transient", evict=False):
                    # memory pressure: the build yields, serving state
                    # stays — same policy as GovernedSorter
                    self.maintenance.incr("label_device_build_skipped")
                else:
                    self.hbm.register("build", need)
                    try:
                        idx, info = label_build.device_build_labels(
                            snap,
                            max_width=self._labels_max_width,
                            landmarks=landmarks,
                            min_gain=self._labels_min_gain,
                            batch=self._labels_batch,
                            mesh=self._mesh if self._sharded else None,
                            shard_count=self._shard_count,
                            progress_cb=self._label_build_progress,
                        )
                    except Exception:
                        _log.warning(
                            "device label build failed; falling back to the "
                            "host path (entry-identical)",
                            exc_info=True,
                        )
                        self.maintenance.incr("label_device_build_errors")
                    else:
                        self.maintenance.incr("label_device_builds")
                        self.maintenance.observe_ms(
                            "label_build_device", idx.build_ms
                        )
                        self.maintenance.set_gauge(
                            "label_build_batches", info.batches
                        )
                        if info.truncated:
                            self._note_label_truncation(info.truncated, idx)
                        return idx
                    finally:
                        self.hbm.release("build")
        if landmarks == 0:
            landmarks = min(n, self.LABELS_AUTO_CAP)
        idx = build_labels(
            snap, max_width=self._labels_max_width, landmarks=landmarks
        )
        if landmarks < n:
            self._note_label_truncation("cap", idx)
        return idx

    def _label_build_progress(self, done: int, total: int, entries: int) -> None:
        """Batch-level narration for an in-flight label build: gauges
        BuildProgress/health read while the sweeps run."""
        self.maintenance.set_gauge("label_build_landmarks", done)
        self.maintenance.set_gauge("label_build_landmarks_total", total)
        self.maintenance.set_gauge("label_build_entries", entries)

    def _note_label_truncation(self, reason: str, idx) -> None:
        """Coverage truncation is a serving-quality event, not a silent
        default: count it by reason (``cap`` — the landmark budget, or
        ``min_gain`` — the marginal-coverage early exit) and log the
        achieved coverage so operators can see exactly what the depth
        tax falls back to BFS for."""
        self.maintenance.incr(f"label_build_truncated_{reason}")
        _log.warning(
            "label build truncated (%s): %d/%d landmarks processed, "
            "coverage_ratio=%.4f — uncovered deep checks fall back to the "
            "BFS kernel (bit-identically)",
            reason, idx.n_landmarks, idx.n, idx.coverage,
        )

    def _start_label_build(self, snap: GraphSnapshot) -> None:
        """The full-rebuild pipeline's labels phase, overlapped: kick
        the (device) label construction on a background thread so
        ``cache_save`` and the rest of the refresh's host work proceed
        while the sweeps run; the engine serves the fresh snapshot with
        the BFS fallback until the index installs under the lock.
        Synchronous when the index is already present (cache reload —
        placement is cheap), in multi-controller mode (background
        collectives must not interleave with serving dispatches across
        hosts), or when labels are off."""
        if not self._labels_enabled or self._labels_suspended:
            return
        if snap.labels is not None or self._multiprocess:
            with self.build_progress.phase("labels"):
                self._ensure_labels(snap)
            return

        def work():
            with self.build_progress.phase("labels"):
                try:
                    idx = self._build_label_index(snap)
                except Exception:
                    self.maintenance.incr("label_build_failures")
                    _log.warning(
                        "background label build failed; serving stays on "
                        "the BFS path",
                        exc_info=True,
                    )
                    return
            with self._lock:
                if (
                    self._closing
                    or self._labels_suspended
                    or not self._labels_enabled
                ):
                    return
                self._install_labels_locked(snap, idx)

        t = threading.Thread(target=work, name="label-build", daemon=True)
        self._label_build_thread = t
        t.start()

    def _install_labels_locked(self, snap: GraphSnapshot, idx) -> None:
        """Land a background-built index (caller holds the lock) — ONLY
        onto the exact snapshot it was built for. A later snapshot that
        merely matches on num_int is not safe: a fold or rebuild can
        change the interior edge set at the same node count, and a stale
        index would serve wrong denies. Deltas that extend ``snap``'s
        overlay in place are fine (the label path already gates on
        lab_dirty); if serving moved to a different snapshot object, the
        index is dropped and the next rebuild's build starts fresh."""
        if idx is None:
            return
        if snap.labels is None and snap.num_int == idx.n:
            snap.labels = idx
            self.maintenance.incr("label_builds")
            self.maintenance.observe_ms("label_build", idx.build_ms)
        if snap.labels is idx and self._snapshot is snap:
            self._ensure_labels(snap)

    def _label_build_wait(self) -> None:
        """Join the in-flight background label build (the
        ``labels_wait`` seam snapcache.save_snapshot invokes just before
        writing the label segments — everything before them overlaps
        the sweeps, and the saved cache still carries the index)."""
        t = self._label_build_thread
        if t is not None and t.is_alive():
            t.join()

    def labels_settled(self) -> bool:
        """Force the lazy snapshot refresh and block until the overlapped
        label build (if any) has installed. Serving never needs this —
        checks fall back to the BFS kernel bit-identically while the
        build is in flight — but deterministic consumers (tests, benches,
        warm-up hooks) use it to pin down the moment the label fast path
        is live. Returns whether the serving snapshot carries an index."""
        self.snapshot()
        self._label_build_wait()
        snap = self._snapshot
        return snap is not None and snap.labels is not None

    def _label_patcher(self, idx, snap, added_edges, visit_budget: int = 65536):
        """Compaction's incremental label patch, routed through the
        device sweep path when eligible (``device_patch_labels`` — the
        exact ``patch_labels`` semantics, including the abort outcome,
        as bit-packed lane sweeps) and through the host walk otherwise.
        None means the patch aborted and the caller must rebuild."""
        if self._labels_device_build:
            from keto_tpu.graph.device_build import device_available

            if (
                device_available()
                and self._interior_ell_slots(snap) >= self._labels_device_min_edges
            ):
                from keto_tpu.graph import label_build

                try:
                    return label_build.device_patch_labels(
                        idx, snap, added_edges, visit_budget=visit_budget,
                        batch=self._labels_batch,
                        mesh=self._mesh if self._sharded else None,
                        shard_count=self._shard_count,
                    )
                except Exception:
                    _log.warning(
                        "device label patch failed; retrying on the host "
                        "path (entry-identical)",
                        exc_info=True,
                    )
                    self.maintenance.incr("label_device_build_errors")
        from keto_tpu.graph.labels import patch_labels

        return patch_labels(idx, snap, added_edges, visit_budget=visit_budget)

    def _upload_labels(self, snap: GraphSnapshot) -> None:
        idx = snap.labels
        if idx is None:
            snap.device_labels = None
            snap.device_shard_labels = None
            return
        out_lab = np.ascontiguousarray(idx.out_lab)
        in_lab = np.ascontiguousarray(idx.in_lab)
        if self._sharded:
            # row-striped over the graph axis: the sharded intersection
            # kernel reconstructs each pair's two rows with a one-shot
            # psum exchange (keto_tpu/parallel/sharded.py)
            from keto_tpu.parallel import sharded as shard_mod

            out_sh, in_sh, rl, owned = shard_mod.route_labels(
                out_lab, in_lab, self._shard_count
            )
            snap.device_labels = None
            snap.device_shard_labels = self._guard_alloc(
                "labels-upload",
                lambda: (
                    jax.device_put(out_sh, self._shard_stack_sharding),
                    jax.device_put(in_sh, self._shard_stack_sharding),
                    rl,
                ),
            )
            self.hbm.register("labels", idx.device_bytes())
            self.hbm.register_shards("labels", owned)
            return
        if self._mesh is None:
            snap.device_labels = self._guard_alloc(
                "labels-upload",
                lambda: (jax.device_put(out_lab), jax.device_put(in_lab)),
            )
        else:
            # labels replicate: the rows are narrow (≤ max_width) and the
            # intersection kernel never touches the sharded bitmaps
            from jax.sharding import NamedSharding, PartitionSpec as P

            repl = NamedSharding(self._mesh, P())
            snap.device_labels = self._guard_alloc(
                "labels-upload",
                lambda: (
                    jax.device_put(out_lab, repl), jax.device_put(in_lab, repl)
                ),
            )
        self.hbm.register("labels", idx.device_bytes())

    def _labels_usable(self, snap: GraphSnapshot) -> bool:
        """Route checks through the label index on this snapshot? False
        while a pending overlay has mutated the interior (ELL) subgraph
        — counted ONCE per blocked overlay generation as a
        ``label_invalidations`` maintenance event."""
        if not self._labels_enabled or snap.labels is None:
            return False
        if snap.lab_dirty:
            if self._label_blocked_snap != snap.snapshot_id:
                self._label_blocked_snap = snap.snapshot_id
                self.maintenance.incr("label_invalidations")
                self.maintenance.set_gauge(
                    "label_dirty_nodes", len(snap.lab_dirty)
                )
            return False
        self.maintenance.set_gauge("label_dirty_nodes", 0)
        return self._labels_dev(snap) is not None

    def _warm_width_bytes(self, snap: GraphSnapshot, B: int) -> int:
        """Device bytes one warmed width holds live while its slice runs:
        the BFS workspace (~3 W-wide uint32 bitmaps over interior rows —
        the same formula ``_slice_cap`` budgets with)."""
        return (snap.num_int + 1) * 12 * (B // 32)

    def warm_compile(self) -> int:
        """Ahead-of-time compile of the slice-width ladder (BFS and
        label kernels) against the current snapshot's geometry, so the
        first real slice of every width hits the jit cache — and, with a
        persistent compilation cache configured (serve.compile_cache_dir),
        so the multi-second compile cost is paid once per binary instead
        of once per boot. Widths whose compiled-buffer footprint would
        breach the HBM budget are SKIPPED (never evicted for — warming is
        optional work) and counted in the ``warm_widths_skipped`` gauge /
        ``keto_hbm_warm_widths_skipped``. Returns the number of kernels
        warmed."""
        snap = self.snapshot()
        if snap.n_nodes == 0 or snap.n_edges == 0:
            return 0
        ni = snap.num_int
        warmed = 0
        skipped = 0
        warm_bytes = 0
        for B in self.stream_widths(snap):
            if self._closing:
                break  # teardown must never race an in-flight compile
            need = self._warm_width_bytes(snap, B)
            if not self.hbm.plan(need - warm_bytes, what=f"warm width {B}", evict=False):
                skipped += 1
                continue
            # the empty-batch geometry: every entry array at its minimum
            # pad (B), every row a dropped/padded sentinel — the same
            # static shapes a real B-query slice produces
            e_rows = np.full(B, ni + 1, np.int32)
            e_q = np.zeros(B, np.int32)
            a_rows = np.full(B, ni, np.int32)
            targets = np.full(B, ni, np.int32)
            packed = (e_rows, e_q, e_rows, e_q, a_rows, e_q, targets)
            if self._sharded and snap.device_shards is not None:
                dev = self._dispatch_sharded(snap, packed, self._it_cap)
                self._guard_alloc(
                    "warm-compile", lambda d=dev: d.dev.block_until_ready()
                )
            else:
                buf, sizes = pack_entries(packed)
                ov = snap.device_overlay
                self._guard_alloc(
                    "warm-compile",
                    lambda: _check_kernel(
                        snap.device_buckets,
                        jnp.asarray(buf),
                        ov_nbrs=None if ov is None else ov[0],
                        ov_dst=None if ov is None else ov[1],
                        sizes=sizes,
                        n_active=snap.num_active,
                        n_int=ni,
                        valid_rows=tuple(b.n for b in snap.buckets),
                        it_cap=self._it_cap,
                        block_iters=self._block_iters,
                        bitmap_sharding=self._bitmap_sharding
                        if self._mesh is not None and (B // 32) % self._mesh.shape.get("data", 1) == 0
                        else (self._bitmap_sharding_rows_only if self._mesh is not None else None),
                    ).block_until_ready(),
                )
            warmed += 1
            # one slice runs at a time: the warm family holds the WIDEST
            # warmed width's workspace, not the sum over widths
            warm_bytes = max(warm_bytes, need)
            self.hbm.register("warmup", warm_bytes)
            labs = self._labels_dev(snap)
            if self._labels_enabled and labs is not None:
                pairs = np.concatenate(
                    [np.full(B, ni, np.int32), np.full(B, ni, np.int32),
                     np.zeros(B, np.int32)]
                )
                if self._sharded:
                    from keto_tpu.parallel import sharded as shard_mod

                    self._guard_alloc(
                        "warm-compile",
                        lambda: shard_mod.label_kernel(self._mesh)(
                            labs[0], labs[1],
                            jax.device_put(pairs, self._shard_repl_sharding),
                            n_pairs=B, B=B, rl=labs[2],
                        ).block_until_ready(),
                    )
                else:
                    self._guard_alloc(
                        "warm-compile",
                        lambda: _label_kernel(
                            labs[0], labs[1],
                            jnp.asarray(pairs), n_pairs=B, B=B,
                        ).block_until_ready(),
                    )
                warmed += 1
        self.maintenance.set_gauge("warm_widths_skipped", skipped)
        return warmed

    # -- resolution ----------------------------------------------------------

    def _resolve_bulk(
        self, snap: GraphSnapshot, tuples: Sequence[RelationTuple]
    ) -> tuple[np.ndarray, np.ndarray, dict]:
        """Resolve every query to device rows (see ``_resolve_bulk_py`` for
        the result contract). Literal queries go through the C++ intern
        tables in one bulk call when the native library provides it;
        wildcard/pattern/unknown-namespace queries and the pure-Python
        interner use the host loop."""
        if hasattr(snap.interned, "resolve_queries"):
            got = self._resolve_bulk_native(snap, tuples)
            if got is not None:
                return got
        return self._resolve_bulk_py(snap, tuples)

    def _resolve_bulk_native(
        self, snap: GraphSnapshot, tuples: Sequence[RelationTuple]
    ):
        """Pack literal queries into the native wire format and resolve them
        in one C++ pass; route the rest through the per-query Python path.
        Returns None when the buffer framing is unsafe (separator bytes in
        strings) — callers fall back to the pure host loop."""
        n = len(tuples)
        nl = snap.num_live
        wild_ids = snap.wild_ns_ids
        nm = self._nm()
        ns_cache: dict = {}

        def _ns_bytes(name: str):
            """namespace name → decimal-ASCII id bytes, _WILD, or None."""
            hit = ns_cache.get(name, _UNSET)
            if hit is not _UNSET:
                return hit
            if name == "":
                r: object = _WILD
            else:
                try:
                    ns_id = nm.get_namespace_by_name(name).id
                    r = _WILD if ns_id in wild_ids else b"%d" % ns_id
                except ErrNamespaceUnknown:
                    r = None
            ns_cache[name] = r
            return r

        parts: list[bytes] = []
        ap = parts.append
        special: list[int] = []
        dead: list[int] = []  # guaranteed denies; placeholder results ignored
        #: queries whose start resolves normally but whose subject can't
        #: exist (empty-namespace subject set with no "" namespace
        #: configured): the placeholder subject may collide with a real
        #: node, so tg is forced unreachable after the bulk resolve
        no_target: list[int] = []
        for i, rt in enumerate(tuples):
            ns = _ns_bytes(rt.namespace)
            if ns is None:
                dead.append(i)  # unknown namespace → denied
                ap(_PLACEHOLDER)
                continue
            obj, rel = rt.object, rt.relation
            if ns is _WILD or obj == "" or rel == "":
                special.append(i)  # wildcard pattern → host resolver
                ap(_PLACEHOLDER)
                continue
            sub = rt.subject
            if type(sub) is SubjectID:
                ap(b"%b\x1f%b\x1f%b\x1f1\x1f%b\x1f\x1f\x1e"
                   % (ns, obj.encode(), rel.encode(), sub.id.encode()))
            elif isinstance(sub, SubjectSet):
                sns = _ns_bytes(sub.namespace)
                if sns is None:
                    dead.append(i)  # unknown subject namespace → denied
                    ap(_PLACEHOLDER)
                    continue
                if sns is _WILD:
                    # subjects match LITERALLY (host-loop parity:
                    # _subject_target) — an empty subject namespace can
                    # only equal a stored subject in a namespace named
                    # "", so resolve against that namespace's id rather
                    # than routing the whole query to the pattern path
                    # (which the host loop does NOT do when the start is
                    # literal; the divergence was the tier-1
                    # bulk-resolve parity failure)
                    wild_list = list(wild_ids)
                    if not wild_list:
                        # no namespace named "": the target cannot exist
                        # — resolve the start normally, force tg = -1
                        no_target.append(i)
                        ap(b"%b\x1f%b\x1f%b\x1f1\x1f\x1f\x1f\x1e"
                           % (ns, obj.encode(), rel.encode()))
                        continue
                    sns = b"%d" % wild_list[0]
                ap(b"%b\x1f%b\x1f%b\x1f0\x1f%b\x1f%b\x1f%b\x1e"
                   % (ns, obj.encode(), rel.encode(), sns,
                      sub.object.encode(), sub.relation.encode()))
            else:
                dead.append(i)  # nil subject → denied
                ap(_PLACEHOLDER)
        buf = b"".join(parts)
        # separator bytes inside strings corrupt framing — detectable as a
        # field-count mismatch, same check as the ingest path
        if buf.count(b"\x1f") != 6 * n or buf.count(b"\x1e") != n:
            return None
        got = snap.interned.resolve_queries(buf, n)
        if got is None:
            return None
        start_raw, sub_raw = got
        r2d = snap.raw2dev
        sd = np.where(start_raw >= 0, r2d[np.clip(start_raw, 0, None)], -1)
        t = r2d[np.clip(sub_raw, 0, None)]
        # a target only matters when the query has starts (matches the host
        # loop, which leaves tg at the unreachable row for start-less denies)
        tg = np.where((sub_raw >= 0) & (t < nl) & (sd >= 0), t, -1)
        if dead:
            # placeholder records may coincide with real nodes — force deny
            di = np.asarray(dead)
            sd[di] = -1
            tg[di] = -1
        if no_target:
            tg[np.asarray(no_target)] = -1
        multi: dict = {}
        if special:
            self._resolve_specials(snap, tuples, special, sd, tg, multi)
        if (
            snap.ov_set_ids
            or snap.ov_leaf_ids
            or getattr(snap.interned, "has_ext", False)
        ):
            # nodes created since the base build — overlay nodes, or
            # compaction-folded extension nodes (interner.ExtendedInterned)
            # — are invisible to the resident C++ tables: re-resolve the
            # queries whose start or target missed through the
            # extension-aware host path, in ONE bulk call (tg == -1
            # includes every guaranteed deny, so deny-heavy workloads
            # would otherwise loop per query)
            done = set(special) | set(dead)
            miss = [
                int(i)
                for i in np.nonzero((sd == -1) | (tg == -1))[0]
                if int(i) not in done
            ]
            if miss:
                s1, t1, m1 = self._resolve_bulk_py(snap, [tuples[i] for i in miss])
                for j, i in enumerate(miss):
                    sd[i] = s1[j]
                    tg[i] = t1[j]
                    if j in m1:
                        multi[i] = m1[j]
        return sd, tg, multi

    def _ns_resolver(self):
        """Per-batch namespace-name → id resolver with a cache: ``None`` =
        unknown (→ denied, engine.go:76-77), ``WILDCARD`` = empty name."""
        nm = self._nm()
        cache: dict = {}

        def _ns(name: str):
            hit = cache.get(name, _UNSET)
            if hit is not _UNSET:
                return hit
            if name == "":
                r: object = WILDCARD
            else:
                try:
                    r = nm.get_namespace_by_name(name).id
                except ErrNamespaceUnknown:
                    r = None
            cache[name] = r
            return r

        return _ns

    def _subject_target(self, snap: GraphSnapshot, rt: RelationTuple, ns_of):
        """Resolve a query's subject to its target device row: the id, -1
        when no such node exists (target unreachable), or ``None`` when the
        subject itself forces a deny (nil subject, unknown subject
        namespace)."""
        interned = snap.interned
        raw2dev = snap.raw2dev
        sub = rt.subject
        if type(sub) is SubjectID:
            rawl = interned.resolve_leaf(sub.id)
            if rawl >= 0:
                return int(raw2dev[rawl + snap.num_sets])
            ov_leaf = snap.ov_leaf_ids
            return ov_leaf.get(sub.id, -1) if ov_leaf else -1
        if isinstance(sub, SubjectSet):
            sns_id = ns_of(sub.namespace)
            if sns_id is None:
                return None
            if sns_id == WILDCARD:
                # subjects are matched literally; an empty subject
                # namespace can only equal a stored subject in a
                # namespace named ""
                wild_list = list(snap.wild_ns_ids)
                if not wild_list:
                    return -1
                skey = (wild_list[0], sub.object, sub.relation)
            else:
                skey = (sns_id, sub.object, sub.relation)
            rawt = interned.resolve_set(*skey)
            if rawt >= 0:
                return int(raw2dev[rawt])
            ov_set = snap.ov_set_ids
            return ov_set.get(skey, -1) if ov_set else -1
        return None  # nil subject → denied

    def _resolve_specials(self, snap, tuples, indices, sd, tg, multi):
        """Wildcard/pattern queries, resolved in bulk: namespace names go
        through one cache, starts through the snapshot's family-grouped
        sorted indexes (``GraphSnapshot.resolve_starts_bulk`` — one
        vectorized searchsorted pass per pattern family instead of a
        per-query probe), subjects literally. Results splice into the
        caller's bulk arrays."""
        _ns = self._ns_resolver()
        live: list[int] = []
        pats: list[tuple] = []
        for i in indices:
            rt = tuples[i]
            ns_id = _ns(rt.namespace)
            if ns_id is None:
                continue  # unknown namespace → denied
            live.append(i)
            pats.append((ns_id, rt.object, rt.relation))
        if not live:
            return
        starts_l = snap.resolve_starts_bulk(pats)
        ni = snap.num_int
        sbase = snap.sink_base
        nl = snap.num_live
        for i, starts in zip(live, starts_l):
            if starts.size == 0:
                continue  # no matching start node → denied
            t = self._subject_target(snap, tuples[i], _ns)
            if t is None:
                continue  # nil subject / unknown subject namespace → denied
            if 0 <= t < nl or (t >= nl and snap.is_answerable_target(t)):
                tg[i] = t
            sd[i] = -2
            # interior starts seed the bitmap; sink starts (no out-edges)
            # contribute nothing; peeled/static starts are host-propagated
            # at pack time (pack_chunk)
            multi[i] = (
                starts[starts < ni],
                starts[((starts >= ni) & (starts < sbase)) | (starts >= nl)],
            )

    def _resolve_bulk_py(
        self, snap: GraphSnapshot, tuples: Sequence[RelationTuple]
    ) -> tuple[np.ndarray, np.ndarray, dict]:
        """One tight host pass resolving every query to device rows.

        Returns ``(sd, tg, multi)``:

        - ``sd[i]`` — the query's single start row: ``-1`` no start
          (guaranteed deny: unknown namespace per engine.go:76-77, or no
          matching node), ``-2`` multi-start (wildcard pattern, rows in
          ``multi``), else a device id (live or static);
        - ``tg[i]`` — target row, or ``-1`` when unreachable (static row,
          or no such node). -1 — not a node-id sentinel like ``num_live``
          — because every id can be legitimate: in a base graph with zero
          static nodes the first overlay node gets device id num_live,
          and a node-id sentinel would collide with it in the walk's
          target-hit check and the answer-gather key match;
        - ``multi`` — ``{i: (live start rows, host-propagated start rows
          — peeled/static, expanded at pack time)}`` for wildcard-pattern
          queries.

        The common case (literal query, SubjectID) costs two intern-table
        lookups and two ``raw2dev`` reads — no numpy allocation. Pattern
        queries defer to ``_resolve_specials``'s bulk family resolver.
        """
        n = len(tuples)
        nl = snap.num_live
        sd = np.full(n, -1, np.int64)
        tg = np.full(n, -1, np.int64)
        multi: dict = {}
        interned = snap.interned
        resolve_set = interned.resolve_set
        raw2dev = snap.raw2dev
        wild_ids = snap.wild_ns_ids
        ov_set = snap.ov_set_ids or {}
        _ns = self._ns_resolver()

        special: list[int] = []
        for i, rt in enumerate(tuples):
            ns_id = _ns(rt.namespace)
            if ns_id is None:
                continue  # unknown namespace → denied (engine.go:76-77)
            obj, rel = rt.object, rt.relation
            if ns_id == WILDCARD or ns_id in wild_ids or obj == "" or rel == "":
                special.append(i)  # wildcard pattern → bulk family resolver
                continue
            raw = resolve_set(ns_id, obj, rel)
            if raw >= 0:
                start_dev = int(raw2dev[raw])
            else:
                start_dev = ov_set.get((ns_id, obj, rel), -1) if ov_set else -1
                if start_dev < 0:
                    continue
            t = self._subject_target(snap, rt, _ns)
            if t is None:
                continue  # nil subject / unknown subject namespace → denied
            if 0 <= t < nl or (t >= nl and snap.is_answerable_target(t)):
                tg[i] = t
            sd[i] = start_dev
        if special:
            self._resolve_specials(snap, tuples, special, sd, tg, multi)
        return sd, tg, multi

    # -- public API ----------------------------------------------------------

    def batch_check(
        self,
        tuples: Sequence[RelationTuple],
        *,
        at_least: Optional[int] = None,
        mode: str = "latest",
    ) -> list[bool]:
        """Answer every query: slices pipeline resolve→pack→dispatch (host
        work on slice k+1 overlaps device execution of slice k — dispatch is
        async), then all packed outputs concatenate on device and fetch
        ONCE. D2H transfer latency (not bandwidth, not dispatch) dominates
        end-to-end time on tunneled devices, so the whole request ships 1
        bit per query in a single transfer.

        Consistency (the real semantics of the snaptoken/latest fields the
        reference documents but stubs, proto check_service.proto:39-75):
        ``mode="latest"`` (default) is read-your-writes; ``at_least=w``
        serves any snapshot ≥ w (the caller's snaptoken); ``mode="serving"``
        never stalls — see ``snapshot_serving``."""
        return self.batch_check_with_token(tuples, at_least=at_least, mode=mode)[0]

    def batch_check_with_token(
        self,
        tuples: Sequence[RelationTuple],
        *,
        at_least: Optional[int] = None,
        mode: str = "latest",
    ) -> tuple[list[bool], int]:
        """``batch_check`` plus the id of the snapshot that produced the
        decisions — the snaptoken the API returns to callers.

        Degraded mode: when the device path has failed repeatedly, checks
        transparently fall back to the CPU reference engine (bit-identical
        decisions, reference throughput) and the health state machine
        reports DEGRADED; the device path is re-probed periodically and
        recovery is automatic. Multi-controller meshes never fall back —
        hosts diverging on the execution path is a lockstep violation, so
        device failures there fail loudly instead."""
        if self._should_fallback():
            return self._fallback_check(tuples)
        snap = self._snapshot_for(at_least, mode)
        if self._lockstep_verify:
            from keto_tpu.parallel.lockstep import verify_lockstep

            # BEFORE the empty-graph early-out: hosts disagreeing on
            # whether the graph is empty is exactly the divergence that
            # must fail loudly rather than skew answers silently. The
            # fingerprint covers the shard geometry too: a sharded
            # program dispatched with mismatched shard counts would hang
            # mismatched collectives, the failure lockstep exists to
            # pre-empt.
            verify_lockstep(snap.snapshot_id, tuples, shards=self._shard_count)
        if snap.n_nodes == 0 or snap.n_edges == 0 or not tuples:
            return [False] * len(tuples), snap.snapshot_id
        try:
            out, max_iters = self._run_exact(snap, tuples)
        except Exception as e:
            if self._multiprocess or isinstance(e, KetoError):
                raise
            self._note_device_error(e)
            return self._fallback_check(tuples)
        self._note_device_ok()
        self._after_batch(max_iters)
        self._audit_sample(tuples, out, snap.snapshot_id)
        return out.tolist(), snap.snapshot_id

    def _cap_limit(self, snap: GraphSnapshot) -> int:
        """Iteration count that can NEVER truncate: monotone bitmaps reach
        the fixpoint in at most one pull per active row (each growing pull
        sets ≥ 1 new bit in some active row), +1 for the convergence
        observation."""
        return snap.num_active + 1

    def _run_exact(
        self, snap: GraphSnapshot, tuples: Sequence[RelationTuple], it_cap: Optional[int] = None
    ) -> tuple[np.ndarray, int]:
        """Dispatch + collect with the EXACTNESS guarantee the reference's
        visited-set termination gives for free: a truncated kernel (frontier
        still growing at it_cap) never decides a query. Affected queries
        re-run with an escalating cap, bounded by ``_cap_limit`` — the
        final rung cannot truncate, so every decision comes from a true
        fixpoint."""
        cap = it_cap or self._it_cap
        results = list(self._dispatch_slices(snap, tuples, it_cap=cap))
        out, max_iters, trunc_idx = self._collect(results, len(tuples))
        if trunc_idx:
            limit = self._cap_limit(snap)
            if cap >= limit:
                # mathematically unreachable; fail loudly rather than
                # return a possibly-wrong deny
                raise RuntimeError(
                    f"BFS truncated at the fixpoint bound (cap={cap}, "
                    f"active rows={snap.num_active})"
                )
            new_cap = min(max(cap * 8, 8), limit)
            _log.info(
                "check BFS hit it_cap=%d; re-running %d queries exactly at cap=%d",
                cap, len(trunc_idx), new_cap,
            )
            redo, redo_iters = self._run_exact(
                snap, [tuples[i] for i in trunc_idx], it_cap=new_cap
            )
            out[np.asarray(trunc_idx)] = redo
            max_iters = max(max_iters, redo_iters)
        return out, max_iters

    def batch_check_stream(
        self,
        tuples_iter,
        *,
        depth: Optional[int] = None,
        slice_cap: Optional[int] = None,
        at_least: Optional[int] = None,
        mode: str = "latest",
        ordered: bool = True,
    ):
        """Streaming check: consume an iterable of RelationTuples, yield
        decision slices while keeping at most ``depth`` slices in flight
        (flat memory for arbitrarily long streams — BASELINE config 5's
        1M-check batches never materialize device state for more than
        ``depth`` slices).

        The pipeline is latency-adaptive and lands slices in READY order:

        - slice widths follow ``StreamSliceController``: narrowed toward
          ``stream_slice_target_ms`` when kernels/transfers run slow,
          re-widened when headroom returns — instead of the
          throughput-only memory-derived maximum. ``slice_cap`` still
          bounds them from above. (Multi-controller meshes pin the fixed
          bound: slice geometry must be identical on every host.)
        - the dispatch window is decoupled from landing: host resolve/pack
          of slice k+2 proceeds while k+1 executes and k transfers, and an
          early-finished slice is unpacked the moment its
          ``copy_to_host_async`` completes — no head-of-line blocking on
          a straggler.
        - ``ordered=True`` (default) preserves the yield contract — numpy
          ``bool[slice]`` arrays in request order, via an in-order
          delivery buffer. ``ordered=False`` is the fast path for callers
          that re-associate results by index (e.g. ``CheckBatcher``): it
          yields ``(offset, bool[slice])`` the moment each slice lands,
          where ``offset`` is the stream index of the slice's first query.

        Per-slice service times are recorded in ``stream_slice_stats``
        (x/telemetry.DurationStats): the width controller and bench.py
        read the same numbers.
        """
        gen, _ = self.batch_check_stream_with_token(
            tuples_iter, depth=depth, slice_cap=slice_cap,
            at_least=at_least, mode=mode, ordered=ordered,
        )
        return gen

    def batch_check_stream_with_token(
        self,
        tuples_iter,
        *,
        depth: Optional[int] = None,
        slice_cap: Optional[int] = None,
        at_least: Optional[int] = None,
        mode: str = "latest",
        ordered: bool = True,
        with_info: bool = False,
    ):
        """``batch_check_stream`` plus the deciding snapshot's id, resolved
        eagerly so serving callers can attach the snaptoken to responses
        they assemble as slices land. Returns ``(generator, token)``.

        ``with_info=True`` (requires ``ordered=False``) widens each yield
        to ``(offset, decisions, info)`` where ``info`` describes the
        slice that landed: ``width`` (queries), ``bfs_steps``, ``route``
        (``label`` | ``hybrid`` | ``bfs`` | ``host`` | ``cpu``),
        ``service_ms``, and — in sharded mode — ``halo_rounds`` /
        ``halo_bytes``. The CheckBatcher stamps this onto every rider's
        request timeline.

        In degraded mode the stream is served by the CPU reference engine
        with the same yield contract (see ``batch_check_with_token`` for
        the fallback semantics)."""
        if with_info and ordered:
            raise ValueError("with_info requires ordered=False")
        if self._should_fallback():
            return self._fallback_stream(
                tuples_iter, ordered=ordered, with_info=with_info
            )
        snap = self._snapshot_for(at_least, mode)
        gen = self._stream(
            snap, tuples_iter, depth=depth, slice_cap=slice_cap,
            ordered=ordered, with_info=with_info,
        )
        return self._guard_stream(gen), snap.snapshot_id

    def label_witness_info(
        self, rt: RelationTuple, *, at_least: Optional[int] = None,
        mode: str = "latest",
    ) -> Optional[dict]:
        """Explain-path enrichment (keto_tpu/explain): the winning landmark
        of the 2-hop label intersection for ``rt``'s (start, target) pair —
        the hub node the label route's proof went through — or None when
        the pair isn't label-resolvable (labels off/dirty, wildcard query,
        non-interior endpoint). Reads the device arrays through the
        ``label_step_witness`` argmin kernel when they are resident, else
        the host index — entry-identical by construction. Only the explain
        endpoint calls this; the check hot path never does."""
        if not self._labels_enabled:
            return None
        try:
            snap = self._snapshot_for(at_least, mode)
        except Exception:
            return None
        idx = snap.labels
        if idx is None:
            return None
        try:
            sd, tg, multi = self._resolve_bulk(snap, [rt])
        except Exception:
            return None
        if 0 in multi:
            return None  # wildcard pattern: no single (start, target) pair
        a, b = int(sd[0]), int(tg[0])
        ni = snap.num_int
        if a < 0 or b < 0 or a >= ni or b >= ni:
            return None
        lm: Optional[int] = None
        dl = self._labels_dev(snap)
        if dl is not None and not self._sharded:
            try:
                got = int(
                    np.asarray(
                        _label_witness_kernel(
                            dl[0], dl[1],
                            jnp.asarray(np.array([a], np.int32)),
                            jnp.asarray(np.array([b], np.int32)),
                        )
                    )[0]
                )
                lm = got if got >= 0 else None
            except Exception:
                lm = None
        if lm is None:
            lm = idx.witness_landmark(a, b)
        if lm is None:
            return None
        info: dict = {"kind": "2-hop-label", "pair": [a, b], "landmark_dev": int(lm)}
        try:
            kind, key = snap.key_of_dev(int(lm))
            if kind == "set":
                ns_id, obj, rel = key
                name = next(
                    (n.name for n in self._nm().namespaces() if n.id == ns_id), ""
                )
                info["landmark"] = f"{name}:{obj}#{rel}"
            else:
                info["landmark"] = str(key)
        except Exception:  # keto-analyze: ignore[KTA401] landmark naming is best-effort enrichment; the numeric id in landmark_dev already carries the answer
            pass
        return info

    @staticmethod
    def _slice_ready(dev) -> bool:
        """Has this slice's async device→host copy completed? Host-only
        slices are always ready. A seam on purpose: skew tests patch it to
        force adversarial landing orders."""
        if dev is None:
            return True
        ready = getattr(dev, "is_ready", None)
        return True if ready is None else bool(ready())

    def stream_widths(self, snap: GraphSnapshot) -> list[int]:
        """The slice-width ladder the adaptive stream can choose from on
        this snapshot (ascending) — callers pre-warm jit geometries by
        running one batch per width."""
        cap = self._slice_cap(snap)
        return [32 * w for w in self._word_widths() if 32 * w <= cap]

    def _stream(
        self, snap, tuples_iter, *, depth, slice_cap, ordered,
        with_info: bool = False,
    ):
        depth = depth or self._dispatch_window
        bound = self._slice_cap(snap)
        if slice_cap:
            bound = min(bound, slice_cap)
        # multi-controller lockstep: every host must dispatch identical
        # slice geometries, and adaptive widths are a per-host latency
        # measurement — pin the deterministic fixed bound instead
        ctrl = None if self._multiprocess else self.stream_ctrl
        stats = self.stream_slice_stats
        lockstep = self._lockstep_verify
        if lockstep:
            from keto_tpu.parallel.lockstep import verify_lockstep
        it = iter(tuples_iter)
        max_iters = 0
        t_prev_ready = time.perf_counter()

        def slices():
            off = 0
            while True:
                cap = min(bound, ctrl.cap()) if ctrl is not None else bound
                batch = list(itertools.islice(it, cap))
                if not batch:
                    return
                if lockstep:
                    # per stream slice, BEFORE any dispatch (same contract
                    # as batch_check_with_token): divergence fails loudly
                    verify_lockstep(
                        snap.snapshot_id, batch, shards=self._shard_count
                    )
                if snap.n_nodes == 0 or snap.n_edges == 0:
                    yield (
                        off, None, np.zeros(len(batch), dtype=bool),
                        len(batch), batch, [], 0,
                    )
                    off += len(batch)
                    continue
                for dev, host_ans, nq, chunk, leases, n_ent in (
                    self._dispatch_slices(snap, batch)
                ):
                    yield off, dev, host_ans, nq, chunk, leases, n_ent
                    off += nq

        def land(rec):
            # unpack one slice (blocks iff its transfer hasn't finished);
            # a truncated frontier re-runs exactly, mid-stream
            nonlocal max_iters, t_prev_ready
            _seq, off, dev, host_ans, nq, chunk, leases, n_ent, t_disp = rec
            try:
                out, iters, truncated = self._unpack_slice(dev, host_ans, nq)
            finally:
                # the device output is fetched (or the slice failed and
                # will be re-answered elsewhere): the H2D staging copy is
                # over, the buffers may be re-leased
                self._stage_release(leases)
            if dev is not None and not (
                isinstance(dev, _HybridSlice) and dev.bfs_dev is None
            ):
                self.bfs_steps_stats.observe(float(iters))
            if truncated:
                out, redo_iters = self._run_exact(
                    snap, chunk, it_cap=min(
                        max(self._it_cap * 8, 8), self._cap_limit(snap)
                    ),
                )
                iters = max(iters, redo_iters)
            max_iters = max(max_iters, iters)
            now = time.perf_counter()
            # the service time attributable to THIS slice: dispatch→ready
            # when the pipeline ran dry, ready→ready interval when
            # saturated (both equal the caller-visible inter-yield gap)
            ms = (now - max(t_disp, t_prev_ready)) * 1e3
            t_prev_ready = now
            stats.observe(ms)
            if dev is None:
                route = "host"
            elif isinstance(dev, _HybridSlice):
                route = "label" if dev.bfs_dev is None else "hybrid"
            else:
                route = "bfs"
            if ctrl is not None:
                ctrl.observe(
                    nq, ms, route=route, bfs_steps=int(iters), entries=n_ent
                )
            self._note_route(route, nq, ms)
            self._audit_sample(chunk, out, snap.snapshot_id)
            if not with_info:
                return off, out
            # per-slice route/cost description for request timelines:
            # which kernel answered and what it did (the stats words the
            # kernels already carry, threaded per request instead of
            # summed into counters)
            info = {
                "width": nq,
                "bfs_steps": int(iters),
                "route": route,
                "service_ms": round(ms, 3),
            }
            halo_src = None
            if isinstance(dev, _ShardedSlice):
                halo_src = dev
            elif isinstance(dev, _HybridSlice) and isinstance(
                dev.bfs_dev, _ShardedSlice
            ):
                halo_src = dev.bfs_dev
            if halo_src is not None:
                # one frontier all-gather per real BFS hop: rounds ==
                # the slice's iteration count, bytes == rounds x the
                # per-round slab cost the dispatch recorded
                info["halo_rounds"] = int(iters)
                info["halo_bytes"] = int(iters) * halo_src.halo_bytes_per_round
            return off, out, info

        src = slices()
        exhausted = False
        inflight: list = []
        done: dict[int, tuple[int, np.ndarray]] = {}  # landed, awaiting in-order yield
        seq = 0
        next_seq = 0
        try:
            while True:
                # keep the dispatch window full: resolve/pack/dispatch is host
                # work that overlaps device execution of every in-flight slice
                while not exhausted and len(inflight) < depth:
                    nxt = next(src, None)
                    if nxt is None:
                        exhausted = True
                        break
                    off, dev, host_ans, nq, chunk, leases, n_ent = nxt
                    if dev is not None:
                        dev.copy_to_host_async()
                    inflight.append((
                        seq, off, dev, host_ans, nq, chunk, leases, n_ent,
                        time.perf_counter(),
                    ))
                    seq += 1
                if not inflight and exhausted:
                    break
                # ready-order landing: every finished slice unpacks now — an
                # early finisher never waits behind a straggler's transfer
                progressed = False
                still = []
                for rec in inflight:
                    if self._slice_ready(rec[2]):
                        res = land(rec)
                        if ordered:
                            done[rec[0]] = res
                        else:
                            yield res
                        progressed = True
                    else:
                        still.append(rec)
                inflight = still
                if ordered:
                    while next_seq in done:
                        yield done.pop(next_seq)[1]
                        next_seq += 1
                if not progressed and inflight and (exhausted or len(inflight) >= depth):
                    # nothing ready and the window is full (or input is done):
                    # block on the oldest slice — in ordered mode it is the
                    # next to deliver anyway
                    rec = inflight.pop(0)
                    res = land(rec)
                    if ordered:
                        done[rec[0]] = res
                        while next_seq in done:
                            yield done.pop(next_seq)[1]
                            next_seq += 1
                    else:
                        yield res
        finally:
            # a failed or abandoned stream discards its in-flight
            # outputs — their staging buffers may recycle (the same
            # discarded-computation argument as _collect's error path;
            # _stage_release empties each lease list, so a record whose
            # land() already released is a no-op here)
            for rec in inflight:
                self._stage_release(rec[6])
        self._after_batch(max_iters)

    def _slice_cap(self, snap: GraphSnapshot) -> int:
        """Queries per device slice: the widest bitmap the workspace budget
        allows (~3 W-wide uint32 bitmaps over interior rows — huge graphs
        narrow the batch width before the default max_batch could overshoot
        HBM)."""
        widths = self._word_widths()
        w_cap = next(
            (
                w
                for w in reversed(widths)
                if (snap.num_int + 1) * 12 * w <= self._mem_budget
            ),
            widths[0],
        )
        return min(self._max_batch, 32 * w_cap)

    def _entry_counts(
        self, snap: GraphSnapshot, sd: np.ndarray, tg: np.ndarray, multi: dict
    ) -> np.ndarray:
        """Per-query device entry counts (seeds + answer gathers) of a
        resolved slice — the scatter/gather work a query adds to a kernel.
        Host-propagated starts are estimated at one hop of out-degree (the
        peeled closure is not walked here; this only balances sub-chunk
        boundaries)."""
        n = sd.shape[0]
        ni = snap.num_int
        sbase = snap.sink_base
        nl = snap.num_live
        ip = snap.fwd_indptr
        sp_ = snap.sink_indptr
        cnt = np.zeros(n, np.int64)
        m_int = (sd >= 0) & (sd < ni)
        cnt[m_int] = 1
        m_host = ((sd >= ni) & (sd < sbase)) | (sd >= nl)
        if m_host.any():
            s = sd[m_host]
            in_b = s < snap.n_base_nodes
            c = np.ones(s.shape[0], np.int64)  # overlay adjacency ≈ small
            sb_ = s[in_b]
            c[in_b] = ip[sb_ + 1] - ip[sb_]
            cnt[m_host] = c
        has_start = m_int | m_host
        for i, (live, hostp) in multi.items():
            cnt[i] = live.size + hostp.size
            has_start[i] = live.size > 0 or hostp.size > 0
        m_ans = has_start & (tg >= sbase) & (tg < nl)
        if m_ans.any():
            t = tg[m_ans] - sbase
            cnt[m_ans] += sp_[t + 1] - sp_[t]
        return cnt

    def _dispatch_slices(
        self,
        snap: GraphSnapshot,
        tuples: Sequence[RelationTuple],
        it_cap: Optional[int] = None,
    ):
        """Resolve + pack + dispatch ``tuples`` in ``_slice_cap`` query
        slices, yielding ``[dev_out | None, host_ans, nq, chunk_tuples]``
        records as each slice is enqueued (the device chews on earlier
        slices meanwhile; chunk_tuples lets a truncated slice re-run).

        A slice whose resolved fan-out exceeds the entry budget (wildcard
        patterns, high-out-degree static starts) is sub-chunked so entry
        arrays stay within the {B, 2B, 4B} pad geometries — workload can't
        force unbounded allocations or fresh kernel geometries (a single
        monster query still falls through to ``_entry_pad``'s pow2
        fallback; there is no smaller unit to split). The budget is the
        smaller of the geometric 4·B bound and the slice controller's
        PREDICTED-service-time budget (``entry_budget``): a chunk the
        model predicts slow splits BEFORE dispatch, and the stream's
        ready-order window interleaves its sub-slices with fast ones —
        the pre-dispatch half of the slice-tail control loop.

        Yields ``[dev, host_ans, nq, chunk_tuples, leases, n_entries]``;
        ``leases`` are staging buffers released only once the slice has
        landed, ``n_entries`` feeds the controller's entry-cost model."""
        cap_q = self._slice_cap(snap)
        n = len(tuples)
        for s0 in range(0, n, cap_q):
            s1 = min(s0 + cap_q, n)
            sd, tg, multi = self._resolve_bulk(snap, tuples[s0:s1])
            nq = s1 - s0
            W = next(w for w in _WORD_WIDTHS if 32 * w >= nq)
            B = 32 * W
            cap_e = 4 * B
            if not self._multiprocess:
                # service-time-aware split bound (never below one B —
                # the geometric floor keeps slice counts bounded)
                budget = self.stream_ctrl.entry_budget()
                if budget is not None:
                    cap_e = min(cap_e, max(B, budget))
            cnt = self._entry_counts(snap, sd, tg, multi)
            if int(cnt.sum()) <= cap_e:
                bounds = [(0, nq)]
            else:
                csum = np.concatenate([np.zeros(1, np.int64), np.cumsum(cnt)])
                bounds = []
                i0 = 0
                while i0 < nq:
                    i1 = int(np.searchsorted(csum, csum[i0] + cap_e, side="right")) - 1
                    i1 = max(i0 + 1, min(i1, nq))
                    bounds.append((i0, i1))
                    i0 = i1
            use_labels = self._labels_usable(snap)
            for a, b in bounds:
                # sub-chunks keep the slice width: queries pad, geometry stays
                if use_labels:
                    dev, host_ans, leases = self._device_batch_labeled(
                        snap, sd, tg, multi, a, b, W, it_cap=it_cap
                    )
                else:
                    dev, host_ans, leases = self._device_batch(
                        snap, sd, tg, multi, a, b, W, it_cap=it_cap
                    )
                yield [
                    dev, host_ans, b - a, tuples[s0 + a : s0 + b],
                    leases, int(cnt[a:b].sum()),
                ]

    @staticmethod
    def _decode_packed(f: np.ndarray, host_ans: np.ndarray, nq: int):
        """Decode one kernel's packed ``uint32[W+2]`` output (decision
        bits, iteration count, truncation flag — the single place that
        knows the layout check_step emits): device bits ∪ host-decided
        grants. Returns ``(bool[nq], iters, truncated)``."""
        W = f.shape[0] - 2
        lanes = np.arange(32, dtype=np.uint32)
        bits = ((f[:W, None] >> lanes) & 1).astype(bool).ravel()[:nq]
        return bits | host_ans[:nq], int(f[W]), bool(f[W + 1])

    @staticmethod
    def _decode_label_bits(f: Optional[np.ndarray], nq: int) -> np.ndarray:
        """Label kernel output ``uint32[W]`` → bool[nq] (None → zeros)."""
        if f is None:
            return np.zeros(nq, bool)
        lanes = np.arange(32, dtype=np.uint32)
        return ((f[:, None] >> lanes) & 1).astype(bool).ravel()[:nq]

    @staticmethod
    def _decode_packed_sharded(f: np.ndarray, host_ans: np.ndarray, nq: int):
        """Decode one sharded kernel's packed ``uint32[W+3]`` output
        (decision bits, iterations, truncation, frontier-bit population
        — keto_tpu/parallel/sharded.py). Returns ``(bool[nq], iters,
        truncated, frontier_bits)``."""
        W = f.shape[0] - 3
        lanes = np.arange(32, dtype=np.uint32)
        bits = ((f[:W, None] >> lanes) & 1).astype(bool).ravel()[:nq]
        return bits | host_ans[:nq], int(f[W]), bool(f[W + 1]), int(f[W + 2])

    def _decode_bfs(self, f, host_ans, nq, halo_bytes_per_round=None):
        """Decode one fetched BFS output of either flavor; sharded
        outputs additionally feed the keto_shard_* counters (one halo
        exchange per real hop). Returns ``(bool[nq], iters, trunc)``."""
        if halo_bytes_per_round is not None:
            bits, it, tr, fb = self._decode_packed_sharded(f, host_ans, nq)
            self._note_sharded_stats(it, fb, halo_bytes_per_round)
            return bits, it, tr
        return self._decode_packed(f, host_ans, nq)

    @staticmethod
    def _raw_dev(part):
        """The raw device array behind a slice part (``_ShardedSlice``
        wraps one; everything else IS one)."""
        return part.dev if isinstance(part, _ShardedSlice) else part

    @staticmethod
    def _bfs_halo(part) -> Optional[int]:
        return (
            part.halo_bytes_per_round
            if isinstance(part, _ShardedSlice)
            else None
        )

    def _decode_hybrid(self, lab, bfs, bfs_pos, host_ans, nq, bfs_halo=None):
        """Decode one label-routed slice from fetched arrays: label bits
        for the whole slice, BFS sub-batch bits scattered onto their
        positions. Only the BFS part can truncate."""
        out = self._decode_label_bits(lab, nq)
        iters, trunc = 0, False
        if bfs is not None:
            bits2, iters, trunc = self._decode_bfs(
                bfs, host_ans[bfs_pos], bfs_pos.size, bfs_halo
            )
            out[bfs_pos] = bits2
        return out | host_ans[:nq], iters, trunc

    def _unpack_slice(self, dev, host_ans, nq):
        """One slice's decisions. Returns ``(bool[nq], iters, truncated)``."""
        if dev is None:
            return host_ans[:nq], 0, False
        if isinstance(dev, _HybridSlice):
            lab = (
                jax.device_get(dev.label_dev)
                if dev.label_dev is not None
                else None
            )
            bfs = (
                jax.device_get(self._raw_dev(dev.bfs_dev))
                if dev.bfs_dev is not None
                else None
            )
            return self._decode_hybrid(
                lab, bfs, dev.bfs_pos, host_ans, nq,
                bfs_halo=self._bfs_halo(dev.bfs_dev),
            )
        if isinstance(dev, _ShardedSlice):
            bits, it, tr = self._decode_bfs(
                jax.device_get(dev.dev), host_ans, nq,
                dev.halo_bytes_per_round,
            )
            return bits, it, tr
        return self._decode_packed(jax.device_get(dev), host_ans, nq)

    def _collect(self, results, n: int):
        """Fetch every dispatched slice in ONE device transfer and unpack.
        Returns ``(decisions, max_iters, truncated query indices)`` —
        queries in a truncated slice carry NO decision the caller may use
        (``_run_exact`` re-runs them). Hybrid (label-routed) slices
        contribute their label output and BFS sub-batch to the same
        single transfer."""
        devs: list = []
        for r in results:
            d = r[0]
            if d is None:
                continue
            parts = d.parts() if isinstance(d, _HybridSlice) else [d]
            devs.extend(self._raw_dev(p) for p in parts)
        flat = None
        try:
            if devs:
                cat = jnp.concatenate(devs) if len(devs) > 1 else devs[0]
                cat.copy_to_host_async()
                flat = jax.device_get(cat)
        finally:
            # the single fetch has consumed every slice's staging copy —
            # or the batch failed and its outputs are discarded (the CPU
            # fallback re-answers): either way the leases may recycle
            for rec in results:
                self._stage_release(rec[4])
        out = np.zeros(n, dtype=bool)
        max_iters = 0
        trunc_idx: list[int] = []
        pos = 0
        off = 0

        def take(part):
            nonlocal off
            seg = flat[off : off + part.shape[0]]
            off += part.shape[0]
            return seg

        for dev, host_ans, nq, _, _, _ in results:
            if dev is None:
                out[pos : pos + nq] = host_ans[:nq]
            elif isinstance(dev, _HybridSlice):
                lab = take(dev.label_dev) if dev.label_dev is not None else None
                bfs = (
                    take(self._raw_dev(dev.bfs_dev))
                    if dev.bfs_dev is not None
                    else None
                )
                bits, it, tr = self._decode_hybrid(
                    lab, bfs, dev.bfs_pos, host_ans, nq,
                    bfs_halo=self._bfs_halo(dev.bfs_dev),
                )
                out[pos : pos + nq] = bits
                if bfs is not None:
                    self.bfs_steps_stats.observe(float(it))
                max_iters = max(max_iters, it)
                if tr:
                    trunc_idx.extend(range(pos, pos + nq))
            else:
                bits, it, tr = self._decode_bfs(
                    take(self._raw_dev(dev)), host_ans, nq,
                    self._bfs_halo(dev),
                )
                out[pos : pos + nq] = bits
                self.bfs_steps_stats.observe(float(it))
                max_iters = max(max_iters, it)
                if tr:
                    trunc_idx.extend(range(pos, pos + nq))
            pos += nq
        return out, max_iters, trunc_idx

    def _note_route(self, route: str, nq: int, ms: float) -> None:
        """Record one landed slice's route (label | hybrid | bfs | host |
        cpu) for the per-route breakdown bench and
        ``keto_stream_route_slices_total`` read."""
        st = self._route_stats.get(route)
        if st is None:
            st = self._route_stats.setdefault(route, DurationStats())
        st.observe(ms)
        self._route_slices[route] += 1
        self._route_queries[route] += nq

    def stream_route_snapshot(self) -> dict:
        """Per-route stream breakdown: slice/query counts and service-
        time percentiles per route since the last ``reset_route_stats``
        (bench's per-route table; the metrics bridge reads the raw
        counters)."""
        out = {}
        for route, st in list(self._route_stats.items()):
            snap = st.snapshot()
            out[route] = {
                "slices": int(self._route_slices.get(route, 0)),
                "queries": int(self._route_queries.get(route, 0)),
                "p50_ms": snap["p50_ms"],
                "p99_ms": snap["p99_ms"],
                "mean_ms": snap["mean_ms"],
            }
        return out

    def route_slice_counts(self) -> dict:
        """route → landed slice count (the keto_stream_route_slices_total
        scrape callback)."""
        return dict(self._route_slices)

    def reset_route_stats(self) -> None:
        """Zero the per-route breakdown (bench passes start fresh)."""
        self._route_stats.clear()
        self._route_slices.clear()
        self._route_queries.clear()

    def _after_batch(self, max_iters: int) -> None:
        # adapt the pull-block size so deep workloads converge within few
        # convergence observations. Grow-only: block_iters is a static jit
        # argname, so shrinking it would recompile every kernel geometry for
        # a marginal saving (converged pulls inside a block are lax.cond
        # no-ops) — growing pays one recompile to cut while-loop trips.
        want = min(32, _ceil_pow2(max_iters + 1))
        if want > self._block_iters:
            self._block_iters = want

    #: per-query pair-fanout cap on the label path: a query spawning more
    #: pairs than this (huge sink in-degree × wildcardish seed sets)
    #: costs more as intersections than as one more BFS rider
    _LABEL_PAIR_CAP = 64

    def _device_batch_labeled(
        self,
        snap: GraphSnapshot,
        sd: np.ndarray,
        tg: np.ndarray,
        multi: dict,
        i0: int,
        i1: int,
        W: int,
        it_cap: Optional[int] = None,
    ):
        """The label fast path for one sub-chunk: resolve the chunk with
        the SAME host machinery as the BFS path (``pack_chunk`` — host
        walk, sink gathers, host-decided grants), then answer every
        label-certifiable query with ONE intersection kernel step and
        ride the rest on a compacted BFS sub-batch, bit-identically.

        The reach0 mapping (see keto_tpu/graph/labels.py):

        - a query's **pairs** are (seed row u) × (target-side row r):
          the interior target itself, or a sink target's interior
          in-neighbor gathers (``a_rows`` — exactly what the BFS kernel
          gathers from the fixpoint);
        - an e1 seed equal to an interior target would conflate reach0
          with the "via ≥ 1 edge" rule — that query falls back (the
          kernel's R0-vs-pull distinction, which labels don't carry);
          an e2 seed equal to the target was reached via a real edge on
          the host walk, so ``host_ans`` already granted it and the pair
          drops;
        - wildcard/multi-start queries, uncertifiable pairs (coverage
          gaps), and over-fanout queries fall back.
        """
        idx = snap.labels
        if idx is None or self._labels_dev(snap) is None:
            # the eviction ladder dropped the labels between routing and
            # dispatch (concurrent OOM containment): BFS answers instead
            return self._device_batch(snap, sd, tg, multi, i0, i1, W, it_cap=it_cap)
        packed, host_ans = pack_chunk(
            snap, sd, tg, multi, i0, i1, W, native=self._native_pack
        )
        nq = i1 - i0
        leases: list = []
        if packed is None:
            return None, host_ans, leases  # nothing reaches any device path
        (e1r, e1q, e2r, e2q, ar, aq, targets) = packed
        ni = snap.num_int
        B = 32 * W
        tq = np.asarray(targets[:nq], np.int64)
        t_int = tq < ni

        fallback = np.zeros(nq, bool)
        for i in multi:
            if i0 <= i < i1:
                fallback[i - i0] = True

        # valid (non-padding) entries; e1/e2 pad with row ni+1, a with ni
        m1 = (e1r != ni + 1) & (e1q < nq)
        m2 = (e2r != ni + 1) & (e2q < nq)
        ma = (ar != ni) & (aq < nq)
        s_rows = np.concatenate([e1r[m1], e2r[m2]]).astype(np.int64)
        s_q = np.concatenate([e1q[m1], e2q[m2]]).astype(np.int64)
        # e1 seed == interior target: reach0 would count the 0-edge path
        e1_rows_v = e1r[m1].astype(np.int64)
        e1_q_v = e1q[m1].astype(np.int64)
        self_hit = t_int[e1_q_v] & (e1_rows_v == tq[e1_q_v])
        if self_hit.any():
            fallback[e1_q_v[self_hit]] = True

        # target-side rows per query: the interior target, or the sink
        # answer-gather rows
        b_rows = np.concatenate(
            [tq[t_int], ar[ma].astype(np.int64)]
        )
        b_q = np.concatenate([np.nonzero(t_int)[0], aq[ma].astype(np.int64)])

        # group both sides by query, then cross-join per query
        so = np.argsort(s_q, kind="stable")
        s_rows, s_q = s_rows[so], s_q[so]
        bo = np.argsort(b_q, kind="stable")
        b_rows, b_q = b_rows[bo], b_q[bo]
        ns = np.bincount(s_q, minlength=nq)
        nr = np.bincount(b_q, minlength=nq)
        n_pairs_q = ns * nr
        over = n_pairs_q > self._LABEL_PAIR_CAP
        if over.any():
            fallback[over] = True
        # drop both sides of fallback queries before the join
        keep_s = ~fallback[s_q]
        keep_b = ~fallback[b_q]
        s_rows, s_q = s_rows[keep_s], s_q[keep_s]
        b_rows, b_q = b_rows[keep_b], b_q[keep_b]
        ns = np.bincount(s_q, minlength=nq) if s_q.size else np.zeros(nq, np.int64)
        nr = np.bincount(b_q, minlength=nq) if b_q.size else np.zeros(nq, np.int64)

        rep_nr = np.repeat(nr, ns)  # aligned to s_rows
        total = int(rep_nr.sum())
        if total:
            b_starts = np.cumsum(nr) - nr
            seed_q = s_q
            base = np.repeat(b_starts[seed_q], rep_nr)
            csum = np.cumsum(rep_nr) - rep_nr
            within = np.arange(total) - np.repeat(csum, rep_nr)
            pa = np.repeat(s_rows, rep_nr)
            pb = b_rows[base + within]
            pq = np.repeat(seed_q, rep_nr)
            # e2-seed == target pairs: already host-granted, reach0 would
            # double-count the 0-edge path — drop (e1 cases fell back)
            drop = t_int[pq] & (pa == pb)
            if drop.any():
                pa, pb, pq = pa[~drop], pb[~drop], pq[~drop]
            # coverage: a miss on an uncertifiable pair is not a deny
            cert = idx.certifiable(pa, pb)
            if not cert.all():
                bad = np.unique(pq[~cert])
                fallback[bad] = True
                keep = ~fallback[pq]
                pa, pb, pq = pa[keep], pb[keep], pq[keep]
        else:
            pa = pb = pq = np.zeros(0, np.int64)

        n_fb = int(np.count_nonzero(fallback))
        self.maintenance.incr("label_checks", by=nq - n_fb)
        if n_fb:
            self.maintenance.incr("label_fallbacks", by=n_fb)

        ldev = None
        if pa.size:
            faults.check("device-exec")
            P = _entry_pad(B, pa.size)
            pad = P - pa.size
            stg = self._stage_acquire(3 * P) if self._mesh is None else None
            if stg is not None:
                leases.append(stg)
                entries = stg
                entries[:P] = np.concatenate([pa, np.full(pad, ni, np.int64)])
                entries[P : 2 * P] = np.concatenate(
                    [pb, np.full(pad, ni, np.int64)]
                )
                entries[2 * P :] = np.concatenate(
                    [pq, np.zeros(pad, np.int64)]
                )
            else:
                entries = np.concatenate(
                    [
                        np.concatenate([pa, np.full(pad, ni, np.int64)]),
                        np.concatenate([pb, np.full(pad, ni, np.int64)]),
                        np.concatenate([pq, np.zeros(pad, np.int64)]),
                    ]
                ).astype(np.int32)
            dl = self._labels_dev(snap)
            if self._sharded:
                # row-sharded label arrays + replicated pairs: the kernel
                # does the one-shot pair-row exchange internally
                from keto_tpu.parallel import sharded as shard_mod

                ebuf = jax.device_put(entries, self._shard_repl_sharding)
                ldev = self._guard_alloc(
                    "label-kernel",
                    lambda: shard_mod.label_kernel(self._mesh)(
                        dl[0], dl[1], ebuf, n_pairs=P, B=B, rl=dl[2]
                    ),
                )
            else:
                if self._multiprocess:
                    from jax.sharding import NamedSharding, PartitionSpec as P_

                    def put_pairs():
                        return jax.device_put(
                            entries, NamedSharding(self._mesh, P_())
                        )

                    lkern = _label_kernel
                else:
                    def put_pairs():
                        return jnp.asarray(entries)

                    lkern = (
                        _label_kernel_donated
                        if self._donate_entries and self._mesh is None
                        else _label_kernel
                    )
                ldev = self._guard_alloc(
                    "label-kernel",
                    lambda: lkern(dl[0], dl[1], put_pairs(), n_pairs=P, B=B),
                )

        bfs_dev = None
        bfs_pos = None
        if n_fb:
            pos = np.nonzero(fallback)[0]
            gidx = pos + i0
            sd2 = sd[gidx]
            tg2 = tg[gidx]
            multi2 = {
                j: multi[int(i)] for j, i in enumerate(gidx) if int(i) in multi
            }
            W2 = next(w for w in _WORD_WIDTHS if 32 * w >= pos.size)
            bfs_dev, _, bfs_leases = self._device_batch(
                snap, sd2, tg2, multi2, 0, pos.size, W2, it_cap=it_cap
            )
            leases.extend(bfs_leases)
            bfs_pos = pos
        if ldev is None and bfs_dev is None:
            return None, host_ans, leases
        return _HybridSlice(ldev, bfs_dev, bfs_pos), host_ans, leases

    def _device_batch(
        self,
        snap: GraphSnapshot,
        sd: np.ndarray,
        tg: np.ndarray,
        multi: dict,
        i0: int,
        i1: int,
        force_W: Optional[int] = None,
        it_cap: Optional[int] = None,
    ):
        """Pack + dispatch one sub-chunk. Returns ``(dev, host_ans,
        leases)`` — ``leases`` are pooled staging buffers the caller MUST
        release only after the slice lands (``_stage_release``): the H2D
        copy may complete asynchronously, so earlier reuse could corrupt
        an in-flight slice."""
        faults.check("device-exec")
        packed, host_ans = pack_chunk(
            snap, sd, tg, multi, i0, i1, force_W, native=self._native_pack
        )
        leases: list = []
        if packed is None:
            # no query in the chunk reaches the device: host_ans is the
            # whole answer
            return None, host_ans, leases
        if self._sharded and snap.device_shards is not None:
            return (
                self._dispatch_sharded(
                    snap, packed, it_cap or self._it_cap, leases=leases
                ),
                host_ans,
                leases,
            )
        sharding = self._bitmap_sharding
        if self._mesh is not None:
            W = packed[-1].shape[0] // 32
            if W % self._mesh.shape.get("data", 1):
                sharding = self._bitmap_sharding_rows_only
        stg = None
        if self._mesh is None:
            stg = self._stage_acquire(sum(a.shape[0] for a in packed))
            if stg is not None:
                leases.append(stg)
        buf, sizes = pack_entries(packed, out=stg)
        ov = snap.device_overlay

        def put_entries():
            # inside the guarded call: the donated path consumes its
            # device buffer, so an OOM retry must re-stage from host
            if self._multiprocess:
                # multi-controller runtime: jit inputs must be global
                # arrays; every process holds identical host data (the
                # lockstep contract, parallel/mesh.py init_distributed)
                from jax.sharding import NamedSharding, PartitionSpec as P

                return jax.device_put(buf, NamedSharding(self._mesh, P()))
            return jnp.asarray(buf)

        kern = (
            _check_kernel_donated
            if self._donate_entries and self._mesh is None
            else _check_kernel
        )
        dev = self._guard_alloc(
            "check-kernel",
            lambda: kern(
                snap.device_buckets,
                put_entries(),
                ov_nbrs=None if ov is None else ov[0],
                ov_dst=None if ov is None else ov[1],
                sizes=sizes,
                n_active=snap.num_active,
                n_int=snap.num_int,
                valid_rows=tuple(b.n for b in snap.buckets),
                it_cap=it_cap or self._it_cap,
                block_iters=self._block_iters,
                bitmap_sharding=sharding,
            ),
        )
        return dev, host_ans, leases

    def _dispatch_sharded(
        self, snap: GraphSnapshot, packed, it_cap: int, leases=None
    ):
        """Route one packed chunk's entries to their owning shards and
        launch the shard_map BFS kernel (keto_tpu/parallel/sharded.py).
        Returns a ``_ShardedSlice`` whose packed ``uint32[W+3]`` output
        the collect paths decode — decisions bit-identical to the
        single-device kernel, plus the halo/frontier stats words. The
        routed entry stack stages through the same pooled-buffer seam as
        the single-device path (``leases`` collects the buffers for
        release at land time)."""
        from keto_tpu.parallel import sharded as shard_mod

        spec = snap.shard_spec
        B = packed[-1].shape[0]

        def out_alloc(shape):
            if leases is None or self._multiprocess:
                return None
            flat = self._stage_acquire(shape[0] * shape[1])
            if flat is None:
                return None
            leases.append(flat)
            return flat.reshape(shape)

        entries, sizes = shard_mod.route_entries(
            spec, packed, B, out_alloc=out_alloc
        )
        ebuf = jax.device_put(entries, self._shard_stack_sharding)
        ov = snap.device_shard_overlay
        dev = self._guard_alloc(
            "check-kernel",
            lambda: shard_mod.check_kernel(self._mesh)(
                snap.device_shards[0],
                snap.device_shards[1],
                ebuf,
                ov_nbrs=None if ov is None else ov[0],
                ov_dst=None if ov is None else ov[1],
                sizes=sizes,
                rps=spec.rows_per_shard,
                B=B,
                it_cap=it_cap,
                block_iters=self._block_iters,
            ),
        )
        return _ShardedSlice(
            dev, shard_mod.halo_bytes_per_round(spec, B // 32)
        )

    def _note_sharded_stats(self, iters: int, frontier_bits: int, halo_bytes_per_round: int) -> None:
        """Turn one sharded slice's tail words into the keto_shard_*
        counters: one halo exchange per real BFS hop."""
        m = self.maintenance
        if iters:
            m.incr("shard_halo_rounds", by=iters)
            m.incr("shard_halo_bytes", by=iters * halo_bytes_per_round)
        if frontier_bits:
            m.incr("shard_frontier_bits", by=frontier_bits)

    def subject_is_allowed(self, requested: RelationTuple) -> bool:
        """Single-query convenience with the oracle engine's signature
        (reference internal/check/engine.go:93-95)."""
        return self.batch_check([requested])[0]
