"""Batched check engine: multi-source bit-packed BFS on TPU.

Where the reference answers one ``Check`` by a recursive traversal issuing
one SQL query per subject-set node per page (reference
internal/check/engine.go:33-95), this engine answers **thousands of checks
in one device program**:

- up to 32·W queries are packed into a ``uint32[n_nodes+1, W]`` reached
  bitmap ``R`` — bit ``q%32`` of word ``q//32`` in row ``v`` means "query q
  has reached node v";
- one BFS step is a **pull**: ``P[v] = OR over in-neighbors s of R[s]``,
  computed per degree bucket as a gather + OR-reduction
  (see keto_tpu/graph/snapshot.py for the layout rationale);
- ``lax.while_loop`` iterates to the reachability fixpoint (the analog of
  the reference's visited-set cycle guard — monotone bitmaps make cycles
  terminate for free);
- the answer for query q is the target-row bit of ``A = ⋃ pulls``, i.e.
  "reached via ≥ 1 edge", reproducing the reference's rule that a subject
  only matches via an actual tuple, never by being the queried set itself.

Decision parity with the reference engine:
- unknown namespace → denied, not an error (engine.go:76-77): host
  resolution of a literal unknown namespace contributes no start nodes and
  the query's answer bit can never be set;
- empty namespace/object/relation fields wildcard the expansion exactly like
  the reference's tuple query (relationtuples.go:218-235) — a wildcard
  pattern resolves to *all* matching set nodes as BFS sources
  (GraphSnapshot.resolve_starts);
- pagination transparency: BFS has no pages, and reachability is
  independent of the reference's page-at-a-time visit order;
- the ``...``/empty-relation subtlety (engine_test.go:257-295): an empty
  relation wildcards only the *expansion* of that subject set; it never
  fabricates a transitive grant because matching stays literal.
"""

from __future__ import annotations

import threading
from functools import partial
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from keto_tpu import namespace as namespace_pkg
from keto_tpu.graph.snapshot import WILDCARD, GraphSnapshot, build_snapshot
from keto_tpu.relationtuple.model import RelationTuple, SubjectID, SubjectSet
from keto_tpu.x.errors import ErrNamespaceUnknown

# batch widths (in 32-query words) the engine compiles for; a request is
# padded up to the smallest fitting width so jit caches stay small
_WORD_WIDTHS = (1, 8, 64, 256)
# cap on the [rows, chunk, W] gather intermediate per bucket
_DEGREE_CHUNK = 1024


def _pull(
    bucket_nbrs: Sequence[jnp.ndarray], bucket_valid_rows: Sequence[int], R: jnp.ndarray
) -> jnp.ndarray:
    """One BFS pull step over the live (in-edged) rows.

    R: uint32[n_nodes+1, W] → uint32[n_live, W]. Zero-in-degree nodes sort
    last in device order (their rows never change after initialization), so
    the pull only produces the live prefix. Buckets are contiguous in
    device-id order — concatenating per-bucket OR-reductions yields the
    prefix with no scatter.
    """
    outs = []
    for nbrs, n_valid in zip(bucket_nbrs, bucket_valid_rows):
        n_pad, cap = nbrs.shape
        if cap == 0:
            continue  # zero-in-degree tail: not part of the live prefix
        acc = None
        for c0 in range(0, cap, _DEGREE_CHUNK):
            gathered = R[nbrs[:, c0 : c0 + _DEGREE_CHUNK]]  # [n_pad, chunk, W]
            part = lax.reduce(gathered, np.uint32(0), lax.bitwise_or, (1,))
            acc = part if acc is None else lax.bitwise_or(acc, part)
        outs.append(acc[:n_valid])
    return jnp.concatenate(outs, axis=0) if outs else R[:0]


def check_step(
    bucket_nbrs: tuple[jnp.ndarray, ...],
    start_rows: jnp.ndarray,  # int32[SP] node device ids (padding → n_nodes)
    start_words: jnp.ndarray,  # int32[SP] query word index
    start_masks: jnp.ndarray,  # uint32[SP] query bit mask (padding → 0)
    targets: jnp.ndarray,  # int32[B], n_nodes = unresolved
    *,
    n_nodes: int,
    valid_rows: tuple[int, ...],
    it_cap: int,
    block_iters: int = 8,
    bitmap_sharding=None,  # NamedSharding for the [rows, words] bitmaps
) -> tuple[jnp.ndarray, jnp.ndarray]:
    B = targets.shape[0]
    W = B // 32
    n_live = sum(n for (nb, n) in zip(bucket_nbrs, valid_rows) if nb.shape[1] > 0)
    q = jnp.arange(B)
    words = q // 32
    bits = (q % 32).astype(jnp.uint32)
    # per (row, word) slot, masks from distinct queries occupy distinct bits
    # and per-query start lists are deduplicated on host, so scatter-add
    # never carries — add on disjoint bits is bitwise OR
    R0 = (
        jnp.zeros((n_nodes + 1, W), jnp.uint32)
        .at[start_rows, start_words]
        .add(start_masks, mode="drop")
    )
    if bitmap_sharding is not None:
        # "data" shards words (embarrassingly parallel); "graph" shards rows
        # and lets the SPMD partitioner insert the per-step all-gather the
        # pull's cross-shard row gathers need
        R0 = lax.with_sharding_constraint(R0, bitmap_sharding)
    # rows past n_live (zero-in-degree nodes + the phantom sentinel) never
    # change — only the live prefix is carried through the loop
    static_tail = R0[n_live:]

    def step(live):
        R = jnp.concatenate([live, static_tail], axis=0)
        nxt = lax.bitwise_or(_pull(bucket_nbrs, valid_rows, R), live)
        return nxt, jnp.any(nxt != live)

    # The while cond is the only point the runtime must observe a device
    # value, which costs a full round trip on tunneled devices — so each
    # while iteration runs a *block* of pulls, each skipped via lax.cond
    # once the fixpoint is reached (monotone bitmaps: converged stays
    # converged). Steady state: one observation per batch.
    def block(carry):
        def one(_, st):
            live, changed, it = st
            nxt, ch = lax.cond(
                changed, step, lambda l: (l, jnp.bool_(False)), live
            )
            return nxt, ch, it + changed.astype(jnp.int32)
        return lax.fori_loop(0, block_iters, one, carry)

    live, _, iters = lax.while_loop(
        lambda c: c[1] & (c[2] < it_cap), block, (R0[:n_live], jnp.bool_(True), jnp.int32(0))
    )

    # answers require "reached via ≥ 1 edge": one more pull of the fixpoint,
    # without the OR of start bits; unreachable rows (no in-edges) stay zero
    R_fix = jnp.concatenate([live, static_tail], axis=0)
    A = jnp.concatenate(
        [_pull(bucket_nbrs, valid_rows, R_fix), jnp.zeros((n_nodes + 1 - n_live, W), jnp.uint32)],
        axis=0,
    )
    hit = (A[targets, words] >> bits) & jnp.uint32(1)
    return hit == 1, iters


#: jitted entrypoint used by the engine; ``check_step`` stays un-jitted for
#: ahead-of-time compile checks (__graft_entry__.py)
_check_kernel = partial(
    jax.jit,
    static_argnames=("n_nodes", "valid_rows", "it_cap", "block_iters", "bitmap_sharding"),
)(check_step)


def _ceil_pow2(x: int) -> int:
    return 1 if x <= 1 else 1 << (int(x) - 1).bit_length()


def pack_batch(
    snap: GraphSnapshot,
    resolved: Sequence[tuple[np.ndarray, int]],
    force_W: Optional[int] = None,
):
    """Pack resolved queries into kernel arguments.

    ``resolved`` holds per-query ``(start device ids, target device id)``
    from ``TpuCheckEngine._resolve``. Returns ``(rows, words, masks,
    targets)`` numpy arrays, or None when no query has a start node (the
    whole batch is a guaranteed deny).
    """
    nq = len(resolved)
    W = force_W or next(w for w in _WORD_WIDTHS if 32 * w >= nq)
    B = 32 * W
    targets = np.full(B, snap.n_nodes, dtype=np.int32)
    rows_l: list[np.ndarray] = []
    words_l: list[np.ndarray] = []
    masks_l: list[np.ndarray] = []
    for i, (starts, t) in enumerate(resolved):
        targets[i] = t
        if starts.size:
            rows_l.append(starts)
            words_l.append(np.full(starts.size, i // 32, np.int32))
            masks_l.append(np.full(starts.size, np.uint32(1) << np.uint32(i % 32)))
    if not rows_l:
        return None

    rows = np.concatenate(rows_l).astype(np.int32)
    words = np.concatenate(words_l)
    masks = np.concatenate(masks_l)
    # keep the kernel's start-array geometry to a handful of shapes: SP == B
    # when entries fit; multi-start chunks share the max-batch size (the
    # chunker caps entries there); only a single query with a larger
    # wildcard fan-out grows past it
    if rows.size <= B:
        sp = B
    else:
        sp = max(_ceil_pow2(rows.size), 32 * _WORD_WIDTHS[-1])
    pad = sp - rows.size
    rows = np.concatenate([rows, np.full(pad, snap.n_nodes, np.int32)])
    words = np.concatenate([words, np.zeros(pad, np.int32)])
    masks = np.concatenate([masks, np.zeros(pad, np.uint32)])
    return rows, words, masks, targets


class TpuCheckEngine:
    """Drop-in check engine answering batched queries on the device graph.

    ``store`` must expose ``snapshot_rows() -> (rows, watermark)`` and
    ``watermark()`` (keto_tpu/persistence/memory.py); ``namespaces`` is a
    namespace.Manager or a zero-arg callable returning the current one
    (hot-reload safe). This object is the TPU implementation behind the
    registry's ``PermissionEngine()`` seam (reference
    internal/driver/registry_default.go:158-163).
    """

    def __init__(
        self,
        store,
        namespaces,
        *,
        it_cap: int = 4096,
        max_batch: int = 32 * _WORD_WIDTHS[-1],
        mesh=None,
        shard_rows: bool = False,
    ):
        self._store = store
        if isinstance(namespaces, namespace_pkg.Manager):
            self._nm: Callable[[], namespace_pkg.Manager] = lambda: namespaces
        else:
            self._nm = namespaces
        self._it_cap = it_cap
        self._max_batch = max_batch
        # pulls per convergence observation, adapted to the workload's
        # traversal depth from the iteration counts kernels report back
        self._block_iters = 8
        # concurrently in-flight chunks (bounds device bitmap workspaces)
        self._dispatch_window = 16
        self._mesh = mesh
        self._shard_rows = shard_rows
        self._bitmap_sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from keto_tpu.parallel.mesh import DATA_AXIS, GRAPH_AXIS

            row_axis = GRAPH_AXIS if shard_rows else None
            self._bitmap_sharding = NamedSharding(mesh, P(row_axis, DATA_AXIS))
            self._bucket_sharding = NamedSharding(mesh, P(GRAPH_AXIS, None))
            self._replicated = NamedSharding(mesh, P(None, None))
        self._lock = threading.Lock()
        self._snapshot: Optional[GraphSnapshot] = None

    # -- snapshot lifecycle --------------------------------------------------

    def snapshot(self) -> GraphSnapshot:
        """Current device snapshot, rebuilt iff the store moved past the
        snapshot's watermark (double-buffered: checks against the old
        snapshot finish while the new one is prepared)."""
        snap = self._snapshot
        wm = self._store.watermark()
        if snap is not None and snap.snapshot_id == wm:
            return snap
        with self._lock:
            snap = self._snapshot
            wm = self._store.watermark()
            if snap is not None and snap.snapshot_id == wm:
                return snap
            rows, wm = self._store.snapshot_rows()
            wild_ns_ids = frozenset(
                n.id for n in self._nm().namespaces() if n.name == ""
            )
            snap = build_snapshot(rows, wm, wild_ns_ids)
            if self._mesh is None:
                snap.device_buckets = tuple(jax.device_put(b.nbrs) for b in snap.buckets)
            else:
                graph_size = self._mesh.shape.get("graph", 1)
                snap.device_buckets = tuple(
                    jax.device_put(
                        b.nbrs,
                        self._bucket_sharding
                        if b.nbrs.shape[0] % graph_size == 0
                        else self._replicated,
                    )
                    for b in snap.buckets
                )
            self._snapshot = snap
            return snap

    # -- resolution ----------------------------------------------------------

    def _resolve_ns(self, name: str) -> Optional[int]:
        """Namespace name → id; "" wildcards (never resolved, like reference
        relationtuples.go:230-235); unknown → None (denied)."""
        if name == "":
            return WILDCARD
        try:
            return self._nm().get_namespace_by_name(name).id
        except ErrNamespaceUnknown:
            return None

    def _resolve(
        self, snap: GraphSnapshot, rt: RelationTuple
    ) -> tuple[np.ndarray, int]:
        """(start device ids, target device id); phantom target = n_nodes."""
        miss = snap.n_nodes
        none = np.zeros(0, np.int64)
        ns_id = self._resolve_ns(rt.namespace)
        if ns_id is None:
            return none, miss  # unknown namespace → denied (engine.go:76-77)
        starts = snap.resolve_starts(ns_id, rt.object, rt.relation)
        if starts.size == 0:
            return none, miss
        if isinstance(rt.subject, SubjectID):
            target = snap.resolve_leaf(rt.subject.id)
        elif isinstance(rt.subject, SubjectSet):
            sns_id = self._resolve_ns(rt.subject.namespace)
            if sns_id is None:
                return none, miss
            if sns_id == WILDCARD:
                # subjects are matched literally; an empty subject namespace
                # can only equal a stored subject in a namespace named ""
                wild = [i for i in snap.wild_ns_ids]
                target = (
                    snap.resolve_set(wild[0], rt.subject.object, rt.subject.relation)
                    if wild
                    else None
                )
            else:
                target = snap.resolve_set(sns_id, rt.subject.object, rt.subject.relation)
        else:
            return none, miss
        if target is None:
            return starts, miss  # live BFS, but the bit can never match
        return starts, target

    # -- public API ----------------------------------------------------------

    def batch_check(self, tuples: Sequence[RelationTuple]) -> list[bool]:
        snap = self.snapshot()
        if snap.n_nodes == 0 or snap.n_edges == 0 or not tuples:
            return [False] * len(tuples)

        # resolve on host first, then pack chunks so that the start-entry
        # array stays at its padded size B — chunk geometry (W, SP) is then
        # constant across calls and every chunk hits the same jit cache entry
        resolved = [self._resolve(snap, rt) for rt in tuples]

        chunks: list[list[tuple[np.ndarray, int]]] = []
        cur: list[tuple[np.ndarray, int]] = []
        cur_entries = 0
        cap = self._max_batch
        for starts, t in resolved:
            n = int(starts.size)
            if cur and (len(cur) >= cap or cur_entries + n > cap):
                chunks.append(cur)
                cur, cur_entries = [], 0
            cur.append((starts, t))
            cur_entries += n
        if cur:
            chunks.append(cur)

        # one multi-chunk request keeps a single kernel shape: every chunk
        # pads to the width fitting the largest one rather than compiling
        # narrower variants for tails
        force_W = None
        if len(chunks) > 1:
            biggest = max(len(c) for c in chunks)
            force_W = next(w for w in _WORD_WIDTHS if 32 * w >= biggest)

        # dispatch every chunk asynchronously (windowed so in-flight bitmap
        # workspaces stay within HBM), then fetch results in pipelined
        # device_gets — per-fetch latency dominates on tunneled devices, and
        # concurrent fetches overlap
        out: list[bool] = []
        max_iters = 0
        for woff in range(0, len(chunks), self._dispatch_window):
            wave = chunks[woff : woff + self._dispatch_window]
            pending = [(self._device_batch(snap, c, force_W), len(c)) for c in wave]
            fetched = jax.device_get([d for d, _ in pending])
            for (arr, iters), (_, nq) in zip(fetched, pending):
                out.extend(bool(x) for x in arr[:nq])
                max_iters = max(max_iters, int(iters))
        # adapt the pull-block size so the next batch converges within one
        # convergence observation (clamped to powers of two ≤ 32)
        self._block_iters = max(2, min(32, _ceil_pow2(max_iters + 1)))
        return out

    def _device_batch(
        self,
        snap: GraphSnapshot,
        resolved: list[tuple[np.ndarray, int]],
        force_W: Optional[int] = None,
    ):
        packed = pack_batch(snap, resolved, force_W)
        if packed is None:
            W = force_W or next(w for w in _WORD_WIDTHS if 32 * w >= len(resolved))
            return np.zeros(32 * W, dtype=bool), np.int32(0)
        rows, words, masks, targets = packed
        return _check_kernel(
            snap.device_buckets,
            jnp.asarray(rows),
            jnp.asarray(words),
            jnp.asarray(masks),
            jnp.asarray(targets),
            n_nodes=snap.n_nodes,
            valid_rows=tuple(b.n for b in snap.buckets),
            it_cap=self._it_cap,
            block_iters=self._block_iters,
            bitmap_sharding=self._bitmap_sharding,
        )

    def subject_is_allowed(self, requested: RelationTuple) -> bool:
        """Single-query convenience with the oracle engine's signature
        (reference internal/check/engine.go:93-95)."""
        return self.batch_check([requested])[0]
