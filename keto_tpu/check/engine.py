"""Oracle check engine: recursive subject-set expansion on the host.

This is a faithful re-implementation of the reference's check engine
(reference internal/check/engine.go:33-95): depth-first search over
subject-set indirections with early exit on match, a shared visited-set cycle
guard, page-at-a-time reads through the Manager contract, and
unknown-namespace → allowed=false (engine.go:76-77).

Its role here is twofold: it is the *differential-testing oracle* the TPU
engine (keto_tpu/check/tpu_engine.py) must agree with bit-for-bit, and the
fallback engine for stores/queries the device snapshot cannot serve.
"""

from __future__ import annotations

from typing import Optional

from keto_tpu.relationtuple.manager import Manager
from keto_tpu.relationtuple.model import RelationQuery, RelationTuple, SubjectSet
from keto_tpu.x.errors import ErrNotFound
from keto_tpu.x.graph import check_and_add_visited
from keto_tpu.x.pagination import with_size, with_token


class CheckEngine:
    def __init__(self, manager: Manager, page_size: int = 0):
        self._manager = manager
        # page_size=0 keeps the store default (100); tests inject smaller
        # sizes to assert pagination behavior.
        self._page_size = page_size

    def set_store(self, manager: Manager) -> None:
        """Fleet promotion handoff: swap the backing store (same tuple
        history at or past the old watermark; the recursive engine reads
        live state, so nothing else needs invalidating)."""
        self._manager = manager

    def subject_is_allowed(self, requested: RelationTuple) -> bool:
        """Can ``requested.subject`` be reached from
        ``requested.object#requested.relation``? Reference engine.go:93-95."""
        return self._check_one_indirection_further(
            requested,
            RelationQuery(
                namespace=requested.namespace,
                object=requested.object,
                relation=requested.relation,
            ),
            visited=set(),
        )

    def _check_one_indirection_further(
        self, requested: RelationTuple, expand_query: RelationQuery, visited: set[str]
    ) -> bool:
        """Page loop over one subject-set expansion. Reference engine.go:69-91."""
        prev_page = ""
        while True:
            opts = [with_token(prev_page)]
            if self._page_size:
                opts.append(with_size(self._page_size))
            try:
                next_rels, next_page = self._manager.get_relation_tuples(expand_query, *opts)
            except ErrNotFound:
                # unknown namespace → denied, not an error (engine.go:76-77)
                return False

            allowed = self._subject_is_allowed(requested, next_rels, visited)
            if allowed or next_page == "":
                return allowed
            prev_page = next_page

    def _subject_is_allowed(
        self, requested: RelationTuple, rels: list[RelationTuple], visited: set[str]
    ) -> bool:
        """Match + recurse over one page of tuples. Reference engine.go:33-67."""
        for sr in rels:
            if check_and_add_visited(visited, sr.subject):
                continue

            if requested.subject == sr.subject:
                return True

            if not isinstance(sr.subject, SubjectSet):
                continue

            if self._check_one_indirection_further(
                requested,
                RelationQuery(
                    namespace=sr.subject.namespace,
                    object=sr.subject.object,
                    relation=sr.subject.relation,
                ),
                visited,
            ):
                return True

        return False
