from keto_tpu.check.engine import CheckEngine

__all__ = ["CheckEngine"]
