"""ctypes binding for the native pack walk (native/pack.cpp).

``pack_chunk``'s host walk — frontier expansion of host-propagated
starts through the forward CSR, (query, row) seen/seed dedup, target-hit
grants, and the sink answer gather — runs here as one GIL-released C++
call on the eligible path, so resolve/pack of slice k+2 genuinely
overlaps device execution of k+1 instead of fighting the GIL. The numpy
implementation in keto_tpu/check/tpu_engine.py remains the contract
(bit-identical output, fuzz-compared in tests/test_native_pack.py) and
the fallback.

**Eligibility** (``walk_eligible``): the walk reads ONLY the base
forward/sink CSRs, so any overlay state that would change what
``out_neighbors_bulk``/``sink_in_rows_bulk`` return routes the chunk to
numpy: host out-adjacency (``ov_out``), tombstones (``ov_removed``), or
overlay sink in-edges (``ov_sink_in``). Interior overlay-ELL edges are
device-side and do not affect the host walk, so the common
insert-only-delta serving state keeps the native path.

Loading is opportunistic: ``load_library()`` returns None (and callers
fall back to numpy) when the shared object is absent, stale
(``keto_pack_version`` mismatch), ``KETO_TPU_NATIVE=0``, or
``KETO_TPU_NATIVE_PACK=0``. Build with ``make native``.

``COUNTERS`` tracks which path packed each chunk; the registry scrapes
it as ``keto_native_pack_chunks_total{path}``.
"""

from __future__ import annotations

import ctypes
import os
from pathlib import Path
from typing import Optional

import numpy as np

_ABI_VERSION = 1

_lib: Optional[ctypes.CDLL] = None
_lib_checked = False

#: chunks packed per path since process start (scraped as
#: ``keto_native_pack_chunks_total{path}``; "numpy" counts fallbacks for
#: ANY reason — library absent, disabled, or overlay-ineligible)
COUNTERS = {"native": 0, "numpy": 0}

_I64 = ctypes.POINTER(ctypes.c_int64)
_I32 = ctypes.POINTER(ctypes.c_int32)
_U8 = ctypes.POINTER(ctypes.c_uint8)


def _candidate_paths():
    if os.environ.get("KETO_TPU_PACK_LIB"):
        yield Path(os.environ["KETO_TPU_PACK_LIB"])
    root = Path(__file__).resolve().parents[2]
    yield root / "native" / "libketopack.so"


def load_library() -> Optional[ctypes.CDLL]:
    global _lib, _lib_checked
    if _lib_checked:
        return _lib
    _lib_checked = True
    if os.environ.get("KETO_TPU_NATIVE", "1") == "0":
        return None
    if os.environ.get("KETO_TPU_NATIVE_PACK", "1") == "0":
        return None
    for path in _candidate_paths():
        if not path.exists():
            continue
        try:
            lib = ctypes.CDLL(str(path))
        except OSError:
            continue  # corrupt / wrong-arch build → numpy fallback
        c = ctypes.c_int64
        p = ctypes.c_void_p
        try:
            lib.keto_pack_version.restype = c
            if lib.keto_pack_version() != _ABI_VERSION:
                continue  # stale build → numpy fallback
        except AttributeError:
            continue
        lib.keto_pack_walk.restype = p
        lib.keto_pack_walk.argtypes = [
            _I64, _I32, c, c, c, _I64, _I64, c, _I64, c, c,
        ]
        lib.keto_pack_n_seeds.restype = c
        lib.keto_pack_n_seeds.argtypes = [p]
        lib.keto_pack_fetch.argtypes = [p, _I64, _I64, _U8]
        lib.keto_pack_free.argtypes = [p]
        lib.keto_sink_gather.restype = p
        lib.keto_sink_gather.argtypes = [_I64, _I32, _I64, c]
        lib.keto_gather_n.restype = c
        lib.keto_gather_n.argtypes = [p]
        lib.keto_gather_fetch.argtypes = [p, _I32, _I64]
        lib.keto_gather_free.argtypes = [p]
        _lib = lib
        return _lib
    return None


def available() -> bool:
    return load_library() is not None


def walk_eligible(snap) -> bool:
    """True when the native walk would read exactly what the numpy walk
    reads: base CSRs present, no host-visible overlay adjacency, no
    tombstones, no overlay sink in-edges."""
    return (
        snap.fwd_indptr is not None
        and snap.fwd_indices is not None
        and not snap.ov_out
        and not snap.ov_sink_in
        and (snap.ov_removed is None or snap.ov_removed.size == 0)
    )


def _ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def pack_walk(
    snap, rows: np.ndarray, pq: np.ndarray, tgc: np.ndarray
) -> tuple[np.ndarray, np.ndarray, Optional[np.ndarray]]:
    """Run the frontier walk natively. ``rows``/``pq`` are the initial
    host-propagated (row, query) pairs (int64), ``tgc`` the per-query
    target rows (int64, -1 = none). Returns ``(seed_rows, seed_q,
    host_hits)`` — the globally (query, row)-deduplicated device seeds in
    first-occurrence order and the host-decided grants — bit-identical to
    the numpy walk by contract."""
    lib = load_library()
    assert lib is not None, "pack_walk called without the native library"
    indptr = np.ascontiguousarray(snap.fwd_indptr, np.int64)
    indices = np.ascontiguousarray(snap.fwd_indices, np.int32)
    rows = np.ascontiguousarray(rows, np.int64)
    pq = np.ascontiguousarray(pq, np.int64)
    tgc = np.ascontiguousarray(tgc, np.int64)
    nq = tgc.shape[0]
    h = lib.keto_pack_walk(
        _ptr(indptr, ctypes.c_int64),
        _ptr(indices, ctypes.c_int32),
        snap.n_base_nodes,
        snap.num_int,
        snap.sink_base,
        _ptr(rows, ctypes.c_int64),
        _ptr(pq, ctypes.c_int64),
        rows.shape[0],
        _ptr(tgc, ctypes.c_int64),
        nq,
        0,
    )
    try:
        n = lib.keto_pack_n_seeds(h)
        seed_rows = np.empty(n, np.int64)
        seed_q = np.empty(n, np.int64)
        hits = np.zeros(nq, np.uint8)
        lib.keto_pack_fetch(
            h,
            _ptr(seed_rows, ctypes.c_int64),
            _ptr(seed_q, ctypes.c_int64),
            _ptr(hits, ctypes.c_uint8),
        )
    finally:
        lib.keto_pack_free(h)
    return seed_rows, seed_q, (hits.view(bool) if hits.any() else None)


def sink_gather(snap, sinks: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Native twin of the overlay-free arm of ``sink_in_rows_bulk``:
    ``(concatenated interior in-neighbor rows, per-target counts)`` for
    sink-class device ids ``sinks``."""
    lib = load_library()
    assert lib is not None, "sink_gather called without the native library"
    indptr = np.ascontiguousarray(snap.sink_indptr, np.int64)
    indices = np.ascontiguousarray(snap.sink_indices, np.int32)
    local = np.ascontiguousarray(np.asarray(sinks, np.int64) - snap.sink_base)
    n = local.shape[0]
    h = lib.keto_sink_gather(
        _ptr(indptr, ctypes.c_int64),
        _ptr(indices, ctypes.c_int32),
        _ptr(local, ctypes.c_int64),
        n,
    )
    try:
        total = lib.keto_gather_n(h)
        rows = np.empty(total, np.int32)
        cnts = np.empty(n, np.int64)
        lib.keto_gather_fetch(
            h, _ptr(rows, ctypes.c_int32), _ptr(cnts, ctypes.c_int64)
        )
    finally:
        lib.keto_gather_free(h)
    return rows, cnts
