"""String → int32 node interning for the tuple graph.

Every stored relation tuple ``ns:obj#rel@subject`` contributes one directed
edge to a graph whose vertices are:

- **set nodes** — distinct ``(namespace_id, object, relation)`` triples
  appearing either as a tuple's left-hand side or as a subject-set subject;
- **leaf nodes** — distinct subject-ID strings. Subject IDs are globally
  scoped strings (not namespaced), mirroring the reference's
  ``SubjectID.Equals`` which compares only the string
  (reference internal/relationtuple/definitions.go:166-170).

**Wildcard semantics.** The reference's tuple query skips the filter for
every empty field (reference internal/persistence/sql/relationtuples.go:218-235:
``if query.Relation != "" { … }`` etc.), so when the check engine expands a
subject set whose relation/object/namespace is the empty string, that field
matches *anything*. Equality matching of subjects, by contrast, is always
literal. The graph encodes this exactly:

- the **out-edges of a set node K are the subjects of every tuple whose
  left-hand side matches K's query** (empty fields of K wildcarded). For a
  fully literal K that degenerates to "the tuples of K";
- a node is only *matched* (its reached-bit consulted) via exact key
  equality, so wildcards never leak into subject matching.

Namespace wildcarding keys off the namespace *name* being ``""`` — which may
be a configured namespace (reference engine_test.go:119-149 configures one);
reads treat it as a wildcard either way, exactly like the reference, because
``GetRelationTuples`` never resolves an empty namespace name.

Raw ids are dense: set nodes occupy ``[0, num_sets)`` and leaf nodes
``[num_sets, num_sets + num_leaves)``.

Known (documented) divergence: the reference keys its visited set by the
subject's *string form*, so a ``SubjectID`` whose id literally spells
``ns:obj#rel`` can shadow the same-named ``SubjectSet`` mid-traversal and
prune a branch (reference internal/x/graph/graph_utils.go:13-35). The graph
engine interns leaves and sets in disjoint id spaces and never prunes, so it
answers strictly-by-the-model in that pathological case.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import FrozenSet, Iterable

import numpy as np

SET_KIND = 0
LEAF_KIND = 1


class _Codes:
    """Interns strings to dense int codes for vectorized matching."""

    def __init__(self):
        self.by_str: dict[str, int] = {}

    def code(self, s: str) -> int:
        c = self.by_str.get(s)
        if c is None:
            c = len(self.by_str)
            self.by_str[s] = c
        return c


@dataclass
class InternedGraph:
    """Node tables, per-field code arrays, and raw edges for one snapshot."""

    set_ids: dict[tuple[int, str, str], int]
    leaf_ids: dict[str, int]
    obj_codes: dict[str, int]
    rel_codes: dict[str, int]
    # set-node key fields, aligned with raw set index
    key_ns: np.ndarray  # int64 [num_sets]
    key_obj: np.ndarray  # int64 [num_sets] (codes)
    key_rel: np.ndarray  # int64 [num_sets] (codes)
    key_wild: np.ndarray  # bool [num_sets] — any field wildcards
    # raw deduplicated edges
    src: np.ndarray  # int64 [E] (set-node raw ids)
    dst: np.ndarray  # int64 [E] (unified raw ids)

    @property
    def num_sets(self) -> int:
        return len(self.set_ids)

    @property
    def num_leaves(self) -> int:
        return len(self.leaf_ids)

    @property
    def num_nodes(self) -> int:
        return self.num_sets + self.num_leaves

    # -- resolution (the native interner implements the same interface) ------

    def resolve_set(self, ns_id: int, obj: str, rel: str) -> int:
        """Raw set-node id, or -1 when absent."""
        return self.set_ids.get((ns_id, obj, rel), -1)

    def resolve_leaf(self, subject_id: str) -> int:
        """Raw leaf index (not offset by num_sets), or -1 when absent."""
        return self.leaf_ids.get(subject_id, -1)

    def obj_code(self, s: str) -> int:
        return self.obj_codes.get(s, -1)

    def rel_code(self, s: str) -> int:
        return self.rel_codes.get(s, -1)

    def num_obj_codes(self) -> int:
        """Code-table size (ExtendedInterned assigns fresh codes above)."""
        return len(self.obj_codes)

    def num_rel_codes(self) -> int:
        return len(self.rel_codes)

    # -- reverse lookups (expand-tree reconstruction) ------------------------

    def set_key_of(self, raw_id: int):
        """``(ns_id, object, relation)`` of set node ``raw_id``."""
        inv = self.__dict__.get("_set_by_id")
        if inv is None:
            inv = [None] * len(self.set_ids)
            for k, i in self.set_ids.items():
                inv[i] = k
            self.__dict__["_set_by_id"] = inv
        return inv[raw_id]

    def leaf_str(self, idx: int) -> str:
        """Subject-id string of leaf ``idx`` (not offset by num_sets)."""
        inv = self.__dict__.get("_leaf_by_id")
        if inv is None:
            inv = [None] * len(self.leaf_ids)
            for s, i in self.leaf_ids.items():
                inv[i] = s
            self.__dict__["_leaf_by_id"] = inv
        return inv[idx]


class ExtendedInterned:
    """Copy-on-write interner view: an immutable base interner plus small
    append-only extension tables for nodes added by overlay compaction
    (keto_tpu/graph/compaction.py).

    The base is NEVER mutated — snapshots sharing it (in-flight batches on
    the pre-compaction snapshot) stay consistent; the extension is tiny
    (one entry per overlay node folded in). Raw-id numbering matches a
    grown interner: ext set keys take raw ids [base.num_sets,
    num_sets) in fold order, which shifts every leaf's unified raw id by
    the ext set count — the compaction layer rebuilds ``raw2dev``
    accordingly. New field codes are assigned above the base code-table
    sizes, so they can never collide with (or shadow) base codes in the
    snapshot's pattern indexes. Ext keys are always literal: apply_delta
    rejects new wildcard-bearing keys, so ``key_wild`` extends with False.

    Nesting flattens: extending an ExtendedInterned copies its (small)
    ext tables onto the same base rather than stacking wrappers.
    """

    #: engines consult this to re-resolve native-path misses through the
    #: host path (ext nodes are invisible to the resident base tables)
    has_ext = True

    def __init__(self, base, new_set_keys, new_leaves):
        if isinstance(base, ExtendedInterned):
            self._base = base._base
            self._ext_set_keys = list(base._ext_set_keys)
            self._ext_leaves = list(base._ext_leaves)
            self._ext_obj_codes = dict(base._ext_obj_codes)
            self._ext_rel_codes = dict(base._ext_rel_codes)
        else:
            self._base = base
            self._ext_set_keys = []
            self._ext_leaves = []
            self._ext_obj_codes = {}
            self._ext_rel_codes = {}
        b = self._base
        self._base_num_sets = b.num_sets
        self._base_num_leaves = b.num_leaves
        # base code-table sizes: the floor for fresh ext codes. None (a
        # stale native .so without the size exports) is the caller's
        # problem — compaction checks before constructing.
        self._obj_floor = b.num_obj_codes()
        self._rel_floor = b.num_rel_codes()
        if self._obj_floor is None or self._rel_floor is None:
            raise ValueError("base interner does not expose code-table sizes")
        for key in new_set_keys:
            self._ext_set_keys.append(
                (int(key[0]), str(key[1]), str(key[2]))
            )
        self._ext_leaves.extend(str(s) for s in new_leaves)
        self._ext_set_ids = {
            k: self._base_num_sets + i for i, k in enumerate(self._ext_set_keys)
        }
        self._ext_leaf_ids = {
            s: self._base_num_leaves + i for i, s in enumerate(self._ext_leaves)
        }
        # intern ext key field codes (reusing base codes where the string
        # already exists) and build the concatenated key arrays
        ext_obj = np.empty(len(self._ext_set_keys), np.int64)
        ext_rel = np.empty(len(self._ext_set_keys), np.int64)
        ext_ns = np.empty(len(self._ext_set_keys), np.int64)
        self._ext_obj_strs = {c: s for s, c in self._ext_obj_codes.items()}
        self._ext_rel_strs = {c: s for s, c in self._ext_rel_codes.items()}
        for i, (ns, obj, rel) in enumerate(self._ext_set_keys):
            ext_ns[i] = ns
            ext_obj[i] = self._intern_field(obj, self._ext_obj_codes,
                                            self._ext_obj_strs, b.obj_code,
                                            self._obj_floor)
            ext_rel[i] = self._intern_field(rel, self._ext_rel_codes,
                                            self._ext_rel_strs, b.rel_code,
                                            self._rel_floor)
        self.key_ns = np.concatenate([np.asarray(b.key_ns, np.int64), ext_ns])
        self.key_obj = np.concatenate([np.asarray(b.key_obj, np.int64), ext_obj])
        self.key_rel = np.concatenate([np.asarray(b.key_rel, np.int64), ext_rel])
        self.key_wild = np.concatenate(
            [np.asarray(b.key_wild, bool), np.zeros(len(self._ext_set_keys), bool)]
        )

    @staticmethod
    def _intern_field(s, ext_codes, ext_strs, base_lookup, floor):
        c = base_lookup(s)
        if c >= 0:
            return c
        c = ext_codes.get(s)
        if c is None:
            c = floor + len(ext_codes)
            ext_codes[s] = c
            ext_strs[c] = s
        return c

    @property
    def num_sets(self) -> int:
        return self._base_num_sets + len(self._ext_set_keys)

    @property
    def num_leaves(self) -> int:
        return self._base_num_leaves + len(self._ext_leaves)

    @property
    def num_nodes(self) -> int:
        return self.num_sets + self.num_leaves

    @property
    def n_ext_sets(self) -> int:
        return len(self._ext_set_keys)

    @property
    def n_ext(self) -> int:
        return len(self._ext_set_keys) + len(self._ext_leaves)

    def num_obj_codes(self) -> int:
        return self._obj_floor + len(self._ext_obj_codes)

    def num_rel_codes(self) -> int:
        return self._rel_floor + len(self._ext_rel_codes)

    # -- resolution ----------------------------------------------------------

    def resolve_set(self, ns_id: int, obj: str, rel: str) -> int:
        raw = self._base.resolve_set(ns_id, obj, rel)
        if raw >= 0:
            return raw
        return self._ext_set_ids.get((ns_id, obj, rel), -1)

    def resolve_leaf(self, subject_id: str) -> int:
        raw = self._base.resolve_leaf(subject_id)
        if raw >= 0:
            return raw
        return self._ext_leaf_ids.get(subject_id, -1)

    def obj_code(self, s: str) -> int:
        c = self._base.obj_code(s)
        if c >= 0:
            return c
        return self._ext_obj_codes.get(s, -1)

    def rel_code(self, s: str) -> int:
        c = self._base.rel_code(s)
        if c >= 0:
            return c
        return self._ext_rel_codes.get(s, -1)

    def resolve_queries(self, buf: bytes, n: int):
        """Bulk literal resolution through the base's native tables, with
        leaf raw ids re-offset for the grown set count. Ext-only keys come
        back -1; the engine re-resolves those misses through the host path
        (``has_ext``). None when the base has no native bulk entry point."""
        base_rq = getattr(self._base, "resolve_queries", None)
        if base_rq is None:
            return None
        got = base_rq(buf, n)
        if got is None:
            return None
        start, sub = got
        k = len(self._ext_set_keys)
        if k:
            sub = np.where(sub >= self._base_num_sets, sub + k, sub)
        return start, sub

    # -- reverse lookups -----------------------------------------------------

    def set_key_of(self, raw_id: int):
        if raw_id < self._base_num_sets:
            return self._base.set_key_of(raw_id)
        return self._ext_set_keys[raw_id - self._base_num_sets]

    def leaf_str(self, idx: int) -> str:
        if idx < self._base_num_leaves:
            return self._base.leaf_str(idx)
        return self._ext_leaves[idx - self._base_num_leaves]


class IncrementalInterner:
    """Chunk-incremental interning with the exact ``intern_rows``
    semantics: feed row chunks in store ORDER BY order via ``add_rows``
    and ``finish()`` returns the same ``InternedGraph`` a single pass
    over the concatenated stream would produce (ids and field codes are
    assigned in first-occurrence order, which chunking cannot change).

    This is the Python half of the streaming scan+intern pipeline
    (keto_tpu/graph/stream_build.py): the SQL cursor hands over chunks
    as they arrive instead of materializing the full table first. The
    native streaming builder (native/ingest.cpp stream_build_*) is the
    parallel counterpart; both are fuzz-asserted bit-identical."""

    def __init__(self, wild_ns_ids: FrozenSet[int] = frozenset()):
        self._wild_ns_ids = wild_ns_ids
        self._set_ids: dict[tuple[int, str, str], int] = {}
        self._leaf_ids: dict[str, int] = {}
        self._objc = _Codes()
        self._relc = _Codes()
        # pass-1 accumulators (per-tuple field codes + subject raw kind)
        self._t_lhs: list[int] = []
        self._t_ns: list[int] = []
        self._t_obj: list[int] = []
        self._t_rel: list[int] = []
        self._t_sub_kind: list[int] = []
        self._t_sub_idx: list[int] = []

    @property
    def rows_seen(self) -> int:
        return len(self._t_lhs)

    def add_rows(self, rows: Iterable) -> None:
        """Intern one chunk (pass 1); chunks must arrive in stream order."""
        set_ids = self._set_ids
        leaf_ids = self._leaf_ids
        objc, relc = self._objc, self._relc

        def set_node(ns_id: int, obj: str, rel: str) -> int:
            key = (ns_id, obj, rel)
            idx = set_ids.get(key)
            if idx is None:
                idx = len(set_ids)
                set_ids[key] = idx
                # intern field codes at node creation so code numbering
                # matches the native interner exactly (ingest.cpp set_node)
                objc.code(obj)
                relc.code(rel)
            return idx

        def leaf_node(s: str) -> int:
            idx = leaf_ids.get(s)
            if idx is None:
                idx = len(leaf_ids)
                leaf_ids[s] = idx
            return idx

        t_lhs, t_ns = self._t_lhs, self._t_ns
        t_obj, t_rel = self._t_obj, self._t_rel
        t_sub_kind, t_sub_idx = self._t_sub_kind, self._t_sub_idx
        for r in rows:
            lhs = set_node(r.namespace_id, r.object, r.relation)
            t_lhs.append(lhs)
            t_ns.append(r.namespace_id)
            t_obj.append(objc.code(r.object))
            t_rel.append(relc.code(r.relation))
            if r.subject_id is not None:
                t_sub_kind.append(LEAF_KIND)
                t_sub_idx.append(leaf_node(r.subject_id))
            else:
                t_sub_kind.append(SET_KIND)
                t_sub_idx.append(
                    set_node(r.sset_namespace_id, r.sset_object, r.sset_relation)
                )

    def finish(self) -> InternedGraph:
        """Pass 2 over the accumulated per-tuple arrays: key arrays,
        wildcard edge expansion, first-occurrence edge dedup."""
        wild_ns_ids = self._wild_ns_ids
        set_ids = self._set_ids
        leaf_ids = self._leaf_ids
        objc, relc = self._objc, self._relc
        num_sets = len(set_ids)
        key_ns = np.empty(num_sets, np.int64)
        key_obj = np.empty(num_sets, np.int64)
        key_rel = np.empty(num_sets, np.int64)
        wild = np.zeros(num_sets, bool)
        for (ns_id, obj, rel), i in set_ids.items():
            key_ns[i] = ns_id
            key_obj[i] = objc.code(obj)
            key_rel[i] = relc.code(rel)
            wild[i] = (ns_id in wild_ns_ids) or obj == "" or rel == ""
        # resolve after the loop above — "" may first intern via a set key
        empty_obj = objc.by_str.get("")
        empty_rel = relc.by_str.get("")

        tn = np.asarray(self._t_ns, np.int64)
        to = np.asarray(self._t_obj, np.int64)
        tr = np.asarray(self._t_rel, np.int64)
        tl = np.asarray(self._t_lhs, np.int64)
        tk = np.asarray(self._t_sub_kind, np.int64)
        ti = np.asarray(self._t_sub_idx, np.int64)
        t_sub_raw = np.where(tk == SET_KIND, ti, ti + num_sets)

        # edges: literal LHS nodes take their own tuples' subjects;
        # wildcard-bearing set nodes take every matching tuple's subject
        srcs = [tl[~wild[tl]]] if tl.size else [np.zeros(0, np.int64)]
        dsts = [t_sub_raw[~wild[tl]]] if tl.size else [np.zeros(0, np.int64)]
        for i in np.nonzero(wild)[0]:
            m = np.ones(tl.shape[0], bool)
            if key_ns[i] not in wild_ns_ids:
                m &= tn == key_ns[i]
            if key_obj[i] != empty_obj:
                m &= to == key_obj[i]
            if key_rel[i] != empty_rel:
                m &= tr == key_rel[i]
            srcs.append(np.full(int(m.sum()), i, np.int64))
            dsts.append(t_sub_raw[m])

        src = np.concatenate(srcs) if srcs else np.zeros(0, np.int64)
        dst = np.concatenate(dsts) if dsts else np.zeros(0, np.int64)
        if src.size:
            # duplicate tuples produce duplicate store rows (random
            # shard_id PK, reference relationtuples.go:135-138) but add
            # nothing to reachability — dedup edges, keeping the FIRST
            # occurrence in emission order. Rows arrive sorted in the
            # store's ORDER BY, so a set node's surviving out-edge order
            # is exactly the order the Manager pages that node's tuples —
            # the expand engine's tree-child order rides on this.
            packed = src * np.int64(num_sets + len(leaf_ids)) + dst
            _, keep = np.unique(packed, return_index=True)
            src, dst = src[np.sort(keep)], dst[np.sort(keep)]

        return InternedGraph(
            set_ids=set_ids,
            leaf_ids=leaf_ids,
            obj_codes=objc.by_str,
            rel_codes=relc.by_str,
            key_ns=key_ns,
            key_obj=key_obj,
            key_rel=key_rel,
            key_wild=wild,
            src=src,
            dst=dst,
        )


def intern_rows(rows: Iterable, wild_ns_ids: FrozenSet[int] = frozenset()) -> InternedGraph:
    """Intern ``persistence.memory.InternalRow``-shaped rows (attributes:
    namespace_id, object, relation, subject_id | sset_*). ``wild_ns_ids`` are
    the ids of namespaces whose configured *name* is the empty string.
    One-shot wrapper over ``IncrementalInterner`` — the streaming build
    feeds the same machinery chunk by chunk."""
    it = IncrementalInterner(wild_ns_ids)
    it.add_rows(rows)
    return it.finish()
