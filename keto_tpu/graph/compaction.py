"""Overlay compaction: fold a delta overlay into the base layout in place.

Before this module, the only way to retire an overlay (keto_tpu/graph/
overlay.py) was a full rebuild — re-intern every row, re-peel, re-lay-out
every bucket — which at 50M tuples costs minutes and was also the forced
fallback whenever a write burst outgrew the overlay budget. Compaction
instead merges the overlay INTO the existing layout by segment, reusing
everything expensive:

- **interner**: never re-run. New overlay nodes fold in through an
  ``ExtendedInterned`` view (keto_tpu/graph/interner.py) — the immutable
  base tables plus tiny append-only extension dicts, so in-flight batches
  on the pre-compaction snapshot stay consistent;
- **device ids**: all base ids below ``num_live`` are STABLE. New
  sink-class nodes splice in at the sink/static boundary (statics shift
  up by the new-sink count — a vectorized remap of ``raw2dev`` and the
  forward CSR values, nothing else stores static ids); new static-class
  nodes append at the end. Bitmap geometry (``num_int``, ``num_active``,
  bucket row counts) never changes, so every compiled kernel geometry
  stays valid;
- **buckets**: overlay-ELL edges fill sentinel slots in their destination
  row; a row out of slots widens ITS bucket's column capacity (ids stay
  put — bucket membership is an id range, the degree key is only a
  layout heuristic). Tombstoned iterated edges get their slot
  sentinel-cleared in the host arrays (the device copy was already
  patched when the delta applied). Only touched buckets re-upload;
- **CSRs**: the forward CSR and the sink reverse CSR rebuild in O(E)
  vectorized passes — tombstones drop out physically, overlay edges
  splice in. Per-source child ORDER is preserved for expand parity: new
  children insert at their Manager ORDER-BY position exactly like the
  expand engine's overlay merge (keto_tpu/expand/tpu_engine.py
  _merge_overlay_children), so expand trees match a from-scratch rebuild.

``compact_snapshot`` is pure (the input snapshot and everything it shares
with older snapshots are untouched) and returns ``None`` when the overlay
needs a real re-layout, leaving the full rebuild as the fallback:

- a stale native library without the code-table-size exports;
- overlay edges whose source is a wildcard-bearing set node (their child
  order is GLOBAL row order — not reconstructible without a store scan);
- extension tables past ``max_ext`` nodes (repeated compactions must not
  grow an unbounded annex — fold it with one real rebuild).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from keto_tpu.graph.interner import ExtendedInterned
from keto_tpu.graph.snapshot import Bucket, GraphSnapshot


def _ceil_pow2(x: int) -> int:
    return 1 if x <= 1 else 1 << (int(x) - 1).bit_length()


@dataclass
class CompactionResult:
    snapshot: GraphSnapshot
    #: indices into ``snapshot.buckets`` whose host arrays changed (the
    #: engine re-uploads exactly these; untouched device buckets reuse)
    touched_buckets: list = field(default_factory=list)
    #: what happened to the 2-hop label index (keto_tpu/graph/labels.py):
    #: "none" (no index on the input), "kept" (interior subgraph
    #: unchanged — index reused as-is), "patched" (folded ELL inserts
    #: applied incrementally), "patch_abort" (the incremental patch ran
    #: past its visit budget or the resume sets were truncated — the
    #: engine counts ``label_patch_aborts`` and rebuilds), or "rebuild"
    #: (folded ELL deletions — deletion from a 2-hop cover is a rebuild)
    labels: str = "none"
    #: device bytes the touched-bucket re-uploads will place (the old and
    #: new copies of a touched bucket are co-resident while in-flight
    #: batches still gather the old one) — the HBM governor plans this
    #: BEFORE the engine re-uploads (keto_tpu/driver/hbm.py)
    touched_bytes: int = 0


def _subject_order_key(snap: GraphSnapshot, dev: int):
    """Manager ORDER-BY position of a child — identical to the expand
    engine's overlay merge key (subject sets sort before subject ids;
    each group by its key fields)."""
    kind, key = snap.key_of_dev(int(dev))
    return (0, key) if kind == "set" else (1, (key,))


def _removed_mask(keys: np.ndarray, removed: Optional[np.ndarray]) -> np.ndarray:
    """bool[len(keys)] — True where the packed (src<<32|dst) key is
    tombstoned."""
    if removed is None or removed.size == 0 or keys.size == 0:
        return np.zeros(keys.shape[0], bool)
    pos = np.clip(np.searchsorted(removed, keys), 0, removed.size - 1)
    return removed[pos] == keys


def compact_snapshot(
    snap: GraphSnapshot, max_ext: int = 65536, sorter=None, label_patcher=None
) -> Optional[CompactionResult]:
    """Fold ``snap``'s overlay into its base layout. Returns the compacted
    snapshot (same watermark, no overlay) plus the touched bucket indices,
    or ``None`` when the shape requires a full rebuild. ``sorter`` is the
    stable-argsort backend (keto_tpu/graph/device_build.py): the fold's
    expensive tail — re-deriving the transposed CSR and both list layouts
    from the spliced forward CSR — runs its edge-scale sorts on the
    device when given, bit-identically (the splice itself is O(E)
    vectorized scatters and stays host-side). ``label_patcher`` swaps the
    incremental label patch implementation (the engine passes its
    device-sweep resumption, keto_tpu/graph/label_build.py) — same
    ``patch_labels`` signature and abort contract."""
    if not snap.has_overlay:
        return CompactionResult(snapshot=snap)

    interned = snap.interned
    # a stale .so without code-table sizes cannot host an ExtendedInterned
    n_obj = getattr(interned, "num_obj_codes", lambda: None)()
    n_rel = getattr(interned, "num_rel_codes", lambda: None)()
    if n_obj is None or n_rel is None:
        return None

    ni = snap.num_int
    na = snap.num_active
    sb = snap.sink_base
    nl = snap.num_live
    nb = snap.n_base_nodes

    ov_set = snap.ov_set_ids or {}
    ov_leaf = snap.ov_leaf_ids or {}
    ov_class = snap.ov_class or {}
    ov_fwd = {int(k): list(v) for k, v in (snap.ov_fwd or {}).items()}
    ov_sink_in = snap.ov_sink_in or {}
    ov_ell = snap.ov_ell
    removed = snap.ov_removed
    if removed is not None and removed.size == 0:
        removed = None

    # wildcard sources: their child lists order by GLOBAL row order — an
    # overlay edge out of one is not foldable without a store scan
    if snap.has_wildcards and ov_fwd:
        wild_devs = snap.raw2dev[np.nonzero(np.asarray(interned.key_wild))[0]]
        srcs = np.fromiter(ov_fwd.keys(), np.int64, len(ov_fwd))
        if np.isin(srcs, wild_devs).any():
            return None

    # annex growth bound: repeated compactions extend the interner view;
    # past the cap a full rebuild folds everything back into one table
    prior_ext = getattr(interned, "n_ext", 0)
    if prior_ext + len(ov_set) + len(ov_leaf) > max_ext:
        return None

    # --- new nodes: ids, classes, extended interner -------------------------
    # fold order = overlay creation order (old overlay dev id); sinks
    # splice in at the sink/static boundary, statics append at the end
    ov_nodes = sorted(
        [(dev, "set", key) for key, dev in ov_set.items()]
        + [(dev, "leaf", s) for s, dev in ov_leaf.items()]
    )
    new_sinks = [rec for rec in ov_nodes if ov_class.get(rec[0]) != "static"]
    new_statics = [rec for rec in ov_nodes if ov_class.get(rec[0]) == "static"]
    S, T = len(new_sinks), len(new_statics)
    ov_map: dict[int, int] = {}
    for j, (dev, _, _) in enumerate(new_sinks):
        ov_map[dev] = nl + j
    for m, (dev, _, _) in enumerate(new_statics):
        ov_map[dev] = nb + S + m

    def remap(arr: np.ndarray) -> np.ndarray:
        """Old device ids → compacted ids, vectorized: ids below num_live
        are stable, old statics shift past the spliced-in sinks, overlay
        ids take their assigned slots."""
        a = np.asarray(arr, np.int64)
        out = a.copy()
        out[(a >= nl) & (a < nb)] += S
        m_ov = a >= nb
        if m_ov.any():
            out[m_ov] = np.asarray(
                [ov_map[int(d)] for d in a[m_ov]], np.int64
            )
        return out

    if ov_set or ov_leaf:
        new_set_keys = [key for _, kind, key in ov_nodes if kind == "set"]
        new_leaves = [key for _, kind, key in ov_nodes if kind == "leaf"]
        try:
            new_interned = ExtendedInterned(interned, new_set_keys, new_leaves)
        except ValueError:
            return None
        # raw-id order of ext nodes follows fold order within each kind,
        # so the dev of ext set i is the i-th "set" record's mapped id
        new_set_devs = np.asarray(
            [ov_map[dev] for dev, kind, _ in ov_nodes if kind == "set"], np.int64
        )
        new_leaf_devs = np.asarray(
            [ov_map[dev] for dev, kind, _ in ov_nodes if kind == "leaf"], np.int64
        )
    else:
        new_interned = interned
        new_set_devs = np.zeros(0, np.int64)
        new_leaf_devs = np.zeros(0, np.int64)

    ns_field = snap.num_sets  # raw2dev's set/leaf split point (pre-fold)
    old_r2d = snap.raw2dev
    raw2dev = np.concatenate(
        [
            remap(old_r2d[:ns_field]),
            new_set_devs,
            remap(old_r2d[ns_field:]),
            new_leaf_devs,
        ]
    )

    # --- forward CSR: drop tombstones, splice overlay edges in order --------
    fwd_indptr = snap.fwd_indptr
    fwd_indices = snap.fwd_indices
    old_counts = np.diff(fwd_indptr)
    rows_of = np.repeat(np.arange(nb, dtype=np.int64), old_counts)
    vals = fwd_indices.astype(np.int64)
    if removed is not None:
        kept = ~_removed_mask((rows_of << 32) | vals, removed)
        rows_of, vals = rows_of[kept], vals[kept]
        kept_counts = np.bincount(rows_of, minlength=nb).astype(np.int64)
    else:
        kept_counts = old_counts.astype(np.int64)

    # per-source merged child lists (kept base children are subject-sorted
    # for literal nodes; overlay children insert at their sort position —
    # the expand engine's Manager-order reconstruction, materialized)
    merged_rows: dict[int, np.ndarray] = {}
    if ov_fwd:
        import bisect as _bisect

        starts = np.concatenate([np.zeros(1, np.int64), np.cumsum(kept_counts)])
        okey = lambda d: _subject_order_key(snap, d)  # noqa: E731
        for src, extra in ov_fwd.items():
            if src < nb:
                base_ch = vals[starts[src] : starts[src + 1]]
            else:
                base_ch = np.zeros(0, np.int64)
            ov_sorted = sorted(extra, key=okey)
            positions = [
                _bisect.bisect_left(base_ch, okey(d), key=okey) for d in ov_sorted
            ]
            merged_rows[src] = np.insert(base_ch, positions, ov_sorted)

    n_new = nb + S + T
    new_counts = np.zeros(n_new, np.int64)
    # old rows land at their remapped position with their kept counts
    old_devs = np.arange(nb, dtype=np.int64)
    new_counts[np.where(old_devs >= nl, old_devs + S, old_devs)] = kept_counts
    for src, merged in merged_rows.items():
        nr = int(remap(np.asarray([src]))[0])
        new_counts[nr] = merged.shape[0]
    new_indptr = np.concatenate([np.zeros(1, np.int64), np.cumsum(new_counts)])
    new_indices = np.empty(int(new_indptr[-1]), np.int32)

    # bulk scatter of untouched rows
    plain_counts = kept_counts.copy()
    if merged_rows:
        base_merge_srcs = np.asarray(
            [s for s in merged_rows if s < nb], np.int64
        )
        plain_counts[base_merge_srcs] = 0
        plain_keep = ~np.isin(rows_of, base_merge_srcs)
        p_rows, p_vals = rows_of[plain_keep], vals[plain_keep]
    else:
        p_rows, p_vals = rows_of, vals
    if p_rows.size:
        group_starts = np.cumsum(plain_counts) - plain_counts
        rank = np.arange(p_rows.shape[0]) - np.repeat(
            group_starts[plain_counts > 0], plain_counts[plain_counts > 0]
        )
        new_rows = np.where(p_rows >= nl, p_rows + S, p_rows)
        pos = new_indptr[new_rows] + rank
        new_indices[pos] = remap(p_vals).astype(np.int32)
    for src, merged in merged_rows.items():
        nr = int(remap(np.asarray([src]))[0])
        a, b = int(new_indptr[nr]), int(new_indptr[nr + 1])
        new_indices[a:b] = remap(merged).astype(np.int32)

    # --- sink reverse CSR: drop tombstones, extend rows, append new sinks ---
    sink_indptr = snap.sink_indptr
    sink_indices = snap.sink_indices
    n_sink_old = nl - sb
    s_counts = np.diff(sink_indptr).astype(np.int64)
    s_rows = np.repeat(np.arange(n_sink_old, dtype=np.int64), s_counts)
    s_vals = sink_indices.astype(np.int64)
    if removed is not None and s_vals.size:
        # sink-edge tombstone keys pack as (interior src << 32) | sink dev
        keys = (s_vals << 32) | (s_rows + sb)
        kept = ~_removed_mask(keys, removed)
        s_rows, s_vals = s_rows[kept], s_vals[kept]
        s_counts = np.bincount(s_rows, minlength=n_sink_old).astype(np.int64)
    add_counts = np.zeros(n_sink_old + S, np.int64)
    adds: dict[int, np.ndarray] = {}
    for dst, srcs in ov_sink_in.items():
        nd = int(remap(np.asarray([dst]))[0])
        local = nd - sb
        if not (0 <= local < n_sink_old + S):
            return None  # sink-class edge to a non-sink row — be safe
        adds[local] = np.asarray(srcs, np.int64)
        add_counts[local] = adds[local].shape[0]
    new_s_counts = np.concatenate([s_counts, np.zeros(S, np.int64)]) + add_counts
    new_sink_indptr = np.concatenate(
        [np.zeros(1, np.int64), np.cumsum(new_s_counts)]
    )
    new_sink_indices = np.empty(int(new_sink_indptr[-1]), np.int32)
    if s_rows.size:
        g_starts = np.cumsum(s_counts) - s_counts
        rank = np.arange(s_rows.shape[0]) - np.repeat(
            g_starts[s_counts > 0], s_counts[s_counts > 0]
        )
        new_sink_indices[new_sink_indptr[s_rows] + rank] = s_vals.astype(np.int32)
    for local, srcs in adds.items():
        base_n = int(s_counts[local]) if local < n_sink_old else 0
        a = int(new_sink_indptr[local]) + base_n
        new_sink_indices[a : a + srcs.shape[0]] = srcs.astype(np.int32)

    # --- buckets: fill sentinel slots / widen; clear tombstoned slots -------
    buckets = list(snap.buckets)
    touched: dict[int, np.ndarray] = {}  # bucket index → working copy
    offsets = np.asarray([b.offset for b in buckets], np.int64)
    sentinel = np.int32(ni)

    def bucket_of(dst: int) -> int:
        bi = int(np.searchsorted(offsets, dst, "right")) - 1
        b = buckets[bi]
        if not (b.offset <= dst < b.offset + b.n):
            raise LookupError(dst)
        return bi

    def working(bi: int) -> np.ndarray:
        w = touched.get(bi)
        if w is None:
            w = buckets[bi].nbrs.copy()
            touched[bi] = w
        return w

    try:
        if removed is not None:
            ell_keys = removed[(removed >> 32) < ni]
            for key in ell_keys.tolist():
                src, dst = key >> 32, key & 0xFFFFFFFF
                if dst >= na:
                    continue  # not an iterated edge (interior→sink handled above)
                bi = bucket_of(dst)
                w = working(bi)
                row = dst - buckets[bi].offset
                cols = np.nonzero(w[row] == src)[0]
                if cols.size == 0:
                    return None  # base layout disagrees — be safe
                w[row, cols[0]] = sentinel
        if ov_ell is not None:
            for src, dst in ov_ell.tolist():
                bi = bucket_of(int(dst))
                w = working(bi)
                row = int(dst) - buckets[bi].offset
                free = np.nonzero(w[row] == sentinel)[0]
                if free.size == 0:
                    # row out of slots: widen THIS bucket's capacity (ids
                    # stay put; the degree key is only a layout heuristic)
                    wide = np.full(
                        (w.shape[0], _ceil_pow2(w.shape[1] + 1)),
                        sentinel,
                        np.int32,
                    )
                    wide[:, : w.shape[1]] = w
                    w = touched[bi] = wide
                    free = np.nonzero(w[row] == sentinel)[0]
                w[row, free[0]] = np.int32(src)
    except (LookupError, IndexError):
        return None  # edge points outside the bucketed rows — be safe
    for bi, w in touched.items():
        b = buckets[bi]
        buckets[bi] = Bucket(offset=b.offset, n=b.n, nbrs=w)

    new_snap = GraphSnapshot(
        snapshot_id=snap.snapshot_id,
        num_sets=new_interned.num_sets,
        num_leaves=new_interned.num_leaves,
        num_active=na,
        num_int=ni,
        num_live=nl + S,
        n_peeled=snap.n_peeled,
        buckets=buckets,
        interned=new_interned,
        raw2dev=raw2dev,
        wild_ns_ids=snap.wild_ns_ids,
        fwd_indptr=new_indptr,
        fwd_indices=new_indices,
        sink_indptr=new_sink_indptr,
        sink_indices=new_sink_indices,
        _pattern_cache={},
        _cache_lock=threading.Lock(),
    )
    # reverse-query layouts (keto_tpu/list/): re-derive BOTH orientations
    # from the folded forward CSR — the fold clears lst_dirty/lst_patch by
    # construction (overlay edges are now base edges), and the list
    # engine re-uploads the fresh arrays on next use
    from keto_tpu.graph.snapshot import build_list_layouts, build_rev_csr

    n_nodes_new = new_indptr.shape[0] - 1
    new_snap.rev_indptr, new_snap.rev_indices = build_rev_csr(
        new_indptr, new_indices, n_nodes_new, sorter=sorter
    )
    new_snap.lay_fwd, new_snap.lay_rev = build_list_layouts(
        new_indptr, new_indices, n_nodes_new, new_snap.sink_base, sorter=sorter
    )
    # reuse untouched device buckets; the engine re-uploads the touched set
    if snap.device_buckets is not None:
        bufs = list(snap.device_buckets)
        for bi in touched:
            bufs[bi] = None
        new_snap.device_buckets = tuple(bufs)

    # --- 2-hop labels: patch for folded ELL inserts, rebuild on deletes -----
    # (keto_tpu/graph/labels.py). The fold clears lab_dirty by
    # construction: the compacted snapshot either carries an index that
    # exactly matches its interior subgraph, or no index at all.
    labels_state = "none"
    idx = snap.labels
    if idx is not None:
        removed_ell = False
        if removed is not None and removed.size:
            keys = removed[(removed >> 32) < ni]
            removed_ell = bool(keys.size) and bool(
                np.any((keys & np.int64(0xFFFFFFFF)) < na)
            )
        if removed_ell:
            # deleting from a 2-hop cover is a rebuild in the literature
            # too — leave labels off; the engine rebuilds off-path
            labels_state = "rebuild"
        elif ov_ell is not None and ov_ell.shape[0]:
            if label_patcher is None:
                from keto_tpu.graph.labels import patch_labels as label_patcher

            patched = label_patcher(
                idx, new_snap, [tuple(e) for e in ov_ell.tolist()]
            )
            if patched is not None:
                new_snap.labels = patched
                labels_state = "patched"
            else:
                # budget/truncation — be safe; the engine counts the
                # abort and schedules the (device) rebuild
                labels_state = "patch_abort"
        else:
            # interior subgraph untouched (sink splices, host-walk edges,
            # host-masked tombstones only): the index is still exact
            new_snap.labels = idx
            new_snap.device_labels = snap.device_labels
            labels_state = "kept"
    return CompactionResult(
        snapshot=new_snap,
        touched_buckets=sorted(touched),
        labels=labels_state,
        touched_bytes=sum(int(w.nbytes) for w in touched.values()),
    )
