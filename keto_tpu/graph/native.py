"""ctypes binding for the native (C++) tuple→graph interner.

``native/ingest.cpp`` implements the same interning contract as
``keto_tpu.graph.interner.intern_rows`` (same node-id assignment order, same
wildcard-expansion edges, same dedup), parsing a packed byte buffer in one
native pass and keeping the string tables resident so per-query resolution
stays in C++. Build it with ``make native`` (repo root); loading is
opportunistic — ``load_library()`` returns None and callers fall back to the
Python interner when the shared object is absent or
``KETO_TPU_NATIVE=0``.
"""

from __future__ import annotations

import ctypes
import os
from pathlib import Path
from typing import Iterable, Optional

import numpy as np

_FIELD = b"\x1f"
_RECORD = b"\x1e"

_lib: Optional[ctypes.CDLL] = None
_lib_checked = False


def _candidate_paths():
    if os.environ.get("KETO_TPU_NATIVE_LIB"):
        yield Path(os.environ["KETO_TPU_NATIVE_LIB"])
    root = Path(__file__).resolve().parents[2]
    yield root / "native" / "libketoingest.so"


def load_library() -> Optional[ctypes.CDLL]:
    global _lib, _lib_checked
    if _lib_checked:
        return _lib
    _lib_checked = True
    if os.environ.get("KETO_TPU_NATIVE", "1") == "0":
        return None
    for path in _candidate_paths():
        if not path.exists():
            continue
        try:
            lib = ctypes.CDLL(str(path))
        except OSError:
            continue  # corrupt / wrong-arch build → Python fallback
        c = ctypes.c_int64
        p = ctypes.c_void_p
        lib.graph_build.restype = p
        lib.graph_build.argtypes = [ctypes.c_char_p, c, ctypes.POINTER(c), c]
        lib.graph_free.argtypes = [p]
        for fn in ("graph_num_sets", "graph_num_leaves", "graph_num_edges"):
            getattr(lib, fn).restype = c
            getattr(lib, fn).argtypes = [p]
        lib.graph_edges.argtypes = [p, ctypes.POINTER(c), ctypes.POINTER(c)]
        lib.graph_release_edges.argtypes = [p]
        lib.graph_keys.argtypes = [
            p, ctypes.POINTER(c), ctypes.POINTER(c), ctypes.POINTER(c),
            ctypes.POINTER(ctypes.c_uint8),
        ]
        lib.graph_resolve_set.restype = c
        lib.graph_resolve_set.argtypes = [p, c, ctypes.c_char_p, c, ctypes.c_char_p, c]
        if hasattr(lib, "graph_resolve_queries"):
            lib.graph_resolve_queries.restype = c
            lib.graph_resolve_queries.argtypes = [
                p, ctypes.c_char_p, c, c, ctypes.POINTER(c), ctypes.POINTER(c),
            ]
        for fn in ("graph_resolve_leaf", "graph_obj_code", "graph_rel_code"):
            getattr(lib, fn).restype = c
            getattr(lib, fn).argtypes = [p, ctypes.c_char_p, c]
        for fn in ("graph_obj_str", "graph_rel_str", "graph_leaf_str"):
            if hasattr(lib, fn):
                getattr(lib, fn).restype = p
                getattr(lib, fn).argtypes = [p, c, ctypes.POINTER(c)]
        _lib = lib
        return _lib
    return None


def encode_row(r) -> bytes:
    """One InternalRow-shaped row in the parser's record format — the single
    Python-side definition of the wire encoding (native/ingest.cpp parses
    it; InternalRow.packed() caches it)."""
    if r.subject_id is not None:
        sub = b"1" + _FIELD + r.subject_id.encode() + _FIELD + _FIELD
    else:
        sub = (
            b"0" + _FIELD + str(r.sset_namespace_id).encode() + _FIELD
            + r.sset_object.encode() + _FIELD + r.sset_relation.encode()
        )
    return (
        str(r.namespace_id).encode() + _FIELD + r.object.encode() + _FIELD
        + r.relation.encode() + _FIELD + sub + _RECORD
    )


def pack_rows(rows) -> bytes:
    """Serialize rows into the parser's buffer format. Rows exposing
    ``packed()`` (keto_tpu.persistence.memory.InternalRow) amortize the
    encoding across snapshot rebuilds."""
    if not isinstance(rows, list):
        rows = list(rows)
    if not rows:
        return b""
    if hasattr(rows[0], "packed"):
        return b"".join(r.packed() for r in rows)
    return b"".join(encode_row(r) for r in rows)


class NativeInterned:
    """Drop-in for ``InternedGraph``: same arrays and resolution interface,
    backed by the resident C++ intern tables."""

    def __init__(self, lib: ctypes.CDLL, handle: int):
        self._lib = lib
        self._handle = handle
        c = ctypes.c_int64
        self.num_sets = int(lib.graph_num_sets(handle))
        self.num_leaves = int(lib.graph_num_leaves(handle))
        n_edges = int(lib.graph_num_edges(handle))
        self.src = np.empty(n_edges, np.int64)
        self.dst = np.empty(n_edges, np.int64)
        if n_edges:
            lib.graph_edges(
                handle,
                self.src.ctypes.data_as(ctypes.POINTER(c)),
                self.dst.ctypes.data_as(ctypes.POINTER(c)),
            )
        lib.graph_release_edges(handle)  # numpy owns the copies now
        self.key_ns = np.empty(self.num_sets, np.int64)
        self.key_obj = np.empty(self.num_sets, np.int64)
        self.key_rel = np.empty(self.num_sets, np.int64)
        self.key_wild = np.empty(self.num_sets, np.uint8)
        if self.num_sets:
            lib.graph_keys(
                handle,
                self.key_ns.ctypes.data_as(ctypes.POINTER(c)),
                self.key_obj.ctypes.data_as(ctypes.POINTER(c)),
                self.key_rel.ctypes.data_as(ctypes.POINTER(c)),
                self.key_wild.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            )
        self.key_wild = self.key_wild.astype(bool)

    @property
    def num_nodes(self) -> int:
        return self.num_sets + self.num_leaves

    def __del__(self):
        lib = getattr(self, "_lib", None)
        if lib is not None and self._handle:
            lib.graph_free(self._handle)
            self._handle = None

    def resolve_set(self, ns_id: int, obj: str, rel: str) -> int:
        o, r = obj.encode(), rel.encode()
        return int(self._lib.graph_resolve_set(self._handle, ns_id, o, len(o), r, len(r)))

    def resolve_queries(self, buf: bytes, n: int):
        """Bulk literal-query resolution: ``buf`` packs ``n`` records in the
        row wire format (kind 1: f0 = subject id; kind 0: subject set).
        Returns ``(start_raw, sub_raw)`` int64 arrays (-1 = not present;
        leaf subjects offset by num_sets), or None when the lib predates
        the entry point or rejects the buffer framing."""
        if not hasattr(self._lib, "graph_resolve_queries"):
            return None
        c = ctypes.c_int64
        start = np.empty(n, np.int64)
        sub = np.empty(n, np.int64)
        rc = self._lib.graph_resolve_queries(
            self._handle, buf, len(buf), n,
            start.ctypes.data_as(ctypes.POINTER(c)),
            sub.ctypes.data_as(ctypes.POINTER(c)),
        )
        if rc != 0:
            return None
        return start, sub

    def resolve_leaf(self, subject_id: str) -> int:
        s = subject_id.encode()
        return int(self._lib.graph_resolve_leaf(self._handle, s, len(s)))

    def obj_code(self, s: str) -> int:
        b = s.encode()
        return int(self._lib.graph_obj_code(self._handle, b, len(b)))

    def rel_code(self, s: str) -> int:
        b = s.encode()
        return int(self._lib.graph_rel_code(self._handle, b, len(b)))

    # -- reverse lookups (expand-tree reconstruction) ------------------------

    def _str_at(self, fn_name: str, idx: int) -> str:
        fn = getattr(self._lib, fn_name, None)
        if fn is None:
            # silently returning None would embed null strings in expand
            # trees; fail loud with the remedy instead
            raise RuntimeError(
                "libketoingest.so predates the expand reverse-lookup "
                "exports — rebuild it with `make native` (or set "
                "KETO_TPU_NATIVE=0 to use the Python interner)"
            )
        n = ctypes.c_int64()
        ptr = fn(self._handle, idx, ctypes.byref(n))
        if not ptr:
            raise IndexError(f"{fn_name}({idx}) out of range")
        return ctypes.string_at(ptr, n.value).decode()

    def set_key_of(self, raw_id: int):
        """``(ns_id, object, relation)`` of set node ``raw_id`` — field
        codes come from the resident key arrays, strings from the C tables."""
        return (
            int(self.key_ns[raw_id]),
            self._str_at("graph_obj_str", int(self.key_obj[raw_id])),
            self._str_at("graph_rel_str", int(self.key_rel[raw_id])),
        )

    def leaf_str(self, idx: int) -> Optional[str]:
        """Subject-id string of leaf ``idx`` (not offset by num_sets)."""
        return self._str_at("graph_leaf_str", idx)


def native_intern_rows(rows: Iterable, wild_ns_ids=frozenset()) -> Optional[NativeInterned]:
    """Native counterpart of ``intern_rows``; None when the lib is absent."""
    lib = load_library()
    if lib is None:
        return None
    if not isinstance(rows, list):
        rows = list(rows)
    buf = pack_rows(rows)
    # strings containing the separator control bytes would corrupt the
    # framing — detectable as a field-count mismatch; fall back to Python
    if buf.count(_FIELD) != 6 * len(rows) or buf.count(_RECORD) != len(rows):
        return None
    wild = np.asarray(sorted(wild_ns_ids), np.int64)
    handle = lib.graph_build(
        buf,
        len(buf),
        wild.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(wild),
    )
    if not handle:
        return None  # parser rejected the buffer → Python fallback
    return NativeInterned(lib, handle)
