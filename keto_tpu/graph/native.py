"""ctypes binding for the native (C++) tuple→graph interner.

``native/ingest.cpp`` implements the same interning contract as
``keto_tpu.graph.interner.intern_rows`` (same node-id assignment order, same
wildcard-expansion edges, same dedup), parsing a packed byte buffer in one
native pass and keeping the string tables resident so per-query resolution
stays in C++. Build it with ``make native`` (repo root); loading is
opportunistic — ``load_library()`` returns None and callers fall back to the
Python interner when the shared object is absent or
``KETO_TPU_NATIVE=0``.
"""

from __future__ import annotations

import ctypes
import os
from pathlib import Path
from typing import Iterable, Optional

import numpy as np

_FIELD = b"\x1f"
_RECORD = b"\x1e"

_lib: Optional[ctypes.CDLL] = None
_lib_checked = False


def _candidate_paths():
    if os.environ.get("KETO_TPU_NATIVE_LIB"):
        yield Path(os.environ["KETO_TPU_NATIVE_LIB"])
    root = Path(__file__).resolve().parents[2]
    yield root / "native" / "libketoingest.so"


def load_library() -> Optional[ctypes.CDLL]:
    global _lib, _lib_checked
    if _lib_checked:
        return _lib
    _lib_checked = True
    if os.environ.get("KETO_TPU_NATIVE", "1") == "0":
        return None
    for path in _candidate_paths():
        if not path.exists():
            continue
        try:
            lib = ctypes.CDLL(str(path))
        except OSError:
            continue  # corrupt / wrong-arch build → Python fallback
        c = ctypes.c_int64
        p = ctypes.c_void_p
        lib.graph_build.restype = p
        lib.graph_build.argtypes = [ctypes.c_char_p, c, ctypes.POINTER(c), c]
        if hasattr(lib, "graph_build_columnar"):
            pc = ctypes.POINTER(c)
            pb = ctypes.c_char_p
            lib.graph_build_columnar.restype = p
            lib.graph_build_columnar.argtypes = (
                [c, pc, ctypes.POINTER(ctypes.c_uint8), pc]
                + [pb, pc, pc] * 5
                + [pc, c]
            )
        if hasattr(lib, "graph_build_ucs4"):
            pc = ctypes.POINTER(c)
            pu = ctypes.POINTER(ctypes.c_uint32)
            lib.graph_build_ucs4.restype = p
            lib.graph_build_ucs4.argtypes = (
                [c, pc, ctypes.POINTER(ctypes.c_uint8), pc]
                + [pu, c] * 5
                + [pc, c]
            )
        if hasattr(lib, "stream_build_new"):
            # streaming (chunk-fed) builder: scan chunks intern on a
            # worker pool concurrently with the caller's next fetch
            lib.stream_build_new.restype = p
            lib.stream_build_new.argtypes = [ctypes.POINTER(c), c, c]
            lib.stream_build_feed.restype = c
            lib.stream_build_feed.argtypes = [p, ctypes.c_char_p, c, c]
            lib.stream_build_finish.restype = p
            lib.stream_build_finish.argtypes = [p]
            lib.stream_build_abort.argtypes = [p]
        lib.graph_free.argtypes = [p]
        for fn in ("graph_num_sets", "graph_num_leaves", "graph_num_edges"):
            getattr(lib, fn).restype = c
            getattr(lib, fn).argtypes = [p]
        for fn in ("graph_num_obj_codes", "graph_num_rel_codes"):
            if hasattr(lib, fn):
                getattr(lib, fn).restype = c
                getattr(lib, fn).argtypes = [p]
        lib.graph_edges.argtypes = [p, ctypes.POINTER(c), ctypes.POINTER(c)]
        lib.graph_release_edges.argtypes = [p]
        lib.graph_keys.argtypes = [
            p, ctypes.POINTER(c), ctypes.POINTER(c), ctypes.POINTER(c),
            ctypes.POINTER(ctypes.c_uint8),
        ]
        lib.graph_resolve_set.restype = c
        lib.graph_resolve_set.argtypes = [p, c, ctypes.c_char_p, c, ctypes.c_char_p, c]
        if hasattr(lib, "graph_resolve_queries"):
            lib.graph_resolve_queries.restype = c
            lib.graph_resolve_queries.argtypes = [
                p, ctypes.c_char_p, c, c, ctypes.POINTER(c), ctypes.POINTER(c),
            ]
        for fn in ("graph_resolve_leaf", "graph_obj_code", "graph_rel_code"):
            getattr(lib, fn).restype = c
            getattr(lib, fn).argtypes = [p, ctypes.c_char_p, c]
        for fn in ("graph_obj_str", "graph_rel_str", "graph_leaf_str"):
            if hasattr(lib, fn):
                getattr(lib, fn).restype = p
                getattr(lib, fn).argtypes = [p, c, ctypes.POINTER(c)]
        _lib = lib
        return _lib
    return None


def encode_row(r) -> bytes:
    """One InternalRow-shaped row in the parser's record format — the single
    Python-side definition of the wire encoding (native/ingest.cpp parses
    it; InternalRow.packed() caches it)."""
    if r.subject_id is not None:
        sub = b"1" + _FIELD + r.subject_id.encode() + _FIELD + _FIELD
    else:
        sub = (
            b"0" + _FIELD + str(r.sset_namespace_id).encode() + _FIELD
            + r.sset_object.encode() + _FIELD + r.sset_relation.encode()
        )
    return (
        str(r.namespace_id).encode() + _FIELD + r.object.encode() + _FIELD
        + r.relation.encode() + _FIELD + sub + _RECORD
    )


def pack_rows(rows) -> bytes:
    """Serialize rows into the parser's buffer format. Rows exposing
    ``packed()`` (keto_tpu.persistence.memory.InternalRow) amortize the
    encoding across snapshot rebuilds."""
    if not isinstance(rows, list):
        rows = list(rows)
    if not rows:
        return b""
    if hasattr(rows[0], "packed"):
        return b"".join(r.packed() for r in rows)
    return b"".join(encode_row(r) for r in rows)


class NativeInterned:
    """Drop-in for ``InternedGraph``: same arrays and resolution interface,
    backed by the resident C++ intern tables."""

    def __init__(self, lib: ctypes.CDLL, handle: int):
        self._lib = lib
        self._handle = handle
        c = ctypes.c_int64
        self.num_sets = int(lib.graph_num_sets(handle))
        self.num_leaves = int(lib.graph_num_leaves(handle))
        n_edges = int(lib.graph_num_edges(handle))
        self.src = np.empty(n_edges, np.int64)
        self.dst = np.empty(n_edges, np.int64)
        if n_edges:
            lib.graph_edges(
                handle,
                self.src.ctypes.data_as(ctypes.POINTER(c)),
                self.dst.ctypes.data_as(ctypes.POINTER(c)),
            )
        lib.graph_release_edges(handle)  # numpy owns the copies now
        self.key_ns = np.empty(self.num_sets, np.int64)
        self.key_obj = np.empty(self.num_sets, np.int64)
        self.key_rel = np.empty(self.num_sets, np.int64)
        self.key_wild = np.empty(self.num_sets, np.uint8)
        if self.num_sets:
            lib.graph_keys(
                handle,
                self.key_ns.ctypes.data_as(ctypes.POINTER(c)),
                self.key_obj.ctypes.data_as(ctypes.POINTER(c)),
                self.key_rel.ctypes.data_as(ctypes.POINTER(c)),
                self.key_wild.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            )
        self.key_wild = self.key_wild.astype(bool)

    @property
    def num_nodes(self) -> int:
        return self.num_sets + self.num_leaves

    def num_obj_codes(self) -> Optional[int]:
        """Size of the object-string code table, or None when the loaded
        .so predates the export (compaction then falls back to a full
        rebuild rather than guessing a safe code range)."""
        if not hasattr(self._lib, "graph_num_obj_codes"):
            return None
        return int(self._lib.graph_num_obj_codes(self._handle))

    def num_rel_codes(self) -> Optional[int]:
        if not hasattr(self._lib, "graph_num_rel_codes"):
            return None
        return int(self._lib.graph_num_rel_codes(self._handle))

    def __del__(self):
        lib = getattr(self, "_lib", None)
        if lib is not None and self._handle:
            lib.graph_free(self._handle)
            self._handle = None

    def resolve_set(self, ns_id: int, obj: str, rel: str) -> int:
        o, r = obj.encode(), rel.encode()
        return int(self._lib.graph_resolve_set(self._handle, ns_id, o, len(o), r, len(r)))

    def resolve_queries(self, buf: bytes, n: int):
        """Bulk literal-query resolution: ``buf`` packs ``n`` records in the
        row wire format (kind 1: f0 = subject id; kind 0: subject set).
        Returns ``(start_raw, sub_raw)`` int64 arrays (-1 = not present;
        leaf subjects offset by num_sets), or None when the lib predates
        the entry point or rejects the buffer framing."""
        if not hasattr(self._lib, "graph_resolve_queries"):
            return None
        c = ctypes.c_int64
        start = np.empty(n, np.int64)
        sub = np.empty(n, np.int64)
        rc = self._lib.graph_resolve_queries(
            self._handle, buf, len(buf), n,
            start.ctypes.data_as(ctypes.POINTER(c)),
            sub.ctypes.data_as(ctypes.POINTER(c)),
        )
        if rc != 0:
            return None
        return start, sub

    def resolve_leaf(self, subject_id: str) -> int:
        s = subject_id.encode()
        return int(self._lib.graph_resolve_leaf(self._handle, s, len(s)))

    def obj_code(self, s: str) -> int:
        b = s.encode()
        return int(self._lib.graph_obj_code(self._handle, b, len(b)))

    def rel_code(self, s: str) -> int:
        b = s.encode()
        return int(self._lib.graph_rel_code(self._handle, b, len(b)))

    # -- reverse lookups (expand-tree reconstruction) ------------------------

    def _str_at(self, fn_name: str, idx: int) -> str:
        fn = getattr(self._lib, fn_name, None)
        if fn is None:
            # silently returning None would embed null strings in expand
            # trees; fail loud with the remedy instead
            raise RuntimeError(
                "libketoingest.so predates the expand reverse-lookup "
                "exports — rebuild it with `make native` (or set "
                "KETO_TPU_NATIVE=0 to use the Python interner)"
            )
        n = ctypes.c_int64()
        ptr = fn(self._handle, idx, ctypes.byref(n))
        if not ptr:
            raise IndexError(f"{fn_name}({idx}) out of range")
        return ctypes.string_at(ptr, n.value).decode()

    def set_key_of(self, raw_id: int):
        """``(ns_id, object, relation)`` of set node ``raw_id`` — field
        codes come from the resident key arrays, strings from the C tables."""
        return (
            int(self.key_ns[raw_id]),
            self._str_at("graph_obj_str", int(self.key_obj[raw_id])),
            self._str_at("graph_rel_str", int(self.key_rel[raw_id])),
        )

    def leaf_str(self, idx: int) -> Optional[str]:
        """Subject-id string of leaf ``idx`` (not offset by num_sets)."""
        return self._str_at("graph_leaf_str", idx)


def _string_column(strs: list) -> Optional[tuple[bytes, np.ndarray, np.ndarray]]:
    """(utf-8 blob, byte starts, byte lens) for a string column, built in
    a handful of vectorized passes (the columnar fast path's whole point:
    no per-row Python encode). Joins on NUL — multi-byte UTF-8 never
    contains a 0x00 byte, so separator positions are exactly the zero
    bytes of the encoded blob. None when a string embeds NUL (nothing
    legitimate does; the packed-buffer path handles it by falling back to
    the Python interner)."""
    n = len(strs)
    if n == 0:
        return b"", np.zeros(0, np.int64), np.zeros(0, np.int64)
    joined = "\x00".join(strs)
    if joined.count("\x00") != n - 1:
        return None
    blob = joined.encode()
    seps = np.nonzero(np.frombuffer(blob, np.uint8) == 0)[0]
    starts = np.empty(n, np.int64)
    starts[0] = 0
    starts[1:] = seps + 1
    ends = np.empty(n, np.int64)
    ends[:-1] = seps
    ends[-1] = len(blob)
    return blob, starts, ends - starts


def native_intern_rows_columnar(
    lib, rows: list, wild_ns_ids
) -> Optional[NativeInterned]:
    from operator import attrgetter

    n = len(rows)
    c = ctypes.c_int64
    # C-speed column extraction: one attrgetter map per column (a Python
    # per-row loop over six attributes dominated the handoff at 10M rows)
    ns = np.fromiter(map(attrgetter("namespace_id"), rows), np.int64, n)
    col_sid = list(map(attrgetter("subject_id"), rows))
    kind = np.fromiter((s is not None for s in col_sid), np.uint8, n)
    sns = np.fromiter(
        (v if v is not None else 0 for v in map(attrgetter("sset_namespace_id"), rows)),
        np.int64,
        n,
    )
    cols = []
    for attr, none_ok in (
        ("object", False), ("relation", False), ("subject_id", True),
        ("sset_object", True), ("sset_relation", True),
    ):
        vals = col_sid if attr == "subject_id" else list(map(attrgetter(attr), rows))
        if none_ok:
            # `or ""` maps None→"" and keeps "" as-is — the only falsy str
            vals = [v or "" for v in vals]
        col = _string_column(vals)
        if col is None:
            return None
        cols.append(col)

    def ptr(a):
        return a.ctypes.data_as(ctypes.POINTER(c))

    wild = np.asarray(sorted(wild_ns_ids), np.int64)
    args = [n, ptr(ns), kind.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), ptr(sns)]
    for blob, starts, lens in cols:
        args += [blob, ptr(starts), ptr(lens)]
    args += [ptr(wild), len(wild)]
    handle = lib.graph_build_columnar(*args)
    if not handle:
        return None
    return NativeInterned(lib, handle)


def _ucs4_ok(arr: np.ndarray) -> bool:
    """True when every cell's NUL padding is trailing-only: an embedded
    NUL code point would truncate in the C++ decoder (NUL is the pad)."""
    if arr.dtype.itemsize == 0 or arr.size == 0:
        return True
    v = arr.view(np.uint32).reshape(arr.shape[0], -1)
    if v.shape[1] <= 1:
        return True
    z = v == 0
    return not bool(np.any(z[:, :-1] & (v[:, 1:] != 0)))


def native_intern_columns(lib, columns: dict, wild_ns_ids) -> Optional[NativeInterned]:
    """Intern from the store's cached sorted column bundle (numpy '<U*'
    string arrays + int/kind arrays) — zero per-row Python work; the C++
    side decodes UCS4 cells straight out of the numpy buffers."""
    if not hasattr(lib, "graph_build_ucs4"):
        return None
    c = ctypes.c_int64
    n = int(columns["ns"].shape[0])
    str_cols = []
    for name in ("obj", "rel", "sid", "sso", "ssr"):
        arr = np.ascontiguousarray(columns[name])
        if arr.dtype.kind != "U" or not _ucs4_ok(arr):
            return None
        str_cols.append(arr)
    ns = np.ascontiguousarray(columns["ns"], np.int64)
    kind = np.ascontiguousarray(columns["kind"], np.uint8)
    sns = np.ascontiguousarray(columns["sns"], np.int64)

    def ptr(a):
        return a.ctypes.data_as(ctypes.POINTER(c))

    wild = np.asarray(sorted(wild_ns_ids), np.int64)
    args = [n, ptr(ns), kind.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), ptr(sns)]
    for arr in str_cols:
        args += [
            arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            arr.dtype.itemsize // 4,
        ]
    args += [ptr(wild), len(wild)]
    handle = lib.graph_build_ucs4(*args)
    if not handle:
        return None
    return NativeInterned(lib, handle)


class NativeStreamBuilder:
    """Chunk-fed native interner (native/ingest.cpp stream_build_*).

    ``feed(rows)`` packs one scan chunk into the wire format and hands
    it to the C++ worker pool — the call returns as soon as the chunk is
    enqueued (or after blocking briefly on the bounded queue), so the
    caller's next store fetch overlaps interning. ``finish()`` merges
    the per-chunk shards in feed order, which reproduces the one-shot
    build's first-occurrence ids bit-identically
    (tests/test_streaming_build.py asserts equality against both the
    one-shot native path and the Python interner).

    A chunk the packer cannot frame (strings containing the separator
    control bytes — nothing legitimate does) poisons the native stream;
    ``feed`` then returns False and the caller falls back to the Python
    interner over its accumulated rows.
    """

    def __init__(self, lib: ctypes.CDLL, wild_ns_ids):
        self._lib = lib
        wild = np.asarray(sorted(wild_ns_ids), np.int64)
        self._handle = lib.stream_build_new(
            wild.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), len(wild), 0
        )
        self._dead = self._handle is None or not self._handle

    @classmethod
    def create(cls, wild_ns_ids) -> Optional["NativeStreamBuilder"]:
        lib = load_library()
        if lib is None or not hasattr(lib, "stream_build_new"):
            return None
        sb = cls(lib, wild_ns_ids)
        return None if sb._dead else sb

    def feed(self, rows: list) -> bool:
        """Enqueue one chunk; False when the stream is unusable (framing
        rejection or an earlier malformed chunk)."""
        if self._dead:
            return False
        buf = pack_rows(rows)
        if buf.count(_FIELD) != 6 * len(rows) or buf.count(_RECORD) != len(rows):
            self.abort()
            return False
        rc = self._lib.stream_build_feed(self._handle, buf, len(buf), len(rows))
        if rc != 0:
            self.abort()
            return False
        return True

    def finish(self) -> Optional[NativeInterned]:
        """Join the workers and merge; None when the stream died (the
        caller falls back to the Python interner)."""
        if self._dead:
            return None
        handle = self._lib.stream_build_finish(self._handle)
        self._handle = None
        self._dead = True
        if not handle:
            return None
        return NativeInterned(self._lib, handle)

    def abort(self) -> None:
        if not self._dead:
            self._lib.stream_build_abort(self._handle)
            self._handle = None
            self._dead = True


def native_intern_rows(
    rows: Iterable, wild_ns_ids=frozenset(), columns: Optional[dict] = None
) -> Optional[NativeInterned]:
    """Native counterpart of ``intern_rows``; None when the lib is absent.
    ``columns`` is an optional pre-extracted column bundle (the store's
    bulk-ingest cache) that skips row iteration entirely."""
    lib = load_library()
    if lib is None:
        return None
    if columns is not None:
        got = native_intern_columns(lib, columns, wild_ns_ids)
        if got is not None:
            return got
    if not isinstance(rows, list):
        rows = list(rows)
    if rows and hasattr(lib, "graph_build_columnar") and hasattr(rows[0], "namespace_id"):
        got = native_intern_rows_columnar(lib, rows, wild_ns_ids)
        if got is not None:
            return got
    buf = pack_rows(rows)
    # strings containing the separator control bytes would corrupt the
    # framing — detectable as a field-count mismatch; fall back to Python
    if buf.count(_FIELD) != 6 * len(rows) or buf.count(_RECORD) != len(rows):
        return None
    wild = np.asarray(sorted(wild_ns_ids), np.int64)
    handle = lib.graph_build(
        buf,
        len(buf),
        wild.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(wild),
    )
    if not handle:
        return None  # parser rejected the buffer → Python fallback
    return NativeInterned(lib, handle)
