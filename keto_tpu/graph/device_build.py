"""Device-side snapshot construction: stable sorts on the accelerator.

Building a 50M-tuple snapshot is dominated by O(E log E) host sorts: the
device-id renumbering lexsort, the ELL edge grouping, the forward CSR,
the sink reverse CSR, the transposed CSR, and both reverse-query list
layouts — six stable argsorts over edge-scale arrays executed serially
by numpy (keto_tpu/graph/snapshot.py documents each). TrieJax's framing
(PAPERS.md) applies directly: they are relational sort/group-by passes
that map cleanly onto the accelerator.

This module provides the **sorter seam** those builders now go through:

- ``HostSorter`` — ``np.argsort(kind="stable")``, the legacy path and
  the bit-exactness oracle;
- ``DeviceSorter`` — the same stable argsort executed by ``jax.lax.sort``
  (via ``jnp.argsort(stable=True)``), batched so one build round-trips
  the device a handful of times (``argsort_many`` fuses independent
  sorts into one dispatch) instead of once per numpy pass.

**Bit-identity is the contract, not a goal.** Every key array the build
sorts is integral and fits int32 (device ids and edge endpoints are
int32 throughout the layout), and a stable sort over equal integer keys
is unique — so the permutation the device returns is *defined* to equal
the host one, and tests/test_streaming_build.py fuzz-asserts byte
equality of every derived snapshot array. Anything non-sort (searchsorted
offsets, bucket scatters) stays on host over the returned permutations:
those passes are O(E) memcpy-speed and keeping them host-side keeps the
two paths one code path.

The engine registers the transient sort footprint with the HBM governor
under the ``build`` tag and falls back to ``HostSorter`` (same answers,
host speed) when the plan does not fit — a cold start must never evict
serving state just to build faster (keto_tpu/driver/hbm.py).
"""

from __future__ import annotations

import logging
import threading
from typing import Optional, Sequence

import numpy as np

_log = logging.getLogger("keto_tpu.device_build")

#: device builds below this edge count are not worth the dispatch +
#: transfer overhead; the engine compares against max(n_nodes, n_edges)
DEFAULT_MIN_EDGES = 65536

_jit_lock = threading.Lock()
_jit_cache: dict[int, object] = {}


def _sort_fn(n_arrays: int):
    """A jitted function computing ``n_arrays`` independent stable
    argsorts in one dispatch. Cached per arity; XLA caches per shape."""
    fn = _jit_cache.get(n_arrays)
    if fn is None:
        with _jit_lock:
            fn = _jit_cache.get(n_arrays)
            if fn is None:
                import jax
                import jax.numpy as jnp

                def many(*keys):
                    return tuple(jnp.argsort(k, stable=True) for k in keys)

                fn = jax.jit(many)
                _jit_cache[n_arrays] = fn
    return fn


class HostSorter:
    """The numpy stable-argsort backend (the legacy build path)."""

    backend = "host"

    def argsort(self, keys: np.ndarray) -> np.ndarray:
        return np.argsort(keys, kind="stable").astype(np.int64, copy=False)

    def argsort_many(self, arrays: Sequence[np.ndarray]) -> list:
        return [self.argsort(a) for a in arrays]


class DeviceSorter:
    """Stable argsorts executed on the accelerator.

    Keys are downcast to int32 before upload (jax's default x64-disabled
    mode would silently truncate int64 anyway): every build key — bucket
    keys, device ids, CSR endpoints — fits int32 by construction, and
    sorting the int32 copies yields the identical permutation. A key
    outside int32 range raises instead of corrupting (never observed:
    node counts are bounded far below 2^31 by the int32 CSR layout)."""

    backend = "device"

    def _prep(self, keys: np.ndarray) -> np.ndarray:
        a = np.asarray(keys)
        if a.dtype != np.int32:
            if a.size and (int(a.min()) < -(2**31) or int(a.max()) >= 2**31):
                raise OverflowError("build sort key outside int32 range")
            a = a.astype(np.int32)
        return a

    def argsort(self, keys: np.ndarray) -> np.ndarray:
        return self.argsort_many([keys])[0]

    def argsort_many(self, arrays: Sequence[np.ndarray]) -> list:
        """All permutations in one device dispatch (the "one device
        pass" over the interned edge array: independent sorts fuse)."""
        prepped = [self._prep(a) for a in arrays]
        fn = _sort_fn(len(prepped))
        outs = fn(*prepped)
        return [np.asarray(o).astype(np.int64, copy=False) for o in outs]


_HOST = HostSorter()


def host_sorter() -> HostSorter:
    return _HOST


def device_available() -> bool:
    """True when a jax backend exists to sort on. Cheap after first call."""
    try:
        import jax

        return len(jax.local_devices()) > 0
    except Exception:
        return False


def estimate_sort_bytes(n_nodes: int, n_edges: int) -> int:
    """Transient device bytes a full build's sorts peak at: keys + iota +
    sorted outputs for the largest concurrent batch (3 edge-scale sorts),
    plus the node-scale renumbering sort. int32 everywhere; XLA holds
    input and output buffers live across the fused sort."""
    per_edge_sort = 4 * 4  # key in, iota, sorted key, sorted iota
    return 3 * per_edge_sort * max(1, n_edges) + per_edge_sort * max(1, n_nodes)


class GovernedSorter:
    """The engine's build-sort policy: each argsort batch runs on the
    device when (a) a backend exists, (b) the largest array clears
    ``min_size`` (below it dispatch overhead wins), and (c) the HBM
    governor's transient plan fits WITHOUT evicting — a build must never
    push serving state off the chip just to finish faster; under
    pressure it falls back to the host path bit-identically. The
    transient footprint is ledgered under the ``build`` tag for the
    duration of the dispatch, and failures of any kind demote to host
    (counted as ``device_build_errors``; answers unchanged)."""

    backend = "governed"

    def __init__(self, hbm=None, *, min_size: int = DEFAULT_MIN_EDGES, stats=None):
        self._dev = make_device_sorter()
        self._host = host_sorter()
        self._hbm = hbm
        self._min_size = int(min_size)
        self._stats = stats  # MaintenanceStats or None

    def _incr(self, key: str) -> None:
        if self._stats is not None:
            self._stats.incr(key)

    def argsort(self, keys: np.ndarray) -> np.ndarray:
        return self.argsort_many([keys])[0]

    def argsort_many(self, arrays: Sequence[np.ndarray]) -> list:
        arrays = [np.asarray(a) for a in arrays]
        if self._dev is None or max((a.size for a in arrays), default=0) < self._min_size:
            return self._host.argsort_many(arrays)
        need = sum(16 * a.size for a in arrays)
        gov = self._hbm
        if gov is not None:
            if not gov.plan(need, what="device build transient", evict=False):
                # memory pressure: the build yields, serving state stays
                self._incr("device_build_skipped")
                return self._host.argsort_many(arrays)
            gov.register("build", need)
        try:
            out = self._dev.argsort_many(arrays)
            self._incr("device_build_dispatches")
            return out
        except Exception:
            _log.warning(
                "device build sort failed; falling back to host (bit-identical)",
                exc_info=True,
            )
            self._incr("device_build_errors")
            return self._host.argsort_many(arrays)
        finally:
            if gov is not None:
                gov.release("build")


def shard_row_ranges(n_rows: int, n_shards: int) -> list:
    """Contiguous ``[lo, hi)`` row ranges assigning ``n_rows`` rows to
    ``n_shards`` equal slabs of ``ceil(n_rows / n_shards)`` rows each (the
    last may be short). This is THE shard assignment of the sharded
    serving path: keto_tpu/parallel/sharded.py partitions the bitmap /
    bucket / label rows with it at upload time, and the snapshot cache
    (keto_tpu/graph/snapcache.py FORMAT_VERSION 6) stripes its bucket
    segments with the same ranges so a mesh cold-starts by loading each
    shard's stripe in parallel — one function, one assignment, no drift."""
    n_shards = max(1, int(n_shards))
    rps = -(-max(1, int(n_rows)) // n_shards)  # ceil div; ≥ 1
    return [
        (min(s * rps, n_rows), min((s + 1) * rps, n_rows))
        for s in range(n_shards)
    ]


def make_device_sorter() -> Optional[DeviceSorter]:
    """A ``DeviceSorter`` when a backend is present, else None. The
    caller gates on size and on the HBM governor's plan; this only
    answers "is there hardware"."""
    if not device_available():
        return None
    return DeviceSorter()
