"""Tuple-graph machinery for the TPU check engine.

The reference answers ``Check`` by a recursive traversal that issues one SQL
query per subject-set node per page (reference internal/check/engine.go:33-95).
Here the tuple set is interned into a static int32 node/edge graph snapshot
(``keto_tpu.graph.interner``, ``keto_tpu.graph.snapshot``) laid out for
gather-only breadth-first reachability on TPU
(``keto_tpu.check.tpu_engine``).
"""

from keto_tpu.graph.interner import InternedGraph, intern_rows, LEAF_KIND, SET_KIND
from keto_tpu.graph.snapshot import GraphSnapshot, WILDCARD, build_snapshot

__all__ = [
    "InternedGraph",
    "intern_rows",
    "GraphSnapshot",
    "build_snapshot",
    "WILDCARD",
    "LEAF_KIND",
    "SET_KIND",
]
