"""Device-friendly graph snapshot: bucketed reverse-ELL adjacency.

The TPU check kernel (keto_tpu/check/tpu_engine.py) runs breadth-first
reachability as a **pull**: per step, every node ORs the reached-bitmaps of
its *in*-neighbors. A pull step is gather-only — TPUs gather well but
serialize scatters with colliding indices, so the layout makes the inner
loop pure gathers + OR-reductions:

- nodes are **renumbered** ("device ids") into four classes, sorted in
  this order:

  * **active interior** — has in-edges AND out-edges, with ≥ 1 in-edge
    from another interior node. These are the only rows the BFS loop
    iterates: everything else is provably constant (or irrelevant) during
    propagation.
  * **passive interior** — in/out-edges, but in-edges only from
    zero-in-degree ("static") sources. Constant after initialization (the
    one-hop start propagation computed on host per batch,
    tpu_engine.pack_chunk), yet still gathered as a propagation source.
  * **sink** — in-edges but NO out-edges (typically subject leaves — the
    bulk of most graphs, e.g. every user in an RBAC workload). Sinks
    cannot propagate, so they get **no bitmap row at all**; a sink's
    answer is resolved per batch by gathering its interior in-neighbors
    from the fixpoint bitmap (``sink_indptr``/``sink_indices`` below).
  * **static** — no in-edges. Never materialized on device; their only
    effect is the host-side one-hop propagation.

  Excluding sinks is the big win: the iterated bitmap is
  ``[num_int+1, W]`` over interior nodes only (RBAC example: ~10k groups
  instead of ~110k groups+users), and each pull gathers only
  interior→interior edges — orders of magnitude fewer rows than the raw
  edge count.

- active-interior nodes are grouped into power-of-two **interior-in-degree**
  buckets; each bucket stores a dense ``[rows, degree]`` int32 matrix of
  interior in-neighbor device ids (ELL format), padded with sentinel
  ``num_int`` pointing at an all-zero bitmap row;
- bucket row counts are padded to powers of two so a snapshot rebuild after
  tuple writes usually keeps the same array shapes and hits the jit cache.

Because buckets are contiguous in device-id order, the pull output is the
concatenation of per-bucket OR-reductions — no scatter anywhere.

This layout replaces the reference's covering SQL index as the check hot
path's data structure (reference
internal/persistence/sql/migrations/sql/20210623162417000003_relationtuple.postgres.up.sql:1-9).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, FrozenSet, Iterable, Optional

import numpy as np

from keto_tpu.graph.interner import InternedGraph, intern_rows

#: namespace sentinel meaning "wildcard" in a resolved query pattern
WILDCARD = -1


def _ceil_pow2(x: int) -> int:
    return 1 if x <= 1 else 1 << (int(x) - 1).bit_length()


def _csr_gather_host(indptr: np.ndarray, indices: np.ndarray, nodes: np.ndarray):
    """(all out-neighbors of ``nodes`` concatenated, per-node counts)."""
    cnts = indptr[nodes + 1] - indptr[nodes]
    return _csr_gather_counts(indptr, indices, nodes, cnts)


def _csr_gather_counts(
    indptr: np.ndarray, indices: np.ndarray, nodes: np.ndarray, cnts: np.ndarray
):
    """CSR gather with caller-supplied per-node counts (callers zero the
    counts of nodes that must contribute nothing, e.g. overlay ids that are
    out of the base CSR's range)."""
    total = int(cnts.sum())
    if not total:
        return np.zeros(0, indices.dtype), cnts
    base = np.repeat(indptr[nodes], cnts)
    within = np.arange(total) - np.repeat(np.cumsum(cnts) - cnts, cnts)
    return indices[base + within], cnts


@dataclass
class Bucket:
    """One live-in-degree bucket: ``nbrs[i, j]`` is the device id of the
    j-th live in-neighbor of device node ``offset + i`` (sentinel
    ``num_int`` — the all-zero bitmap row — when padding)."""

    offset: int  # device id of the first row
    n: int  # valid rows (bucket membership)
    nbrs: np.ndarray  # int32 [n_padded, degree_capacity]


@dataclass
class ListLayout:
    """Bucketed-ELL gather layout for the reverse-query BFS
    (keto_tpu/list/tpu_engine.py), one per orientation.

    Rows cover EVERY interior-class device id ``[0, sink_base)`` —
    unlike the check kernel's buckets there is no peel/passive split,
    because a listing must read the reached flag of every interior node
    rather than a handful of packed targets. Rows are renumbered so
    buckets are contiguous (``order``/``dev2row``); bucket matrices hold
    ROW indices (sentinel ``n_rows`` = the all-zero bitmap row), so a
    pull step is the same gather + OR-reduce + concat the check kernel
    runs — no scatter.

    - ``orient == "fwd"``: row r gathers the interior IN-neighbors of
      its node — forward reachability (ListSubjects) pulls "reached"
      toward edge targets;
    - ``orient == "rev"``: row r gathers the interior OUT-neighbors —
      the TRANSPOSED orientation; backward reachability (ListObjects)
      pulls "reaches the target" toward edge sources.
    """

    orient: str
    n_rows: int  # == sink_base of the owning snapshot
    n_active: int  # rows with ≥ 1 gathered neighbor (bucket-covered prefix)
    order: np.ndarray  # int64 [n_rows]: device id of row r
    dev2row: np.ndarray  # int64 [n_rows]: device id → row
    buckets: list  # [Bucket], nbrs hold row indices, sentinel n_rows

    def device_bytes(self) -> int:
        """Device footprint of the bucket matrices as uploaded — what
        the HBM governor plans under the ``reverse`` ledger tag."""
        return sum(int(np.asarray(b.nbrs).nbytes) for b in self.buckets)


def _host_sorter():
    """Default stable-argsort backend (lazy import — snapshot.py must
    stay importable without the device_build module's jax probing)."""
    from keto_tpu.graph.device_build import host_sorter

    return host_sorter()


def _one_list_layout(
    rows_dev: np.ndarray, nbr_dev: np.ndarray, n_rows: int, orient: str,
    sorter=None,
) -> ListLayout:
    """Bucketize ``rows_dev[i] gathers nbr_dev[i]`` into a ListLayout
    over ``n_rows`` interior-class device ids (same machinery as the
    check buckets: pow2 degree buckets, pow2 row padding, contiguous
    rows per bucket). ``sorter`` is the stable-argsort backend
    (keto_tpu/graph/device_build.py); host and device produce identical
    permutations by the stable-sort contract."""
    S = sorter or _host_sorter()
    deg = np.bincount(rows_dev, minlength=n_rows) if rows_dev.size else np.zeros(n_rows, np.int64)
    with np.errstate(divide="ignore"):
        bkey = np.ceil(np.log2(np.maximum(deg, 1))).astype(np.int64) + 1
    bkey[deg <= 1] = 1
    bkey[deg == 0] = 63  # degree-0 rows sort last, outside every bucket
    # np.lexsort((arange, bkey)) == stable argsort of bkey: the arange
    # tie-break IS stability, so both backends share one primitive
    order = S.argsort(bkey)
    dev2row = np.empty(n_rows, np.int64)
    dev2row[order] = np.arange(n_rows)
    n_active = int(np.count_nonzero(deg > 0))
    buckets: list[Bucket] = []
    if rows_dev.size:
        r = dev2row[rows_dev]
        v = dev2row[nbr_dev].astype(np.int32)
        eorder = S.argsort(r)
        rs = r[eorder]
        vs = v[eorder]
        starts = np.searchsorted(rs, np.arange(n_active))
        cumcount = np.arange(rs.shape[0]) - starts[rs]
        key_by_row = bkey[order][:n_active]
        sentinel = np.int32(n_rows)
        for key in np.unique(key_by_row):
            members = np.nonzero(key_by_row == key)[0]  # contiguous
            offset, n_r = int(members[0]), int(members.shape[0])
            cap = 1 << (int(key) - 1)
            n_pad = _ceil_pow2(n_r)
            nbrs = np.full((n_pad, cap), sentinel, dtype=np.int32)
            emask = (rs >= offset) & (rs < offset + n_r)
            nbrs[rs[emask] - offset, cumcount[emask]] = vs[emask]
            buckets.append(Bucket(offset=offset, n=n_r, nbrs=nbrs))
    return ListLayout(
        orient=orient, n_rows=n_rows, n_active=n_active, order=order,
        dev2row=dev2row, buckets=buckets,
    )


def build_rev_csr(
    fwd_indptr: np.ndarray, fwd_indices: np.ndarray, n_nodes: int,
    sorter=None,
) -> tuple[np.ndarray, np.ndarray]:
    """The transposed CSR over ALL device ids: in-neighbors per node.
    Derived from the forward CSR in one stable sort, persisted by the
    snapshot cache so both orientations survive restarts."""
    S = sorter or _host_sorter()
    src = np.repeat(np.arange(n_nodes, dtype=np.int64), np.diff(fwd_indptr))
    dst = fwd_indices.astype(np.int64)
    rorder = S.argsort(dst)
    rev_indptr = np.searchsorted(dst[rorder], np.arange(n_nodes + 1))
    rev_indices = src[rorder].astype(np.int32)
    return rev_indptr, rev_indices


def build_list_layouts(
    fwd_indptr: np.ndarray, fwd_indices: np.ndarray, n_nodes: int, sink_base: int,
    sorter=None,
) -> tuple[ListLayout, ListLayout]:
    """Both reverse-query orientations over the interior-class subgraph
    (device ids < ``sink_base``), from the forward CSR. Shared by the
    snapshot builder, compaction (which re-derives them after folding),
    and the snapshot-cache load path."""
    src = np.repeat(np.arange(n_nodes, dtype=np.int64), np.diff(fwd_indptr))
    dst = fwd_indices.astype(np.int64)
    m = (src < sink_base) & (dst < sink_base)
    lay_fwd = _one_list_layout(dst[m], src[m], sink_base, "fwd", sorter=sorter)
    lay_rev = _one_list_layout(src[m], dst[m], sink_base, "rev", sorter=sorter)
    return lay_fwd, lay_rev


@dataclass
class GraphSnapshot:
    """An immutable device-layout view of the tuple set at one watermark.

    The watermark doubles as the snapshot id — the real implementation of
    what the reference stubs as "snaptoken" (reference
    internal/check/handler.go:162).
    """

    snapshot_id: int
    num_sets: int
    num_leaves: int
    #: device ids < num_active are iterated by the BFS loop
    num_active: int
    #: device ids < num_int are interior with bitmap rows (active +
    #: passive); the device bitmap has num_int+1 rows (last row all-zero)
    num_int: int
    #: device ids in [num_int, num_live) split into peeled interior
    #: [num_int, sink_base) — init-constant rows folded into host
    #: propagation, see build_snapshot's peel note — and sinks
    #: [sink_base, num_live); ids ≥ num_live are static (no in-edges)
    num_live: int
    #: count of peeled interior nodes (sink_base = num_int + n_peeled)
    n_peeled: int
    buckets: list[Bucket]
    # string→raw-id resolution: an InternedGraph (Python dicts) or a
    # NativeInterned (resident C++ tables) — same interface either way
    interned: Any
    raw2dev: np.ndarray  # int64 [n_nodes]: raw node id → device id
    wild_ns_ids: FrozenSet[int] = frozenset()
    # forward CSR over device ids, host-side (expand assist, debugging)
    fwd_indptr: Optional[np.ndarray] = None  # int64 [n_nodes+1]
    fwd_indices: Optional[np.ndarray] = None  # int32 [E]
    #: per sink (indexed by device id - num_int): interior in-neighbor
    #: device ids — the rows gathered to answer a sink-targeted query
    sink_indptr: Optional[np.ndarray] = None  # int64 [num_live-num_int+1]
    sink_indices: Optional[np.ndarray] = None  # int32
    device_buckets: Any = None  # jnp arrays, populated lazily by the engine

    # -- delta overlay (keto_tpu/graph/overlay.py) ---------------------------
    # Insert-only writes since the base build live in a small overlay
    # instead of forcing a full re-intern + relayout: new nodes get fresh
    # device ids ≥ ``n_base_nodes`` (they never need bitmap rows — class
    # transitions that would require one trigger a full rebuild), new
    # static→x edges extend the host one-hop adjacency, new edges into
    # sinks extend the answer gathers, and new interior→interior edges form
    # a tiny device-side "overlay ELL" applied as an extra scatter stage in
    # every BFS pull (tpu_engine.check_step).
    ov_set_ids: Optional[dict] = None  # (ns_id, obj, rel) → overlay dev id
    ov_leaf_ids: Optional[dict] = None  # subject str → overlay dev id
    ov_class: Optional[dict] = None  # overlay dev id → "static" | "sink"
    ov_next: int = 0  # next free overlay device id
    ov_out: Optional[dict] = None  # src dev → np.int64[...] out-neighbor devs
    ov_sink_in: Optional[dict] = None  # sink dev → np.int32[...] interior srcs
    #: unified overlay out-adjacency: src dev → [dst devs] for EVERY added
    #: edge regardless of kernel class (ov_out/ov_ell/ov_sink_in are the
    #: class-partitioned device views; this is the expand engine's source)
    ov_fwd: Optional[dict] = None
    ov_ell: Optional[np.ndarray] = None  # int64 [K, 2] (src, dst) edges
    #: tombstoned BASE edges as a sorted int64 key array ((src << 32) | dst)
    #: — deletes applied as deltas (keto_tpu/graph/overlay.py). Host
    #: gathers mask against it; iterated edges are additionally sentinel-
    #: patched out of the device buckets (``ell_patch``).
    ov_removed: Optional[np.ndarray] = None
    #: pending device-bucket patches [(bucket, row, col, value)] relative
    #: to the base's device_buckets; the engine applies + clears them
    ell_patch: Optional[list] = None
    device_overlay: Any = None  # (ov_nbrs, ov_dst) jnp arrays or None
    #: per-delta overlay-ELL change record relative to the base snapshot:
    #: ``(base_snapshot_id, added, dropped)`` where added/dropped are
    #: (src, dst) tuples — the engine's incremental device-overlay apply
    #: consumes (and clears) it like ``ell_patch``; None means "repack"
    ov_ell_delta: Any = None

    # -- reverse-query layouts (keto_tpu/list/) ------------------------------
    #: transposed CSR over ALL device ids (in-neighbors per node) —
    #: backward seeding, static-answer resolution, and the CPU-reference
    #: lister all gather through it (masked by tombstones + overlay)
    rev_indptr: Optional[np.ndarray] = None  # int64 [n_nodes+1]
    rev_indices: Optional[np.ndarray] = None  # int32 [E]
    #: bucketed-ELL list layouts over interior-class rows, both
    #: orientations (ListLayout); None on pre-reverse snapshots
    lay_fwd: Any = None
    lay_rev: Any = None
    #: overlay interior-class edges [(src, dst)] mirrored for the list
    #: kernels' extra gather stage (the transposed twin of ov_ell +
    #: interior-source ov_out entries)
    lst_ov_edges: Optional[list] = None
    #: pending device patches for the list layouts, APPEND-ONLY across
    #: stacked deltas: (orient, bucket, row, col, row-value). The list
    #: engine applies entries past its applied-counter (device arrays
    #: ride dataclasses.replace like device_buckets)
    lst_patch: Optional[list] = None
    #: True when an overlay shape could not be mirrored into the list
    #: layouts — the device list path falls back to the CPU-reference
    #: lister (bit-identical) until compaction folds the overlay
    lst_dirty: bool = False
    device_list: Any = None  # per-orientation jnp arrays, list-engine-set

    # -- sharded serving (keto_tpu/parallel/sharded.py) ----------------------
    #: row-range shard partitioning of the bucket matrices, built at
    #: upload time by the sharded engine mode; None on single-device /
    #: GSPMD engines. Deltas carry it (the base layout is unchanged);
    #: compaction and rebuilds re-derive it with the fresh buckets.
    shard_spec: Any = None
    #: stacked per-shard device arrays: (bucket nbrs tuple, bucket dst
    #: tuple), each [n_shards, ...] sharded over the mesh's graph axis
    device_shards: Any = None
    #: per-shard overlay-ELL gather arrays (nbrs, dst), routed by
    #: destination-row ownership; reset to None by apply_delta exactly
    #: like device_overlay (the engine re-routes + re-uploads)
    device_shard_overlay: Any = None
    #: row-striped label arrays (out, in, rows_per_shard) for the sharded
    #: label-intersection kernel
    device_shard_labels: Any = None

    # -- 2-hop reachability labels (keto_tpu/graph/labels.py) ----------------
    #: pruned-landmark label index over interior rows, built at snapshot
    #: build time; None when disabled or not yet built
    labels: Any = None
    #: interior device ids whose label entries the pending overlay
    #: invalidated (endpoints of inserted/tombstoned ELL edges). While
    #: non-empty the engine routes every check to the BFS kernel; every
    #: other overlay class (new sinks, sink in-edges, host-walk
    #: adjacency, host-masked tombstones) leaves the interior subgraph —
    #: the labels' whole universe — untouched, so labels stay exact.
    lab_dirty: Optional[set] = None
    device_labels: Any = None  # (out_lab, in_lab) jnp arrays, engine-set
    _pattern_cache: dict = field(default_factory=dict)
    _cache_lock: threading.Lock = field(default_factory=threading.Lock)

    @property
    def n_nodes(self) -> int:
        return self.num_sets + self.num_leaves

    @property
    def has_overlay(self) -> bool:
        """True when ANY delta-overlay state is pending (the single
        predicate consumers must use — hand-enumerating the ov_* fields
        is how a newly added one gets missed)."""
        return (
            bool(self.ov_set_ids)
            or bool(self.ov_leaf_ids)
            or bool(self.ov_out)
            or bool(self.ov_sink_in)
            or bool(self.ov_fwd)
            or self.ov_ell is not None
            or (self.ov_removed is not None and self.ov_removed.size > 0)
        )

    @property
    def labels_usable(self) -> bool:
        """True when the 2-hop label index may serve checks on this
        snapshot: an index exists and no pending overlay mutation touched
        the interior (ELL) subgraph it indexes."""
        return self.labels is not None and not self.lab_dirty

    def bucket_device_bytes(self) -> int:
        """Device footprint of the bucket matrices as uploaded — what the
        HBM governor (keto_tpu/driver/hbm.py) plans and registers under
        the ``snapshot`` ledger tag BEFORE ``jax.device_put`` runs (mesh
        row padding adds at most one graph-axis stripe per bucket and is
        ignored here)."""
        return sum(int(np.asarray(b.nbrs).nbytes) for b in self.buckets)

    @property
    def has_wildcards(self) -> bool:
        """True when any set node is wildcard-bearing — fixed per
        snapshot, cached (the raw scan is O(num_sets))."""
        with self._cache_lock:
            v = self._pattern_cache.get("_has_wild")
            if v is None:
                v = bool(np.any(np.asarray(self.interned.key_wild)))
                self._pattern_cache["_has_wild"] = v
            return v

    @property
    def sink_base(self) -> int:
        """First sink device id (peeled interior ids come before)."""
        return self.num_int + self.n_peeled


    @property
    def n_base_nodes(self) -> int:
        """Device ids below this are base nodes (classifiable by range);
        ids in [n_base_nodes, ov_next) are overlay nodes."""
        return self.n_nodes

    @property
    def n_edges(self) -> int:
        base = 0 if self.fwd_indices is None else int(self.fwd_indices.shape[0])
        ov = 0
        if self.ov_out:
            ov = sum(v.size for v in self.ov_out.values())
        if self.ov_ell is not None:
            ov += int(self.ov_ell.shape[0])
        if self.ov_sink_in:
            ov += sum(v.size for v in self.ov_sink_in.values())
        if self.ov_removed is not None:
            ov -= int(self.ov_removed.size)
        return base + ov


    def resolve_set(self, ns_id: int, obj: str, rel: str) -> Optional[int]:
        raw = self.interned.resolve_set(ns_id, obj, rel)
        if raw >= 0:
            return int(self.raw2dev[raw])
        if self.ov_set_ids is not None:
            return self.ov_set_ids.get((ns_id, obj, rel))
        return None

    def resolve_leaf(self, subject_id: str) -> Optional[int]:
        raw = self.interned.resolve_leaf(subject_id)
        if raw >= 0:
            return int(self.raw2dev[raw + self.num_sets])
        if self.ov_leaf_ids is not None:
            return self.ov_leaf_ids.get(subject_id)
        return None

    def is_answerable_target(self, dev: int) -> bool:
        """True when a query targeting device node ``dev`` can be granted:
        the node has in-edges AND either a bitmap row (interior), answer
        gathers (sink), or overlay in-edges (sink-class overlay nodes —
        whose in-edges may live purely in the host one-hop adjacency)."""
        if dev < self.num_live:
            return True
        if self.ov_class is not None and self.ov_class.get(dev) == "sink":
            return True
        if self.ov_sink_in is not None and dev in self.ov_sink_in:
            return True
        return False

    def key_of_dev(self, dev: int):
        """``("set", (ns_id, object, relation))`` or ``("leaf",
        subject_id)`` for any device id, base or overlay — the reverse of
        ``resolve_set``/``resolve_leaf``, used by the expand engine to
        reconstruct tree-node subjects from BFS-captured device ids."""
        nb = self.n_base_nodes
        if dev >= nb:
            with self._cache_lock:
                inv = self._pattern_cache.get("_ov_inv")
                if inv is None:
                    inv = {}
                    for k, d in (self.ov_set_ids or {}).items():
                        inv[d] = ("set", k)
                    for s, d in (self.ov_leaf_ids or {}).items():
                        inv[d] = ("leaf", s)
                    self._pattern_cache["_ov_inv"] = inv
            return inv[dev]
        raw = int(self._dev2raw()[dev])
        if raw < self.num_sets:
            return ("set", self.interned.set_key_of(raw))
        return ("leaf", self.interned.leaf_str(raw - self.num_sets))

    def _dev2raw(self) -> np.ndarray:
        """Lazily cached inverse of the raw2dev permutation."""
        with self._cache_lock:
            d2r = self._pattern_cache.get("_dev2raw")
            if d2r is None:
                nb = self.n_base_nodes
                d2r = np.empty(nb, np.int64)
                d2r[self.raw2dev] = np.arange(nb)
                self._pattern_cache["_dev2raw"] = d2r
            return d2r

    def is_set_dev_bulk(self, devs: np.ndarray) -> np.ndarray:
        """bool[len(devs)] — True where the device id is a set node (base
        or overlay); False for subject-id leaves."""
        devs = np.asarray(devs)
        nb = self.n_base_nodes
        d2r = self._dev2raw()
        in_base = devs < nb
        out = np.zeros(devs.shape[0], bool)
        out[in_base] = d2r[devs[in_base]] < self.num_sets
        if not in_base.all():
            ov_sets = set((self.ov_set_ids or {}).values())
            for i in np.nonzero(~in_base)[0]:
                out[i] = int(devs[i]) in ov_sets
        return out

    def _removed_drop(self, keys: np.ndarray, cnts: np.ndarray):
        """(keep-mask over gathered entries, per-segment adjusted counts)
        for the tombstone filter, or None when nothing matches. ``keys``
        are (endpoint << 32) | endpoint packed like ``ov_removed``."""
        rem = self.ov_removed
        pos = np.clip(np.searchsorted(rem, keys), 0, rem.size - 1)
        hit = rem[pos] == keys
        if not hit.any():
            return None
        seg = np.repeat(np.arange(cnts.shape[0]), cnts)
        return ~hit, cnts - np.bincount(seg[hit], minlength=cnts.shape[0])

    def out_neighbors_bulk(self, nodes: np.ndarray, overlay: bool = True):
        """(concatenated out-neighbor devs of ``nodes``, per-node counts) —
        base forward CSR merged with the delta overlay's host-propagation
        adjacency (``ov_out`` — the class the check engine's batch-setup
        walk needs) and masked by its tombstones (deleted tuples). Node
        order is preserved. Base neighbor order within a node is
        GUARANTEED to be store row order (= the Manager's page order;
        interner dedup keeps first occurrence — the expand engine's
        tree-child parity depends on this, keto_tpu/expand/tpu_engine.py);
        overlay extras append after base neighbors. ``overlay=False``
        skips the ov_out merge (still tombstone-masked) — the expand
        engine merges the COMPLETE overlay adjacency (``ov_fwd``) itself,
        in Manager order."""
        nodes = np.asarray(nodes)
        nb = self.n_base_nodes
        if nodes.size and int(nodes.max()) >= nb:
            # overlay ids are out of the base CSR's range — contribute 0
            # base neighbors (their adjacency, if any, lives in ov_out)
            in_base = nodes < nb
            base_nodes = np.where(in_base, nodes, 0)
            cnts = np.where(
                in_base,
                self.fwd_indptr[base_nodes + 1] - self.fwd_indptr[base_nodes],
                0,
            )
            rows, cnts = _csr_gather_counts(
                self.fwd_indptr, self.fwd_indices, base_nodes, cnts
            )
        else:
            rows, cnts = _csr_gather_host(self.fwd_indptr, self.fwd_indices, nodes)
        if self.ov_removed is not None and self.ov_removed.size and rows.size:
            keys = (np.repeat(nodes.astype(np.int64), cnts) << 32) | rows.astype(
                np.int64
            )
            drop = self._removed_drop(keys, cnts)
            if drop is not None:
                keep, cnts = drop
                rows = rows[keep]
        ov = self.ov_out
        if not overlay or ov is None or not ov:
            return rows, cnts
        # vectorized membership: pack_chunk's multi-hop propagation calls
        # this per hop with frontiers of thousands of rows — a Python
        # per-element `in` loop would serialize the hot path
        with self._cache_lock:
            ov_keys = self._pattern_cache.get("_ov_out_keys")
            if ov_keys is None:
                ov_keys = np.fromiter(ov.keys(), np.int64, len(ov))
                self._pattern_cache["_ov_out_keys"] = ov_keys
        member = np.isin(nodes, ov_keys)
        if not member.any():
            return rows, cnts
        ends = np.cumsum(cnts)
        mi = np.nonzero(member)[0]
        extras = [np.asarray(ov[int(nodes[i])], rows.dtype) for i in mi]
        lens = np.asarray([e.size for e in extras], np.int64)
        rows = np.insert(rows, np.repeat(ends[mi], lens), np.concatenate(extras))
        cnts = cnts.copy()
        cnts[mi] += lens
        return rows, cnts

    def sink_in_rows_bulk(self, sinks: np.ndarray):
        """(concatenated interior in-neighbor rows of sink-class targets,
        per-target counts) — base sink reverse CSR merged with overlay
        in-edges and masked by tombstones. ``sinks`` are device ids (base
        sinks or overlay nodes)."""
        sinks = np.asarray(sinks)
        sb, nl = self.sink_base, self.num_live
        no_ov = self.ov_sink_in is None or not self.ov_sink_in
        if no_ov and (self.ov_removed is None or not self.ov_removed.size):
            return _csr_gather_host(self.sink_indptr, self.sink_indices, sinks - sb)
        in_base = (sinks >= sb) & (sinks < nl)
        base_idx = np.where(in_base, sinks - sb, 0)
        cnts = np.where(
            in_base,
            self.sink_indptr[base_idx + 1] - self.sink_indptr[base_idx],
            0,
        )
        rows, cnts = _csr_gather_counts(self.sink_indptr, self.sink_indices, base_idx, cnts)
        if self.ov_removed is not None and self.ov_removed.size and rows.size:
            keys = (rows.astype(np.int64) << 32) | np.repeat(
                sinks.astype(np.int64), cnts
            )
            drop = self._removed_drop(keys, cnts)
            if drop is not None:
                keep, cnts = drop
                rows = rows[keep]
        if no_ov:
            return rows, cnts
        ov = self.ov_sink_in
        member = np.asarray([int(s) in ov for s in sinks], bool)
        if not member.any():
            return rows, cnts
        ends = np.cumsum(cnts)
        mi = np.nonzero(member)[0]
        extras = [np.asarray(ov[int(sinks[i])], rows.dtype) for i in mi]
        lens = np.asarray([e.size for e in extras], np.int64)
        rows = np.insert(rows, np.repeat(ends[mi], lens), np.concatenate(extras))
        cnts = cnts.copy()
        cnts[mi] += lens
        return rows, cnts

    def _ov_rev(self) -> dict:
        """Lazily cached REVERSE of the unified overlay adjacency:
        dst dev → [src devs] for every overlay-added edge — the seeding
        source for backward listings while a delta overlay pends.
        Rebuilt per snapshot object (apply_delta resets the cache)."""
        with self._cache_lock:
            inv = self._pattern_cache.get("_ov_rev")
            if inv is None:
                inv = {}
                for src, dsts in (self.ov_fwd or {}).items():
                    for dst in dsts:
                        inv.setdefault(int(dst), []).append(int(src))
                self._pattern_cache["_ov_rev"] = inv
            return inv

    def in_neighbors_bulk(self, nodes: np.ndarray):
        """(concatenated in-neighbor devs of ``nodes``, per-node counts)
        — the transposed twin of ``out_neighbors_bulk``: base reverse
        CSR masked by tombstones, merged with the overlay's reverse
        adjacency. Feeds backward-listing seeds and the CPU-reference
        lister (keto_tpu/list/)."""
        nodes = np.asarray(nodes)
        nb = self.n_base_nodes
        if nodes.size and int(nodes.max()) >= nb:
            in_base = nodes < nb
            base_nodes = np.where(in_base, nodes, 0)
            cnts = np.where(
                in_base,
                self.rev_indptr[base_nodes + 1] - self.rev_indptr[base_nodes],
                0,
            )
            rows, cnts = _csr_gather_counts(
                self.rev_indptr, self.rev_indices, base_nodes, cnts
            )
        else:
            rows, cnts = _csr_gather_host(self.rev_indptr, self.rev_indices, nodes)
        if self.ov_removed is not None and self.ov_removed.size and rows.size:
            # tombstone keys pack (src << 32) | dst; here the gathered
            # entry is the SOURCE and the queried node the destination
            keys = (rows.astype(np.int64) << 32) | np.repeat(
                nodes.astype(np.int64), cnts
            )
            drop = self._removed_drop(keys, cnts)
            if drop is not None:
                keep, cnts = drop
                rows = rows[keep]
        ov = self._ov_rev() if self.ov_fwd else None
        if not ov:
            return rows, cnts
        member = np.asarray([int(n) in ov for n in nodes], bool)
        if not member.any():
            return rows, cnts
        ends = np.cumsum(cnts)
        mi = np.nonzero(member)[0]
        extras = [np.asarray(ov[int(nodes[i])], rows.dtype) for i in mi]
        lens = np.asarray([e.size for e in extras], np.int64)
        rows = np.insert(rows, np.repeat(ends[mi], lens), np.concatenate(extras))
        cnts = cnts.copy()
        cnts[mi] += lens
        return rows, cnts

    def _pattern_index(self, kind: str):
        """Lazily built sorted key index for pattern resolution:
        ``(order, sorted primary col, sorted secondary col | None,
        composite (primary<<32 | secondary) col | None)``.
        Kinds: "no" = (ns, obj), "nr" = (ns, rel), "or" = (obj, rel),
        "r" = (rel,). Built once per snapshot; every pattern family then
        resolves with binary searches instead of an O(num_sets) scan —
        the fix for wildcard-heavy batches serializing on the host. The
        composite column is sorted under the same lexsort, so a BULK of
        two-field patterns resolves with one vectorized searchsorted over
        pairs (``resolve_starts_bulk``)."""
        ck = ("_pidx", kind)
        with self._cache_lock:
            hit = self._pattern_cache.get(ck)
        if hit is not None:
            return hit
        i = self.interned
        kn = np.asarray(i.key_ns)
        ko = np.asarray(i.key_obj)
        kr = np.asarray(i.key_rel)
        if kind == "no":
            order = np.lexsort((ko, kn))
            c1, c2 = kn[order], ko[order]
        elif kind == "nr":
            order = np.lexsort((kr, kn))
            c1, c2 = kn[order], kr[order]
        elif kind == "or":
            order = np.lexsort((kr, ko))
            c1, c2 = ko[order], kr[order]
        else:  # "r"
            order = np.argsort(kr, kind="stable")
            c1, c2 = kr[order], None
        comp = None if c2 is None else (c1.astype(np.int64) << 32) | c2.astype(np.int64)
        entry = (order, c1, c2, comp)
        with self._cache_lock:
            self._pattern_cache[ck] = entry
        return entry

    @staticmethod
    def _index_range(entry, v1, v2=None) -> np.ndarray:
        """Raw set ids whose primary key equals ``v1`` (and secondary
        equals ``v2`` when given), via the sorted index."""
        order, c1, c2, _comp = entry
        lo = int(np.searchsorted(c1, v1, "left"))
        hi = int(np.searchsorted(c1, v1, "right"))
        if v2 is None or c2 is None:
            return order[lo:hi]
        seg = c2[lo:hi]
        l2 = int(np.searchsorted(seg, v2, "left"))
        h2 = int(np.searchsorted(seg, v2, "right"))
        return order[lo + l2 : lo + h2]

    def resolve_starts(self, ns_id: int, obj: str, rel: str) -> np.ndarray:
        """Device ids of the set nodes a check starting at ``(ns, obj, rel)``
        expands — the graph analog of the reference's wildcarding tuple query
        (reference internal/persistence/sql/relationtuples.go:218-235).

        ``ns_id == WILDCARD`` (empty namespace name) wildcards the namespace;
        empty ``obj``/``rel`` wildcard those fields. A fully literal pattern
        resolves to at most one node. For wildcard patterns, every node key
        matching the pattern is a start: the union of their out-edges is
        exactly the subjects of the pattern's matching tuples (a matching
        key's query is always a sub-query of the pattern's).
        """
        ns_wild = ns_id == WILDCARD or ns_id in self.wild_ns_ids
        if not ns_wild and obj != "" and rel != "":
            dev = self.resolve_set(ns_id, obj, rel)
            return np.asarray([] if dev is None else [dev], np.int64)

        key = (WILDCARD if ns_wild else ns_id, obj if obj != "" else None, rel if rel != "" else None)
        with self._cache_lock:
            hit = self._pattern_cache.get(key)
        if hit is not None:
            return hit
        oc = self.interned.obj_code(obj) if obj != "" else None
        rc = self.interned.rel_code(rel) if rel != "" else None
        if (obj != "" and oc < 0) or (rel != "" and rc < 0):
            cand = np.zeros(0, np.int64)  # a literal field never interned
        elif not ns_wild:
            if oc is not None:  # (ns, obj, *)
                cand = self._index_range(self._pattern_index("no"), ns_id, oc)
            elif rc is not None:  # (ns, *, rel)
                cand = self._index_range(self._pattern_index("nr"), ns_id, rc)
            else:  # (ns, *, *)
                cand = self._index_range(self._pattern_index("no"), ns_id)
        else:
            if oc is not None and rc is not None:  # (*, obj, rel)
                cand = self._index_range(self._pattern_index("or"), oc, rc)
            elif oc is not None:  # (*, obj, *)
                cand = self._index_range(self._pattern_index("or"), oc)
            elif rc is not None:  # (*, *, rel)
                cand = self._index_range(self._pattern_index("r"), rc)
            else:  # (*, *, *)
                cand = np.arange(self.num_sets, dtype=np.int64)
        return self._starts_from_candidates(key, ns_wild, ns_id, obj, rel, cand)

    def _starts_from_candidates(
        self, key, ns_wild: bool, ns_id, obj: str, rel: str, cand: np.ndarray
    ) -> np.ndarray:
        """Candidate raw set ids → device start rows (+ overlay extras),
        cached under ``key`` — the shared tail of ``resolve_starts`` and
        ``resolve_starts_bulk``."""
        # ascending raw-id order: bitwise-identical to the old full-scan
        # nonzero() result (multi-host lockstep determinism)
        starts = self.raw2dev[np.sort(cand)] if cand.size else np.zeros(0, np.int64)
        if self.ov_set_ids:
            # overlay keys are always fully literal (a new wildcard key
            # forces a full rebuild), so pattern-match them directly
            extra = [
                dev
                for (k_ns, k_obj, k_rel), dev in self.ov_set_ids.items()
                if (ns_wild or k_ns == ns_id)
                and (obj == "" or k_obj == obj)
                and (rel == "" or k_rel == rel)
            ]
            if extra:
                starts = np.concatenate([starts, np.asarray(extra, np.int64)])
        with self._cache_lock:
            self._pattern_cache[key] = starts
        return starts

    def resolve_starts_bulk(self, pats) -> list:
        """``resolve_starts`` for a whole batch of ``(ns_id, obj, rel)``
        patterns in one pass. Duplicate patterns dedupe against the
        pattern cache; uncached patterns group by wildcard family so each
        family costs ONE vectorized searchsorted over its sorted index
        (two-field families probe the composite key column) instead of a
        per-query probe — the fix for wildcard-heavy batches serializing
        on host pattern resolution. Results land in the same cache
        ``resolve_starts`` uses, so follow-up streams stay O(1)."""
        out: list = [None] * len(pats)
        fresh: dict[tuple, list[int]] = {}
        for j, (ns_id, obj, rel) in enumerate(pats):
            ns_wild = ns_id == WILDCARD or ns_id in self.wild_ns_ids
            if not ns_wild and obj != "" and rel != "":
                out[j] = self.resolve_starts(ns_id, obj, rel)  # literal: ≤ 1 node
                continue
            key = (
                WILDCARD if ns_wild else ns_id,
                obj if obj != "" else None,
                rel if rel != "" else None,
            )
            with self._cache_lock:
                hit = self._pattern_cache.get(key)
            if hit is not None:
                out[j] = hit
            else:
                fresh.setdefault(key, []).append(j)
        if not fresh:
            return out
        # one probe spec per distinct uncached pattern, grouped by family
        groups: dict[tuple, list] = {}
        for key, js in fresh.items():
            kns, kobj, krel = key
            ns_wild = kns == WILDCARD
            obj = kobj if kobj is not None else ""
            rel = krel if krel is not None else ""
            oc = self.interned.obj_code(obj) if kobj is not None else None
            rc = self.interned.rel_code(rel) if krel is not None else None
            if (kobj is not None and oc < 0) or (krel is not None and rc < 0):
                # a literal field never interned: no candidates
                starts = self._starts_from_candidates(
                    key, ns_wild, kns, obj, rel, np.zeros(0, np.int64)
                )
                for j in js:
                    out[j] = starts
                continue
            if not ns_wild:
                if oc is not None:  # (ns, obj, *)
                    spec = ("no", kns, oc)
                elif rc is not None:  # (ns, *, rel)
                    spec = ("nr", kns, rc)
                else:  # (ns, *, *)
                    spec = ("no", kns, None)
            else:
                if oc is not None and rc is not None:  # (*, obj, rel)
                    spec = ("or", oc, rc)
                elif oc is not None:  # (*, obj, *)
                    spec = ("or", oc, None)
                elif rc is not None:  # (*, *, rel)
                    spec = ("r", rc, None)
                else:  # (*, *, *): every set node
                    starts = self._starts_from_candidates(
                        key, True, kns, obj, rel,
                        np.arange(self.num_sets, dtype=np.int64),
                    )
                    for j in js:
                        out[j] = starts
                    continue
            kind, v1, v2 = spec
            groups.setdefault((kind, v2 is not None), []).append(
                (key, js, v1, v2, ns_wild, kns, obj, rel)
            )
        for (kind, two), items in groups.items():
            order, c1, _c2, comp = self._pattern_index(kind)
            v1s = np.asarray([it[2] for it in items], np.int64)
            if two:
                probe = (v1s << 32) | np.asarray([it[3] for it in items], np.int64)
                col = comp
            else:
                probe = v1s
                col = c1
            lo = np.searchsorted(col, probe, "left")
            hi = np.searchsorted(col, probe, "right")
            for (key, js, _v1, _v2, ns_wild, kns, obj, rel), l, h in zip(items, lo, hi):
                starts = self._starts_from_candidates(
                    key, ns_wild, kns, obj, rel, order[l:h]
                )
                for j in js:
                    out[j] = starts
        return out


def build_snapshot(
    rows: Iterable,
    watermark: int,
    wild_ns_ids: FrozenSet[int] = frozenset(),
    peel_seed_cap: float = 4.0,
    columns: Optional[dict] = None,
    sorter=None,
    progress=None,
) -> GraphSnapshot:
    """Intern rows and lay out the bucketed reverse-ELL adjacency.

    ``wild_ns_ids``: ids of configured namespaces whose *name* is the empty
    string — their set nodes expand with a wildcarded namespace. Interning
    runs in the native C++ path when ``native/libketoingest.so`` is built
    (``make native``), else in Python. ``columns`` is the store's optional
    sorted column bundle (MemoryPersister.snapshot_columns) — the
    zero-extraction interning input. ``sorter``/``progress`` ride through
    to ``layout_snapshot`` (device-side build + the build-progress
    observability seam); the streaming pipeline
    (keto_tpu/graph/stream_build.py) interns incrementally and calls
    ``layout_snapshot`` directly.
    """
    rows = list(rows)
    from keto_tpu.graph.native import native_intern_rows

    if progress is not None:
        with progress.phase("intern"):
            g = native_intern_rows(rows, wild_ns_ids, columns=columns)
            if g is None:
                g = intern_rows(rows, wild_ns_ids)
            progress.add_rows(len(rows))
    else:
        g = native_intern_rows(rows, wild_ns_ids, columns=columns)
        if g is None:
            g = intern_rows(rows, wild_ns_ids)
    return layout_snapshot(
        g, watermark, wild_ns_ids, peel_seed_cap=peel_seed_cap,
        sorter=sorter, progress=progress,
    )


def layout_snapshot(
    g,
    watermark: int,
    wild_ns_ids: FrozenSet[int] = frozenset(),
    peel_seed_cap: float = 4.0,
    sorter=None,
    progress=None,
) -> GraphSnapshot:
    """Lay out an already-interned graph ``g`` (InternedGraph or
    NativeInterned) into the device snapshot: classify/peel, renumber,
    bucket, and derive the forward CSR, sink reverse CSR, transposed
    CSR, and both list layouts. Every O(E log E) stable sort goes
    through ``sorter`` (keto_tpu/graph/device_build.py) — the device
    backend runs them on the accelerator in fused dispatches, the host
    backend is the legacy numpy path; both are bit-identical by the
    stable-sort contract and fuzz-asserted so
    (tests/test_streaming_build.py)."""
    if progress is not None:
        ctx = progress.phase("device_build")
        ctx.__enter__()
    S = sorter or _host_sorter()
    src_raw, dst_raw = g.src, g.dst
    n = g.num_nodes
    try:
        snap = _layout_snapshot_inner(
            g, watermark, wild_ns_ids, peel_seed_cap, S, src_raw, dst_raw, n
        )
    finally:
        if progress is not None:
            progress.add_edges(int(np.asarray(src_raw).shape[0]))
            ctx.__exit__(None, None, None)
    return snap


def _layout_snapshot_inner(
    g, watermark, wild_ns_ids, peel_seed_cap, S, src_raw, dst_raw, n
) -> GraphSnapshot:
    if n == 0:
        return GraphSnapshot(
            snapshot_id=watermark,
            num_sets=0,
            num_leaves=0,
            num_active=0,
            num_int=0,
            num_live=0,
            n_peeled=0,
            buckets=[],
            interned=g,
            raw2dev=np.zeros(0, np.int64),
            wild_ns_ids=wild_ns_ids,
            fwd_indptr=np.zeros(1, np.int64),
            fwd_indices=np.zeros(0, np.int32),
            sink_indptr=np.zeros(1, np.int64),
            sink_indices=np.zeros(0, np.int32),
            rev_indptr=np.zeros(1, np.int64),
            rev_indices=np.zeros(0, np.int32),
            lay_fwd=_one_list_layout(np.zeros(0, np.int64), np.zeros(0, np.int64), 0, "fwd"),
            lay_rev=_one_list_layout(np.zeros(0, np.int64), np.zeros(0, np.int64), 0, "rev"),
        )

    in_deg = np.bincount(dst_raw, minlength=n)
    out_deg = np.bincount(src_raw, minlength=n)
    has_in = in_deg > 0
    has_out = out_deg > 0
    interior = has_in & has_out
    sink = has_in & ~has_out

    # --- peel ---------------------------------------------------------------
    # An interior node whose in-edges all come from static or
    # already-peeled nodes has an init-CONSTANT bitmap row: its reached
    # bits never change during the BFS loop. If it additionally has no
    # out-edge into a sink (so forward expansion can't fan into the
    # subject-leaf population), it leaves the device entirely — its effect
    # folds into the per-batch host propagation (tpu_engine.pack_chunk),
    # which generalizes the static one-hop term to the peeled DAG. This is
    # the big lever on grant-chain workloads: e.g. the GitHub-shaped
    # BASELINE config 4, where issues→repos→orgs chains peel ~80% of the
    # bitmap rows and ~90% of the gather entries out of the kernel.
    has_sink_out = np.zeros(n, bool)
    m = sink[dst_raw]
    if m.any():
        has_sink_out[np.unique(src_raw[m])] = True
    # Seed-inflation guard: peeling trades device gather work for
    # host-computed seed entries shipped per batch — on tunneled devices
    # the H2D bytes are the scarcest resource, so a node only peels when
    # the number of bitmap seeds it would expand to (its forward closure
    # through already-peeled nodes) stays small. A high-fanout hub (e.g.
    # an org granting 25 teams) keeps its bitmap row; its fanout stays a
    # device edge gathered per iteration instead of 25 seeds per query.
    # The default of 4 is tuned for a thin host↔device link (tunnel);
    # local hardware with full PCIe/DMA bandwidth can raise it
    # (engine.peel_seed_cap) to trade seed bytes for smaller kernels.
    SEED_CAP = peel_seed_cap
    peeled = np.zeros(n, bool)
    closure = np.zeros(n)  # seeds a peeled node expands to
    for _ in range(16):  # bounded: adversarial deep chains stay active
        blockers = interior & ~peeled
        deg = np.bincount(dst_raw[blockers[src_raw]], minlength=n)
        cand = interior & ~peeled & (deg == 0) & ~has_sink_out
        if not cand.any():
            break
        # candidates never point at same-round candidates (that would be
        # an unpeeled-interior in-edge), so contributions are well-defined
        contrib = np.where(peeled[dst_raw], closure[dst_raw], 1.0)
        cand_closure = np.bincount(src_raw, weights=contrib, minlength=n)
        newly = cand & (cand_closure <= SEED_CAP)
        if not newly.any():
            break
        peeled |= newly
        closure[newly] = cand_closure[newly]

    live_int = interior & ~peeled  # nodes with bitmap rows
    # iterated ("ELL") edges: unpeeled interior → unpeeled interior. Edges
    # from static/peeled sources are the batch-time host-propagation term;
    # edges into sinks are answer-time gathers — neither is materialized
    # in the loop. (A sink's in-neighbors are never peeled: an edge into a
    # sink is exactly what blocks peeling — the answer gather relies on
    # this.)
    ell_edge = live_int[src_raw] & live_int[dst_raw]
    int_in_deg = np.bincount(dst_raw[ell_edge], minlength=n)

    # bucket key: ceil-log2(interior in-degree) + 1 for active-interior;
    # passive-interior 61, peeled 62, sinks 63, static 64
    with np.errstate(divide="ignore"):
        bucket_key = np.ceil(np.log2(np.maximum(int_in_deg, 1))).astype(np.int64) + 1
    bucket_key[int_in_deg == 1] = 1
    bucket_key[live_int & (int_in_deg == 0)] = 61
    bucket_key[peeled] = 62
    bucket_key[sink] = 63
    bucket_key[~has_in] = 64

    # renumber: device order sorts by (bucket, raw id) — the raw-id
    # tie-break IS stability, so lexsort((arange, key)) == stable
    # argsort(key) and both sorter backends share one primitive
    dev_order = S.argsort(bucket_key)
    raw2dev = np.empty(n, dtype=np.int64)
    raw2dev[dev_order] = np.arange(n)

    num_active = int(np.count_nonzero(bucket_key < 61))
    num_int = int(np.count_nonzero(live_int))
    n_peeled = int(np.count_nonzero(peeled))
    num_live = int(np.count_nonzero(has_in))

    # the three edge-scale groupings below (ELL by destination, forward
    # CSR by source, sink reverse CSR by sink) are independent once
    # raw2dev exists — one fused sorter dispatch covers all of them (on
    # the device backend this is the single round trip over the interned
    # edge array; the host backend just loops)
    dst_dev = raw2dev[dst_raw[ell_edge]]
    src_dev = raw2dev[src_raw[ell_edge]]
    all_src_dev = raw2dev[src_raw]
    all_dst_dev = raw2dev[dst_raw]
    s_edge = has_in[src_raw] & sink[dst_raw]
    sink_base = num_int + n_peeled
    s_dst = raw2dev[dst_raw[s_edge]] - sink_base
    s_src = raw2dev[src_raw[s_edge]].astype(np.int32)
    order, forder, sorder = S.argsort_many([dst_dev, all_src_dev, s_dst])

    # group ELL edges by destination device id; cumcount gives the column
    # slot. Destinations of ELL edges are active-interior by construction.
    dst_sorted = dst_dev[order]
    src_sorted = src_dev[order].astype(np.int32)
    starts = np.searchsorted(dst_sorted, np.arange(num_active))
    cumcount = np.arange(dst_sorted.shape[0]) - starts[dst_sorted]

    key_by_dev = bucket_key[dev_order][:num_active]
    buckets: list[Bucket] = []
    sentinel = np.int32(num_int)  # the bitmap's all-zero row
    for key in np.unique(key_by_dev):
        members = np.nonzero(key_by_dev == key)[0]  # contiguous by construction
        offset, n_rows = int(members[0]), int(members.shape[0])
        cap = 1 << (int(key) - 1)
        n_pad = _ceil_pow2(n_rows)
        nbrs = np.full((n_pad, cap), sentinel, dtype=np.int32)
        edge_mask = (dst_sorted >= offset) & (dst_sorted < offset + n_rows)
        nbrs[dst_sorted[edge_mask] - offset, cumcount[edge_mask]] = src_sorted[edge_mask]
        buckets.append(Bucket(offset=offset, n=n_rows, nbrs=nbrs))

    # host-side forward CSR over ALL edges (device ids) — used by expand
    # and by the batch-setup one-hop propagation from static start nodes
    fsrc = all_src_dev[forder]
    findices = all_dst_dev[forder].astype(np.int32)
    findptr = np.searchsorted(fsrc, np.arange(n + 1))

    # sink reverse CSR: interior in-neighbors per sink, for answer gathers
    # (all unpeeled by construction — see the peel note above)
    n_sink = num_live - sink_base
    sink_indptr = np.searchsorted(s_dst[sorder], np.arange(n_sink + 1))
    sink_indices = s_src[sorder]

    # reverse-query layouts (keto_tpu/list/): the transposed CSR over all
    # device ids plus bucketed-ELL list layouts in BOTH orientations over
    # the interior-class rows — built here so every snapshot can answer
    # ListObjects/ListSubjects without a second interning pass
    rev_indptr, rev_indices = build_rev_csr(findptr, findices, n, sorter=S)
    lay_fwd, lay_rev = build_list_layouts(findptr, findices, n, sink_base, sorter=S)

    return GraphSnapshot(
        snapshot_id=watermark,
        num_sets=g.num_sets,
        num_leaves=g.num_leaves,
        num_active=num_active,
        num_int=num_int,
        n_peeled=n_peeled,
        num_live=num_live,
        buckets=buckets,
        interned=g,
        raw2dev=raw2dev,
        wild_ns_ids=wild_ns_ids,
        fwd_indptr=findptr,
        fwd_indices=findices,
        sink_indptr=sink_indptr,
        sink_indices=sink_indices,
        rev_indptr=rev_indptr,
        rev_indices=rev_indices,
        lay_fwd=lay_fwd,
        lay_rev=lay_rev,
    )
