"""Device-friendly graph snapshot: bucketed reverse-ELL adjacency.

The TPU check kernel (keto_tpu/check/tpu_engine.py) runs breadth-first
reachability as a **pull**: per step, every node ORs the reached-bitmaps of
its *in*-neighbors. A pull step is gather-only — TPUs gather well but
serialize scatters with colliding indices, so the layout makes the inner
loop pure gathers + OR-reductions:

- nodes are **renumbered** so nodes with similar in-degree are contiguous
  ("device ids"), grouped into power-of-two degree buckets;
- each bucket stores a dense ``[rows, degree]`` int32 matrix of in-neighbor
  device ids (ELL format), padded with a sentinel id ``n_nodes`` that points
  at a phantom all-zero bitmap row;
- bucket row counts are padded to powers of two so a snapshot rebuild after
  tuple writes usually keeps the same array shapes and hits the jit cache.

Because buckets are contiguous in device-id order, the pull output is the
concatenation of per-bucket OR-reductions — no scatter anywhere.

This layout replaces the reference's covering SQL index as the check hot
path's data structure (reference
internal/persistence/sql/migrations/sql/20210623162417000003_relationtuple.postgres.up.sql:1-9).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, FrozenSet, Iterable, Optional

import numpy as np

from keto_tpu.graph.interner import InternedGraph, intern_rows

#: namespace sentinel meaning "wildcard" in a resolved query pattern
WILDCARD = -1


def _ceil_pow2(x: int) -> int:
    return 1 if x <= 1 else 1 << (int(x) - 1).bit_length()


@dataclass
class Bucket:
    """One in-degree bucket: ``nbrs[i, j]`` is the device id of the j-th
    in-neighbor of device node ``offset + i`` (sentinel ``n_nodes`` when
    padding)."""

    offset: int  # device id of the first row
    n: int  # valid rows (bucket membership)
    nbrs: np.ndarray  # int32 [n_padded, degree_capacity]


@dataclass
class GraphSnapshot:
    """An immutable device-layout view of the tuple set at one watermark.

    The watermark doubles as the snapshot id — the real implementation of
    what the reference stubs as "snaptoken" (reference
    internal/check/handler.go:162).
    """

    snapshot_id: int
    num_sets: int
    num_leaves: int
    buckets: list[Bucket]
    # string→raw-id resolution: an InternedGraph (Python dicts) or a
    # NativeInterned (resident C++ tables) — same interface either way
    interned: Any
    raw2dev: np.ndarray  # int64 [n_nodes]: raw node id → device id
    wild_ns_ids: FrozenSet[int] = frozenset()
    # forward CSR over device ids, host-side (expand assist, debugging)
    fwd_indptr: Optional[np.ndarray] = None  # int64 [n_nodes+1]
    fwd_indices: Optional[np.ndarray] = None  # int32 [E]
    device_buckets: Any = None  # jnp arrays, populated lazily by the engine
    _pattern_cache: dict = field(default_factory=dict)
    _cache_lock: threading.Lock = field(default_factory=threading.Lock)

    @property
    def n_nodes(self) -> int:
        return self.num_sets + self.num_leaves

    @property
    def n_edges(self) -> int:
        return 0 if self.fwd_indices is None else int(self.fwd_indices.shape[0])


    def resolve_set(self, ns_id: int, obj: str, rel: str) -> Optional[int]:
        raw = self.interned.resolve_set(ns_id, obj, rel)
        return None if raw < 0 else int(self.raw2dev[raw])

    def resolve_leaf(self, subject_id: str) -> Optional[int]:
        raw = self.interned.resolve_leaf(subject_id)
        return None if raw < 0 else int(self.raw2dev[raw + self.num_sets])

    def resolve_starts(self, ns_id: int, obj: str, rel: str) -> np.ndarray:
        """Device ids of the set nodes a check starting at ``(ns, obj, rel)``
        expands — the graph analog of the reference's wildcarding tuple query
        (reference internal/persistence/sql/relationtuples.go:218-235).

        ``ns_id == WILDCARD`` (empty namespace name) wildcards the namespace;
        empty ``obj``/``rel`` wildcard those fields. A fully literal pattern
        resolves to at most one node. For wildcard patterns, every node key
        matching the pattern is a start: the union of their out-edges is
        exactly the subjects of the pattern's matching tuples (a matching
        key's query is always a sub-query of the pattern's).
        """
        ns_wild = ns_id == WILDCARD or ns_id in self.wild_ns_ids
        if not ns_wild and obj != "" and rel != "":
            dev = self.resolve_set(ns_id, obj, rel)
            return np.asarray([] if dev is None else [dev], np.int64)

        key = (WILDCARD if ns_wild else ns_id, obj if obj != "" else None, rel if rel != "" else None)
        with self._cache_lock:
            hit = self._pattern_cache.get(key)
        if hit is not None:
            return hit
        m = np.ones(self.num_sets, bool)
        if not ns_wild:
            m &= self.interned.key_ns == ns_id
        if obj != "":
            code = self.interned.obj_code(obj)
            m &= (self.interned.key_obj == code) if code >= 0 else False
        if rel != "":
            code = self.interned.rel_code(rel)
            m &= (self.interned.key_rel == code) if code >= 0 else False
        starts = self.raw2dev[: self.num_sets][np.nonzero(m)[0]]
        with self._cache_lock:
            self._pattern_cache[key] = starts
        return starts


def build_snapshot(
    rows: Iterable, watermark: int, wild_ns_ids: FrozenSet[int] = frozenset()
) -> GraphSnapshot:
    """Intern rows and lay out the bucketed reverse-ELL adjacency.

    ``wild_ns_ids``: ids of configured namespaces whose *name* is the empty
    string — their set nodes expand with a wildcarded namespace. Interning
    runs in the native C++ path when ``native/libketoingest.so`` is built
    (``make native``), else in Python.
    """
    rows = list(rows)
    from keto_tpu.graph.native import native_intern_rows

    g = native_intern_rows(rows, wild_ns_ids)
    if g is None:
        g = intern_rows(rows, wild_ns_ids)
    src_raw, dst_raw = g.src, g.dst
    n = g.num_nodes

    if n == 0:
        return GraphSnapshot(
            snapshot_id=watermark,
            num_sets=0,
            num_leaves=0,
            buckets=[],
            interned=g,
            raw2dev=np.zeros(0, np.int64),
            wild_ns_ids=wild_ns_ids,
            fwd_indptr=np.zeros(1, np.int64),
            fwd_indices=np.zeros(0, np.int32),
        )

    in_deg = np.bincount(dst_raw, minlength=n)
    # bucket key: ceil-log2(degree) + 1; nodes without in-edges sort LAST
    # (key 63) — their bitmap rows never change, so the kernel iterates only
    # the prefix of rows that can (see tpu_engine.check_step)
    with np.errstate(divide="ignore"):
        bucket_key = np.where(
            in_deg == 0, 63, np.ceil(np.log2(np.maximum(in_deg, 1))).astype(np.int64) + 1
        )
    bucket_key[in_deg == 1] = 1

    # renumber: device order sorts by (bucket, raw id); raw2dev inverts it
    dev_order = np.lexsort((np.arange(n), bucket_key))
    raw2dev = np.empty(n, dtype=np.int64)
    raw2dev[dev_order] = np.arange(n)

    # group edges by destination device id; cumcount gives the column slot
    dst_dev = raw2dev[dst_raw]
    src_dev = raw2dev[src_raw]
    order = np.argsort(dst_dev, kind="stable")
    dst_sorted = dst_dev[order]
    src_sorted = src_dev[order].astype(np.int32)
    starts = np.searchsorted(dst_sorted, np.arange(n))
    cumcount = np.arange(dst_sorted.shape[0]) - starts[dst_sorted]

    key_by_dev = bucket_key[dev_order]
    buckets: list[Bucket] = []
    sentinel = np.int32(n)
    for key in np.unique(key_by_dev):
        members = np.nonzero(key_by_dev == key)[0]  # contiguous by construction
        offset, n_rows = int(members[0]), int(members.shape[0])
        cap = 0 if key == 63 else 1 << (int(key) - 1)
        n_pad = _ceil_pow2(n_rows)
        nbrs = np.full((n_pad, cap), sentinel, dtype=np.int32)
        if cap:
            edge_mask = (dst_sorted >= offset) & (dst_sorted < offset + n_rows)
            nbrs[dst_sorted[edge_mask] - offset, cumcount[edge_mask]] = src_sorted[edge_mask]
        buckets.append(Bucket(offset=offset, n=n_rows, nbrs=nbrs))

    # host-side forward CSR (device ids), for expand assist & introspection
    forder = np.argsort(src_dev, kind="stable")
    fsrc = src_dev[forder]
    findices = dst_dev[forder].astype(np.int32)
    findptr = np.searchsorted(fsrc, np.arange(n + 1))

    return GraphSnapshot(
        snapshot_id=watermark,
        num_sets=g.num_sets,
        num_leaves=g.num_leaves,
        buckets=buckets,
        interned=g,
        raw2dev=raw2dev,
        wild_ns_ids=wild_ns_ids,
        fwd_indptr=findptr,
        fwd_indices=findices,
    )
