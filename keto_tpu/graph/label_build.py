"""Device-side 2-hop label construction: landmark BFS as batched frontier
sweeps on the mesh.

``build_labels`` (keto_tpu/graph/labels.py) runs one Python BFS per
landmark — a serial host wall in the cold-start pipeline, which is why
PR 8 capped landmarks at min(num_int, 128k) and why coverage degrades on
exactly the huge deep graphs where the BFS fallback hurts most. This
module rebuilds construction as **W-landmark-wide bit-packed frontier
waves** through the same dense gather-OR pull the check kernels use
(keto_tpu/check/tpu_engine.py ``check_step``, and the halo-exchange
structure of ``parallel/sharded.py`` in sharded mode):

- the batch's W landmark BFSs run simultaneously as one ``uint32[n+1,
  W/32]`` frontier bitmap; each wave is a dense pull over the interior
  ELL groups (forward orientation walks the in-neighbor lists exactly
  like the check kernel; the transposed orientation — the rev-CSR edge
  set PR 10 derives — gives the backward sweep), and PLL **expansion
  pruning is a per-wave ANDNOT** against the batch's ``covered`` rows: a
  per-(node, landmark) bitmask of pairs the already-built labels certify,
  computed once per batch from the resident label arrays;
- entry-set identity with ``build_labels`` is the contract, not a goal
  (tests/test_label_build.py fuzz-asserts array equality). Pre-batch
  pruning is exact by construction; **intra-batch interference** — an
  earlier-ranked batch member whose fresh labels would have pruned a
  later member's sequential BFS — is detected from the sweep output
  itself (lane i stored at lane j's landmark row means member j's
  sequential run would have seen member i in its own label) and resolved
  by **prefix acceptance**: the longest interference-free rank prefix of
  the batch commits, the rest re-runs in the next batch. Width caps, ok
  flags, and per-row entry order replay on host in rank order, exactly
  as the sequential build would have applied them;
- landmarks stream in degree-rank batches with **no hard coverage cap**:
  an early exit fires when the marginal (non-self) entries per processed
  landmark drop below ``min_gain`` — saturated graphs stop paying for
  fully-pruned landmarks, deep graphs keep going as far as the build
  budget and HBM allow. The caller (``TpuCheckEngine._ensure_labels``)
  plans the transient sweep footprint against the HBM governor
  ``evict=False``, like ``GovernedSorter``: a label build must never
  push serving state off the chip.

``device_patch_labels`` resumes per-landmark sweeps through the same
path for incremental edge insertion (the ``patch_labels`` semantics:
no expansion pruning, per-edge landmark resumption), so overlay churn
no longer forces host rebuilds.

Scale note: sweep state transfers back per batch to extract entries;
batches that store nothing (the saturated tail) skip the transfer. The
per-batch device work is O(edges · depth · W/32) words — independent of
how much pruning shrinks the *entry* count — which is why the
``min_gain`` exit, not a landmark cap, bounds the build.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from functools import lru_cache, partial
from typing import Callable, Optional

import numpy as np

from keto_tpu.graph.labels import (
    IN_PAD,
    OUT_PAD,
    LabelIndex,
    interior_adjacency,
    landmark_order,
)

_log = logging.getLogger("keto_tpu.label_build")

#: default landmark lanes per sweep batch (one uint32 word pair of
#: frontier state per node); must be a multiple of 32
DEFAULT_BATCH = 64

#: cap on the [rows, chunk] gather intermediate per ELL group — matches
#: the check kernels' per-hop peak-memory bound
_DEGREE_CHUNK = 1024

#: row chunk of the covered-mask kernel (bounds the [rows, W, Wt]
#: compare intermediate)
_COVER_CHUNK = 1 << 16

#: device builds below this interior-edge count lose to dispatch +
#: transfer overhead; callers compare against the snapshot's ELL edges
DEFAULT_MIN_EDGES = 65536


def _ceil_pow2(x: int) -> int:
    return 1 if x <= 1 else 1 << (int(x) - 1).bit_length()


# -- interior ELL groups ------------------------------------------------------


def build_ell_groups(indptr: np.ndarray, indices: np.ndarray, n: int):
    """Degree-bucketed dense gather groups for one pull orientation:
    ``[(nbrs int32[rows, cap], dst int32[rows]), ...]`` with pow2 caps
    and gather sentinel ``n`` (the always-zero bitmap row). Derived from
    the same CSRs as ``interior_adjacency`` so the sweeps and the host
    build walk the identical edge universe."""
    deg = np.diff(indptr)
    groups = []
    if n == 0:
        return groups
    nz = np.nonzero(deg > 0)[0]
    if not nz.size:
        return groups
    bucket_of = np.ceil(np.log2(np.maximum(deg[nz], 1))).astype(np.int64)
    for b in np.unique(bucket_of):
        rows = nz[bucket_of == b]
        cap = 1 << int(b)
        nbrs = np.full((rows.size, cap), np.int32(n), np.int32)
        lens = deg[rows]
        offs = np.arange(int(lens.sum())) - np.repeat(
            np.cumsum(lens) - lens, lens
        )
        nbrs[np.repeat(np.arange(rows.size), lens), offs] = indices[
            np.repeat(indptr[rows], lens) + offs
        ]
        groups.append((np.ascontiguousarray(nbrs), rows.astype(np.int32)))
    return groups


def estimate_build_bytes(n: int, max_width: int, batch: int = DEFAULT_BATCH) -> int:
    """Transient device bytes one sweep batch holds live: frontier /
    visited / stored / covered bitmaps for both orientations plus the
    full-width resident label arrays the covered kernel reads."""
    wt = max(1, batch // 32)
    bitmaps = 6 * (n + 1) * wt * 4
    labels = 2 * (n + 1) * max(1, max_width) * 4
    return bitmaps + labels


# -- jitted kernels -----------------------------------------------------------


@lru_cache(maxsize=1)
def _sweep_step():
    """One frontier wave over every ELL group: dense gather-OR pull of
    the frontier bitmap, newly-visited = pull ANDNOT visited, stores =
    newly-visited ANDNOT covered. ``prune_expansion`` is static PLL
    (certified nodes don't expand); patches pass False and keep walking."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    @partial(jax.jit, static_argnames=("prune_expansion",))
    def step(nbrs, dst, V, X, S, cov, *, prune_expansion=True):
        P = jnp.zeros_like(V)
        for nb, d in zip(nbrs, dst):
            cap = nb.shape[1]
            acc = None
            for c0 in range(0, cap, _DEGREE_CHUNK):
                g = X[nb[:, c0 : c0 + _DEGREE_CHUNK]]
                part = lax.reduce(g, np.uint32(0), lax.bitwise_or, (1,))
                acc = part if acc is None else acc | part
            P = P.at[d].set(acc)
        N = P & ~V
        store = N & ~cov
        V2 = V | N
        X2 = store if prune_expansion else N
        S2 = S | store
        active = jnp.any(X2 != 0)
        visits = jnp.sum(lax.population_count(N), dtype=jnp.int32)
        return V2, X2, S2, active, visits

    return step


@lru_cache(maxsize=1)
def _covered_fn():
    """covered[u] = W-bit mask of batch landmarks whose pre-batch label
    row intersects node u's row — the certification test of PLL pruning,
    vectorized as a searchsorted against the union of the batch's own
    label entries with a per-value lane-mask gather."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    @jax.jit
    def covered(lab, U, masks):
        idx = jnp.searchsorted(U, lab)
        idxc = jnp.minimum(idx, U.shape[0] - 1)
        found = U[idxc] == lab
        rows = jnp.where(found[..., None], masks[idxc], jnp.uint32(0))
        return lax.reduce(rows, np.uint32(0), lax.bitwise_or, (1,))

    return covered


def _compute_covered(lab_d, own_rows_host: np.ndarray, lanes: int, wt: int, pad):
    """Covered bitmap ``uint32[n+1, wt]`` for one orientation: union the
    batch's own pre-batch label entries (host mirror rows), build the
    value → lane-mask table, run the searchsorted kernel row-chunked."""
    import jax.numpy as jnp

    vals: dict[int, int] = {}
    for j in range(lanes):
        row = own_rows_host[j]
        for v in row[row != pad].tolist():
            vals[v] = vals.get(v, 0) | (1 << j)
    n1 = int(lab_d.shape[0])
    if not vals:
        return jnp.zeros((n1, wt), jnp.uint32)
    U = np.array(sorted(vals), np.int32)
    masks = np.zeros((U.size, wt), np.uint32)
    for i, v in enumerate(U.tolist()):
        m = vals[v]
        for w in range(wt):
            masks[i, w] = (m >> (32 * w)) & 0xFFFFFFFF
    fn = _covered_fn()
    U_d = jnp.asarray(U)
    m_d = jnp.asarray(masks)
    parts = [
        fn(lab_d[c0 : c0 + _COVER_CHUNK], U_d, m_d)
        for c0 in range(0, n1, _COVER_CHUNK)
    ]
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts)


# -- sweep drivers ------------------------------------------------------------


class _Sweeper:
    """Runs batched frontier sweeps on one device."""

    backend = "device"

    def __init__(self, fwd_groups, bwd_groups, n: int):
        import jax.numpy as jnp

        self.n = n
        self._fwd = tuple((jnp.asarray(a), jnp.asarray(b)) for a, b in fwd_groups)
        self._bwd = tuple((jnp.asarray(a), jnp.asarray(b)) for a, b in bwd_groups)

    def sweep(
        self,
        forward: bool,
        seeds: np.ndarray,  # int64 node per lane (or -1 for a dead lane)
        cov,  # uint32 [n+1, wt] device
        wt: int,
        *,
        prune_expansion: bool = True,
        budget: Optional[list] = None,
        start_rows: Optional[np.ndarray] = None,
    ) -> Optional[np.ndarray]:
        """Run one orientation's waves to fixpoint; returns the stored
        bitmap ``uint32[n+1, wt]`` on host, or None when ``budget``
        (mutable ``[remaining_visits]``) runs dry. ``start_rows``
        overrides the seed rows (patch resumption: lane j's walk begins
        at ``start_rows[j]`` but stores are still lane j's landmark)."""
        import jax.numpy as jnp

        n = self.n
        rows = seeds if start_rows is None else start_rows
        V0 = np.zeros((n + 1, wt), np.uint32)
        for j, u in enumerate(np.asarray(rows, np.int64).tolist()):
            if 0 <= u < n:
                V0[u, j // 32] |= np.uint32(1) << np.uint32(j % 32)
        V = jnp.asarray(V0)
        X = V
        S = jnp.zeros_like(V)
        groups = self._fwd if forward else self._bwd
        nbrs = tuple(a for a, _ in groups)
        dst = tuple(b for _, b in groups)
        step = _sweep_step()
        while True:
            if not groups:
                break
            V, X, S, active, visits = step(
                nbrs, dst, V, X, S, cov, prune_expansion=prune_expansion
            )
            if budget is not None:
                budget[0] -= int(visits)
                if budget[0] < 0:
                    return None
            if not bool(active):
                break
        return np.asarray(S)


class _ShardedSweeper:
    """The sweep as a ``shard_map`` program over the mesh's graph axis:
    frontier slabs row-range-sharded by the same ownership as the
    serving path (``device_build.shard_row_ranges`` via
    ``parallel/sharded.py:route_label_ell``), one halo exchange per
    wave. Bit-identical to ``_Sweeper`` — OR is OR on any topology."""

    backend = "sharded"

    def __init__(self, fwd_groups, bwd_groups, n: int, mesh, n_shards: int):
        import jax.numpy as jnp

        from keto_tpu.graph.device_build import shard_row_ranges
        from keto_tpu.parallel.sharded import route_label_ell

        self.n = n
        self._mesh = mesh
        g = max(1, int(n_shards))
        ranges = shard_row_ranges(n + 1, g)
        self._rps = ranges[0][1] - ranges[0][0] if ranges[0][1] > ranges[0][0] else 1
        self._g = g
        self._fwd = tuple(
            (jnp.asarray(a), jnp.asarray(b))
            for a, b in route_label_ell(fwd_groups, n, g, self._rps)
        )
        self._bwd = tuple(
            (jnp.asarray(a), jnp.asarray(b))
            for a, b in route_label_ell(bwd_groups, n, g, self._rps)
        )

    def _shard(self, flat: np.ndarray):
        """[n+1, wt] host → [g, rps, wt] device slabs."""
        import jax.numpy as jnp

        g, rps = self._g, self._rps
        wt = flat.shape[1]
        out = np.zeros((g * rps, wt), flat.dtype)
        out[: flat.shape[0]] = flat
        return jnp.asarray(out.reshape(g, rps, wt))

    def sweep(
        self,
        forward: bool,
        seeds: np.ndarray,
        cov,
        wt: int,
        *,
        prune_expansion: bool = True,
        budget: Optional[list] = None,
        start_rows: Optional[np.ndarray] = None,
    ) -> Optional[np.ndarray]:
        from keto_tpu.parallel.sharded import label_sweep_kernel

        n = self.n
        rows = seeds if start_rows is None else start_rows
        V0 = np.zeros((n + 1, wt), np.uint32)
        for j, u in enumerate(np.asarray(rows, np.int64).tolist()):
            if 0 <= u < n:
                V0[u, j // 32] |= np.uint32(1) << np.uint32(j % 32)
        V = self._shard(V0)
        X = V
        import jax.numpy as jnp

        S = jnp.zeros_like(V)
        cov_sh = self._shard(np.asarray(cov))
        groups = self._fwd if forward else self._bwd
        nbrs = tuple(a for a, _ in groups)
        dst = tuple(b for _, b in groups)
        kern = label_sweep_kernel(self._mesh)
        while groups:
            V, X, S, active, visits = kern(
                nbrs, dst, V, X, S, cov_sh,
                rps=self._rps, prune_expansion=prune_expansion,
            )
            if budget is not None:
                budget[0] -= int(visits)
                if budget[0] < 0:
                    return None
            if not bool(active):
                break
        flat = np.asarray(S).reshape(self._g * self._rps, wt)
        return flat[: n + 1]


# -- host-side finalize state -------------------------------------------------


class _Mirror:
    """Host mirror of the evolving label arrays plus their device twins:
    stores apply here in exact sequential (rank) order — width caps, ok
    flags, per-row entry order — and the deltas scatter onto the device
    arrays the next batch's covered kernel reads."""

    def __init__(self, n: int, max_width: int, out0=None, in0=None):
        import jax.numpy as jnp

        self.n = n
        self.max_width = max_width
        W = max(1, max_width)
        self.out_h = np.full((n + 1, W), OUT_PAD, np.int32)
        self.in_h = np.full((n + 1, W), IN_PAD, np.int32)
        # source arrays may be pow2-padded wider than max_width; entries
        # sit sorted at the front so the overflow columns are all pad
        if out0 is not None:
            span = min(W, out0.shape[1])
            self.out_h[: n + 1, :span] = out0[: n + 1, :span]
        if in0 is not None:
            span = min(W, in0.shape[1])
            self.in_h[: n + 1, :span] = in0[: n + 1, :span]
        self.out_w = np.count_nonzero(self.out_h[:n] != OUT_PAD, axis=1).astype(
            np.int32
        )
        self.in_w = np.count_nonzero(self.in_h[:n] != IN_PAD, axis=1).astype(np.int32)
        self.out_ok = np.ones(n, bool)
        self.in_ok = np.ones(n, bool)
        self.out_d = jnp.asarray(self.out_h)
        self.in_d = jnp.asarray(self.in_h)
        self._pending: dict[str, list] = {"out": [], "in": []}
        self.entries = int(self.out_w.sum() + self.in_w.sum())

    def store(self, side: str, nodes: np.ndarray, v: int) -> int:
        """Append landmark ``v`` at ``nodes`` on one side, width-capped;
        a full row trips its ok flag instead of lying (the sequential
        semantics). Returns the number actually stored."""
        nodes = np.asarray(nodes, np.int64)
        if not nodes.size:
            return 0
        h, w, ok, pend = (
            (self.out_h, self.out_w, self.out_ok, self._pending["out"])
            if side == "out"
            else (self.in_h, self.in_w, self.in_ok, self._pending["in"])
        )
        fits = w[nodes] < self.max_width
        good = nodes[fits]
        ok[nodes[~fits]] = False
        if good.size:
            cols = w[good].astype(np.int64)
            h[good, cols] = np.int32(v)
            w[good] += 1
            pend.append((good, cols, np.full(good.size, v, np.int32)))
            self.entries += int(good.size)
        return int(good.size)

    def flush_device(self) -> None:
        """Scatter pending host stores onto the device label arrays."""
        for side in ("out", "in"):
            pend = self._pending[side]
            if not pend:
                continue
            rows = np.concatenate([p[0] for p in pend])
            cols = np.concatenate([p[1] for p in pend])
            vals = np.concatenate([p[2] for p in pend])
            import jax.numpy as jnp

            if side == "out":
                self.out_d = self.out_d.at[jnp.asarray(rows), jnp.asarray(cols)].set(
                    jnp.asarray(vals)
                )
            else:
                self.in_d = self.in_d.at[jnp.asarray(rows), jnp.asarray(cols)].set(
                    jnp.asarray(vals)
                )
            self._pending[side] = []

    def row(self, side: str, u: int) -> np.ndarray:
        h = self.out_h if side == "out" else self.in_h
        w = self.out_w if side == "out" else self.in_w
        return h[u, : w[u]] if u < self.n else h[u, :0]

    def finalize(
        self, processed: np.ndarray, n_landmarks: int, backend: str
    ) -> LabelIndex:
        """Pack the mirrors into the padded, sorted device layout —
        byte-identical to ``labels._finalize`` over the same sets."""

        def pack(h, w, pad):
            wmax = int(w.max()) if self.n else 0
            Wp = _ceil_pow2(max(1, wmax))
            out = np.full((self.n + 1, Wp), pad, np.int32)
            if self.n:
                span = min(Wp, h.shape[1])
                tmp = h[: self.n, :span].copy()
                big = np.int32(2**31 - 1)
                tmp[tmp == pad] = big
                tmp.sort(axis=1)
                tmp[tmp == big] = pad
                out[: self.n, :span] = tmp
            return out

        return LabelIndex(
            n=self.n,
            out_lab=pack(self.out_h, self.out_w, OUT_PAD),
            in_lab=pack(self.in_h, self.in_w, IN_PAD),
            processed=processed,
            out_ok=self.out_ok,
            in_ok=self.in_ok,
            max_width=self.max_width,
            n_landmarks=n_landmarks,
            n_entries=int(self.out_w.sum() + self.in_w.sum()),
            backend=backend,
        )


def _lane_nodes(S: Optional[np.ndarray], nz: Optional[np.ndarray], j: int):
    """Node ids where lane ``j``'s bit is set in the stored bitmap."""
    if S is None or nz is None or not nz.size:
        return np.zeros(0, np.int64)
    hit = (S[nz, j // 32] >> np.uint32(j % 32)) & np.uint32(1)
    return nz[hit.astype(bool)]


def _lane_int(S_rows: np.ndarray, j: int, wt: int) -> int:
    """Lane bitmask at one landmark row as a Python int."""
    v = 0
    for w in range(wt):
        v |= int(S_rows[j, w]) << (32 * w)
    return v


@dataclass
class BuildInfo:
    """What the batched build did — the engine narrates this through
    BuildProgress / maintenance gauges and the truncation satellite."""

    batches: int = 0
    dispatches: int = 0
    landmarks: int = 0
    #: "" | "min_gain" | "cap" — why the landmark stream stopped early
    truncated: str = ""
    sweep_entries: int = 0
    restarts: int = 0  # lanes re-run due to intra-batch interference
    build_ms: float = 0.0
    gain_history: list = field(default_factory=list)


# -- the batched build --------------------------------------------------------


def device_build_labels(
    snap,
    max_width: int = 64,
    landmarks: int = 0,
    *,
    min_gain: float = 0.0,
    batch: int = DEFAULT_BATCH,
    mesh=None,
    shard_count: int = 0,
    progress_cb: Optional[Callable[[int, int, int], None]] = None,
) -> tuple[LabelIndex, BuildInfo]:
    """Construct the 2-hop index for ``snap`` with batched device
    sweeps; entry-set identical to ``build_labels(snap, max_width,
    landmarks=K)`` where K is the number of landmarks actually
    processed (``landmarks == 0`` streams ALL interior nodes, subject
    only to the ``min_gain`` early exit). See the module docstring for
    the batching/prefix-acceptance argument."""
    t0 = time.monotonic()
    n = snap.num_int
    info = BuildInfo()
    out_ip, out_ix, in_ip, in_ix = interior_adjacency(snap)
    order = landmark_order(out_ip, in_ip, n)
    K = n if landmarks <= 0 else min(int(landmarks), n)
    batch = max(32, (int(batch) // 32) * 32)
    wt = batch // 32

    # forward sweeps pull along in-neighbor rows (reach FROM the
    # landmark — the check kernel's orientation); backward sweeps pull
    # the transposed rows
    fwd_groups = build_ell_groups(in_ip, in_ix, n)
    bwd_groups = build_ell_groups(out_ip, out_ix, n)
    if mesh is not None and int(shard_count) > 1:
        sweeper = _ShardedSweeper(fwd_groups, bwd_groups, n, mesh, shard_count)
    else:
        sweeper = _Sweeper(fwd_groups, bwd_groups, n)

    mirror = _Mirror(n, max_width)
    processed = np.zeros(n, bool)
    pos = 0
    while pos < K:
        lanes = min(batch, K - pos)
        v_batch = order[pos : pos + lanes].astype(np.int64)
        seeds = np.full(batch, -1, np.int64)
        seeds[:lanes] = v_batch
        mirror.flush_device()
        # covered masks: certification against the FROZEN pre-batch
        # label arrays (the pruning ANDNOT of every wave this batch)
        cov_f = _compute_covered(
            mirror.in_d, mirror.out_h[v_batch], lanes, wt, OUT_PAD
        )
        cov_b = _compute_covered(
            mirror.out_d, mirror.in_h[v_batch], lanes, wt, IN_PAD
        )
        S_f = sweeper.sweep(True, seeds, cov_f, wt)
        S_b = sweeper.sweep(False, seeds, cov_b, wt)
        info.dispatches += 2
        info.batches += 1
        nz_f = np.nonzero(S_f[: n].any(axis=1))[0] if S_f.size else np.zeros(0, np.int64)
        nz_b = np.nonzero(S_b[: n].any(axis=1))[0] if S_b.size else np.zeros(0, np.int64)
        # intra-batch interference: lane i stored at lane j's landmark
        # row (either orientation) means sequential processing of j
        # would have seen i's fresh labels — accept the clean prefix
        rows_f = S_f[v_batch]
        rows_b = S_b[v_batch]
        jstar = lanes
        for j in range(lanes):
            inter = (_lane_int(rows_f, j, wt) | _lane_int(rows_b, j, wt)) & (
                (1 << j) - 1
            )
            if inter:
                jstar = j
                break
        if jstar == 0:
            raise AssertionError("lane 0 can never interfere with itself")
        info.restarts += lanes - jstar
        swept = 0
        for j in range(jstar):
            v = int(v_batch[j])
            # self entries first — reach0(v, v) must hit, the sequential
            # build's invariant (labels.build_labels)
            mirror.store("out", np.array([v]), v)
            mirror.store("in", np.array([v]), v)
            swept += mirror.store("in", _lane_nodes(S_f, nz_f, j), v)
            swept += mirror.store("out", _lane_nodes(S_b, nz_b, j), v)
            processed[v] = True
        info.sweep_entries += swept
        pos += jstar
        info.landmarks = pos
        gain = swept / max(1, jstar) / max(1, n)
        info.gain_history.append(round(gain, 9))
        if progress_cb is not None:
            progress_cb(pos, K, mirror.entries)
        if min_gain > 0.0 and gain < min_gain and pos < K:
            info.truncated = "min_gain"
            break

    if not info.truncated and K < n:
        info.truncated = "cap"
    idx = mirror.finalize(processed, pos, sweeper.backend)
    idx.build_ms = (time.monotonic() - t0) * 1e3
    info.build_ms = idx.build_ms
    info.landmarks = pos
    return idx, info


# -- incremental patch through the device path --------------------------------


def device_patch_labels(
    idx: LabelIndex,
    snap,
    added_edges,
    visit_budget: int = 65536,
    *,
    batch: int = DEFAULT_BATCH,
    mesh=None,
    shard_count: int = 0,
) -> Optional[LabelIndex]:
    """Incremental-PLL edge insertion through the batched sweep path:
    the exact ``labels.patch_labels`` semantics (per-edge landmark
    resumption, NO expansion pruning, store-certification against the
    evolving sets) with each edge's resume list processed as bit-packed
    lanes. Interference between lanes is static here — a resume
    landmark's own label row is frozen for the whole loop — so the lane
    list splits into clean groups up front. Returns None when the
    caller must rebuild (same contract as the host patch): truncated
    endpoint labels, budget dry, universe mismatch. The visit budget
    counts newly-visited (node, landmark) pairs exactly like the host
    walk, though the abort point may differ near the boundary."""
    t0 = time.monotonic()
    n = snap.num_int
    if idx.n != n:
        return None
    added = [(int(a), int(b)) for a, b in added_edges]
    for a, b in added:
        if not (0 <= a < n and 0 <= b < n):
            return None
        if not (idx.in_ok[a] and idx.out_ok[b]):
            return None

    out_ip, out_ix, in_ip, in_ix = interior_adjacency(snap)
    fwd_groups = build_ell_groups(in_ip, in_ix, n)
    bwd_groups = build_ell_groups(out_ip, out_ix, n)
    if mesh is not None and int(shard_count) > 1:
        sweeper = _ShardedSweeper(fwd_groups, bwd_groups, n, mesh, shard_count)
    else:
        sweeper = _Sweeper(fwd_groups, bwd_groups, n)
    mirror = _Mirror(n, idx.max_width, out0=idx.out_lab, in0=idx.in_lab)
    mirror.out_ok = idx.out_ok.copy()
    mirror.in_ok = idx.in_ok.copy()
    batch = max(32, (int(batch) // 32) * 32)
    wt = batch // 32
    budget = [int(visit_budget)]

    def lane_groups(lms: list[int], own_side: str) -> list[list[int]]:
        """Split the ordered resume list into clean prefix groups: lane
        j joins the open group only when no earlier member of the group
        appears in j's own (frozen) label row."""
        groups: list[list[int]] = []
        cur: list[int] = []
        cur_set: set = set()
        for lm in lms:
            own = set(int(x) for x in mirror.row(own_side, lm))
            if cur_set & own or len(cur) >= batch:
                groups.append(cur)
                cur, cur_set = [], set()
            cur.append(lm)
            cur_set.add(lm)
        if cur:
            groups.append(cur)
        return groups

    def run_side(forward: bool, resume_at: int, store_at: int, lms: list[int]) -> bool:
        """One direction of one edge: every landmark in ``lms`` stores
        at ``store_at`` (certified against the current sets) and resumes
        its walk at ``resume_at``. Returns False on budget exhaustion."""
        own_side, write_side = ("out", "in") if forward else ("in", "out")
        for group in lane_groups(lms, own_side):
            mirror.flush_device()
            lanes = len(group)
            own_rows = np.full((lanes, mirror.max_width), OUT_PAD if forward else IN_PAD, np.int32)
            for j, lm in enumerate(group):
                r = mirror.row(own_side, lm)
                own_rows[j, : r.size] = r
            cov = _compute_covered(
                mirror.in_d if forward else mirror.out_d,
                own_rows, lanes, wt, OUT_PAD if forward else IN_PAD,
            )
            seeds = np.full(batch, -1, np.int64)
            seeds[:lanes] = group
            starts = np.full(batch, -1, np.int64)
            starts[:lanes] = resume_at
            S = sweeper.sweep(
                forward, seeds, cov, wt,
                prune_expansion=False, budget=budget, start_rows=starts,
            )
            if S is None:
                return False
            nz = (
                np.nonzero(S[:n].any(axis=1))[0] if S.size else np.zeros(0, np.int64)
            )
            for j, lm in enumerate(group):
                # the explicit store at the edge endpoint runs before
                # the resumed walk, certified against the live sets —
                # exactly patch_labels' _store
                own = set(int(x) for x in mirror.row(own_side, lm))
                write_row = set(int(x) for x in mirror.row(write_side, store_at))
                if not (own & write_row):
                    mirror.store(write_side, np.array([store_at]), lm)
                nodes = _lane_nodes(S, nz, j)
                # the device covered mask was computed against the
                # group-entry sets; stores by earlier lanes of THIS
                # group can't certify (the clean-group invariant), so
                # the mask is exact for every lane
                mirror.store(write_side, nodes, lm)
        return True

    for a, b in added:
        fwd_lms = sorted(int(x) for x in mirror.row("in", a))
        if not run_side(True, b, b, fwd_lms):
            return None
        bwd_lms = sorted(int(x) for x in mirror.row("out", b))
        if not run_side(False, a, a, bwd_lms):
            return None

    new = mirror.finalize(idx.processed.copy(), idx.n_landmarks, "device")
    new.build_ms = (time.monotonic() - t0) * 1e3
    return new
