"""Delta application: extend a snapshot without rebuilding it.

The reference serves reads during writes through SQL MVCC — a transactional
insert or delete never stalls readers (reference
internal/persistence/sql/relationtuples.go:178-201, 271-278). The TPU analog
cannot re-intern and re-lay-out the device graph per write (seconds at 1M+
tuples), so watermark advances apply as an **overlay** on the immutable base
snapshot:

- new nodes get device ids ≥ ``base.n_base_nodes``. They never need bitmap
  rows: a brand-new set key seen as a tuple's LHS has only out-edges
  (static-class), one seen as a subject has only in-edges (sink-class), and
  new subject-ID leaves are always sinks;
- new edges partition by endpoint class:

  * static source → host one-hop adjacency (``ov_out``), consulted by the
    engine's batch-setup propagation;
  * interior source → active-interior destination → the **overlay ELL**: a
    tiny ``[K, C]`` gather matrix applied as an extra scatter-OR stage in
    every BFS pull (tpu_engine.check_step), so multi-hop paths through delta
    edges converge exactly like base edges;
  * interior source → sink destination → answer-gather overlay
    (``ov_sink_in``);

- **deleted edges become tombstones** instead of forcing a rebuild (the
  reference's MVCC serves reads through deletes the same way): a removed
  base edge enters ``ov_removed`` — a sorted key array the snapshot's host
  gathers (``out_neighbors_bulk`` / ``sink_in_rows_bulk``) mask against —
  and, when it is an iterated interior→interior edge, an ``ell_patch``
  entry that overwrites its slot in the device bucket with the all-zero
  sentinel row (the engine applies patches with one tiny device scatter —
  no re-upload). Deleting an overlay-added edge simply removes it from the
  overlay structures. Deletes never change the layout: a node left
  edgeless keeps its (now unreachable) rows and answers deny. Only graphs
  containing wildcard set nodes rebuild on delete — a removed tuple's
  wildcard-attach edges survive exactly when another matching row covers
  them, which requires a store scan;
- a delta tuple also attaches to every **existing wildcard set node** whose
  pattern matches it, mirroring the base builder's wildcard expansion
  (keto_tpu/graph/interner.py intern_rows pass 2);
- anything that would change an existing node's class on INSERT — a sink
  gaining an out-edge, a static node gaining an in-edge, an edge into a
  passive-interior row (which the BFS loop never updates), a new
  wildcard-bearing key (whose out-edges require a full tuple scan), an
  overlay node transitioning class — returns ``None``: the caller falls
  back to a full rebuild.

``apply_delta`` consumes an ordered op list (``("ins", row) | ("del",
key7)`` — the store's ``changes_since`` seam) and nets it per tuple key
first: only the last op per key matters for edge presence, so
delete-then-reinsert within one delta window is a no-op and
insert-then-delete never materializes.

``apply_delta`` is pure: it returns a NEW GraphSnapshot sharing the base's
arrays (in-flight batches keep using the old object), with the overlay
containers copied-and-extended. Pending device patches ride in
``ell_patch`` relative to the base's ``device_buckets``; the engine applies
and clears them under its snapshot lock.
"""

from __future__ import annotations

import dataclasses
from typing import FrozenSet, Iterable, Optional

import numpy as np

from keto_tpu.graph.snapshot import GraphSnapshot


def _merged(old: Optional[dict]) -> dict:
    return dict(old) if old else {}


def rows_as_ops(rows: Iterable) -> list:
    """Wrap an insert-only row list in the op format (the ``rows_since``
    compatibility shim for stores without a delete log)."""
    return [("ins", r) for r in rows]


def apply_delta(
    base: GraphSnapshot,
    ops: list,
    new_watermark: int,
    wild_ns_ids: FrozenSet[int],
) -> Optional[GraphSnapshot]:
    """Overlay ``ops`` (ordered mutations since the base watermark) onto
    ``base``. Returns the extended snapshot, or ``None`` when the delta
    needs a full rebuild."""
    if wild_ns_ids != base.wild_ns_ids:
        return None  # namespace config changed — wildcard expansion differs
    if base.n_nodes == 0:
        # an empty base has no device layout to overlay onto, and the
        # engines' empty-graph early-outs would deny every query while
        # the overlay pends — the first real build is trivially cheap
        return None

    # net effect per tuple key: the last op wins (deletes remove ALL rows
    # of a key, so edge presence after the delta is decided by whether the
    # final op re-inserted it). First-seen key order keeps processing
    # deterministic across hosts (the multi-controller lockstep contract).
    net: dict[tuple, tuple] = {}
    for kind, payload in ops:
        key = payload if kind == "del" else payload.key7()
        net[key] = (kind, payload)
    ins_rows = [p for k, (kind, p) in net.items() if kind == "ins"]
    del_keys = [k for k, (kind, _) in net.items() if kind == "del"]

    ni = base.num_int
    na = base.num_active
    sb = base.sink_base  # peeled interior ids live in [ni, sb)
    nl = base.num_live
    nb = base.n_base_nodes

    interned = base.interned
    raw2dev = base.raw2dev

    if del_keys and base.has_wildcards:
        # a removed tuple's wildcard-attach edges survive iff another
        # matching row covers them — deciding that needs a store scan
        return None

    ov_set = _merged(base.ov_set_ids)
    ov_leaf = _merged(base.ov_leaf_ids)
    ov_out = {k: v for k, v in (base.ov_out or {}).items()}
    ov_sink_in = {k: v for k, v in (base.ov_sink_in or {}).items()}
    # unified per-source overlay out-adjacency (every added edge, whatever
    # its kernel class) — the expand engine's complete child source
    ov_fwd = {k: list(v) for k, v in (base.ov_fwd or {}).items()}
    ell = [tuple(e) for e in (() if base.ov_ell is None else base.ov_ell)]
    removed: set[int] = (
        set(int(k) for k in base.ov_removed) if base.ov_removed is not None else set()
    )
    ell_patch: list[tuple[int, int, int, int]] = []
    nxt = base.ov_next or nb
    # reverse-query mirror (keto_tpu/list/): interior-class overlay edges
    # join the list kernels' extra gather stage; base-edge tombstones /
    # restores patch the list layouts the way ell_patch patches the check
    # buckets. lst_patch is APPEND-ONLY across stacked deltas (the list
    # engine applies entries past its own counter); an edge the layouts
    # can't locate flips lst_dirty and the device list path falls back to
    # the CPU-reference lister until compaction folds the overlay.
    lst_edges = [tuple(e) for e in (base.lst_ov_edges or ())]
    lst_edge_set = set(lst_edges)
    lst_patch = list(base.lst_patch or ())
    lst_dirty = bool(base.lst_dirty)

    def lst_slot(lay, row_dev: int, val_dev: int):
        row = int(lay.dev2row[row_dev])
        want = np.int32(lay.dev2row[val_dev])
        for bi, b in enumerate(lay.buckets):
            if b.offset <= row < b.offset + b.n:
                cols = np.nonzero(b.nbrs[row - b.offset] == want)[0]
                if cols.size == 0:
                    return None
                return bi, row - b.offset, int(cols[0])
        return None

    def lst_tombstone(src: int, dst: int, restore: bool) -> None:
        nonlocal lst_dirty
        if base.lay_fwd is None or base.lay_rev is None:
            lst_dirty = True
            return
        for lay, row_dev, val_dev in (
            (base.lay_fwd, dst, src),
            (base.lay_rev, src, dst),
        ):
            slot = lst_slot(lay, row_dev, val_dev)
            if slot is None:
                lst_dirty = True
                continue
            val = int(lay.dev2row[val_dev]) if restore else lay.n_rows
            lst_patch.append((lay.orient, slot[0], slot[1], slot[2], val))

    def lst_drop(src: int, dst: int) -> None:
        if (src, dst) in lst_edge_set:
            lst_edge_set.discard((src, dst))
            lst_edges.remove((src, dst))
    # label invalidation (keto_tpu/graph/labels.py): any mutation of the
    # iterated interior subgraph — an inserted overlay-ELL edge, a
    # tombstoned or restored base ELL edge — invalidates the 2-hop label
    # entries through its endpoints; the engine disables the label fast
    # path while this set is non-empty (compaction patches labels and
    # clears it). Monotone across stacked deltas on purpose: a restore
    # returns the graph to base, but proving label parity for the
    # intermediate states is not worth the bookkeeping.
    lab_dirty: set[int] = set(base.lab_dirty or ())

    # overlay node classes: "static" = out-edges only, "sink" = in-edges only
    ov_class: dict[int, str] = dict(base.ov_class or {})

    def resolve_or_new_set(ns_id: int, obj: str, rel: str):
        raw = interned.resolve_set(ns_id, obj, rel)
        if raw >= 0:
            return int(raw2dev[raw]), False
        dev = ov_set.get((ns_id, obj, rel))
        if dev is not None:
            return dev, False
        return None, True

    def resolve_or_new_leaf(s: str):
        raw = interned.resolve_leaf(s)
        if raw >= 0:
            return int(raw2dev[raw + base.num_sets]), False
        dev = ov_leaf.get(s)
        if dev is not None:
            return dev, False
        return None, True

    # wildcard base set nodes, for per-row attach matching
    wild_idx = np.nonzero(np.asarray(interned.key_wild))[0]
    if wild_idx.size:
        w_ns = np.asarray(interned.key_ns)[wild_idx]
        w_obj = np.asarray(interned.key_obj)[wild_idx]
        w_rel = np.asarray(interned.key_rel)[wild_idx]
        w_dev = raw2dev[wild_idx]
        wild_ns_arr = np.asarray(sorted(wild_ns_ids), np.int64)
        empty_obj = interned.obj_code("")
        empty_rel = interned.rel_code("")

    new_edges: list[tuple[int, int]] = []
    fwd_indptr = base.fwd_indptr
    fwd_indices = base.fwd_indices

    def in_base_csr(src: int, dst: int) -> bool:
        # re-inserting an existing tuple (legal: duplicate inserts create
        # additional store rows) must not duplicate the graph edge —
        # out-neighbor lists feed pack_chunk's disjoint-bit scatter-ADD
        if src >= nb:
            return False
        a, b = fwd_indptr[src], fwd_indptr[src + 1]
        return bool(np.any(fwd_indices[a:b] == dst))

    def ell_slot(src: int, dst: int) -> Optional[tuple[int, int, int]]:
        """(bucket index, bucket-local row, column) of base ELL edge
        src→dst — located in the base host arrays (never patched, so slots
        stay stable across remove/restore cycles)."""
        for bi, b in enumerate(base.buckets):
            if b.offset <= dst < b.offset + b.n:
                row = dst - b.offset
                cols = np.nonzero(b.nbrs[row] == src)[0]
                if cols.size == 0:
                    return None
                return bi, row, int(cols[0])
        return None

    for r in ins_rows:
        lhs_wild = (
            r.namespace_id in wild_ns_ids or r.object == "" or r.relation == ""
        )
        # subject node
        if r.subject_id is not None:
            sub_dev, is_new = resolve_or_new_leaf(r.subject_id)
            if is_new:
                sub_dev = nxt
                nxt += 1
                ov_leaf[r.subject_id] = sub_dev
                ov_class[sub_dev] = "sink"
        else:
            sub_wild = (
                r.sset_namespace_id in wild_ns_ids
                or r.sset_object == ""
                or r.sset_relation == ""
            )
            sub_key = (r.sset_namespace_id, r.sset_object, r.sset_relation)
            sub_dev, is_new = resolve_or_new_set(*sub_key)
            if is_new:
                if sub_wild:
                    # a new wildcard key's out-edges need a full tuple scan
                    return None
                sub_dev = nxt
                nxt += 1
                ov_set[sub_key] = sub_dev
                ov_class[sub_dev] = "sink"
            elif sub_dev >= nb and ov_class.get(sub_dev) == "static":
                return None  # overlay static node gains an in-edge
        # LHS node
        lhs_key = (r.namespace_id, r.object, r.relation)
        lhs_dev, lhs_new = resolve_or_new_set(*lhs_key)
        if lhs_new:
            if lhs_wild:
                return None  # new wildcard LHS: out-edges need a full scan
            lhs_dev = nxt
            nxt += 1
            ov_set[lhs_key] = lhs_dev
            ov_class[lhs_dev] = "static"
        elif lhs_dev >= nb and ov_class.get(lhs_dev) == "sink":
            return None  # overlay sink node gains an out-edge
        elif sb <= lhs_dev < nl:
            return None  # base sink gains an out-edge: needs a bitmap row
        # self-loops route through normal classification: they ARE paths
        # of length 1 (a check of a node against its own subject set
        # grants through one — the base builder keeps them, and dropping
        # them here wrongly denied that query while the overlay was
        # pending). An active→active self-loop becomes an overlay-ELL
        # edge the kernel handles like any other; other classes rebuild.
        new_edges.append((lhs_dev, sub_dev))

        # attach to every existing wildcard set node matching this tuple
        # (the base builder's pass-2 expansion, incrementally)
        if wild_idx.size:
            m = np.isin(w_ns, wild_ns_arr) | (w_ns == r.namespace_id)
            oc = interned.obj_code(r.object)
            m &= (w_obj == empty_obj) | ((w_obj == oc) if oc >= 0 else False)
            rc = interned.rel_code(r.relation)
            m &= (w_rel == empty_rel) | ((w_rel == rc) if rc >= 0 else False)
            for wdev in w_dev[m]:
                wdev = int(wdev)
                if wdev == lhs_dev:
                    continue  # the literal edge above already covers it
                if sb <= wdev < nl:
                    return None  # wildcard node is a base sink (shouldn't
                    # happen: it has out-edges) — be safe
                new_edges.append((wdev, sub_dev))

    # classify + partition the new edges
    add_out: dict[int, list[int]] = {}
    add_sink_in: dict[int, list[int]] = {}

    def fwd_add(src: int, dst: int) -> None:
        lst = ov_fwd.setdefault(src, [])
        if dst not in lst:
            lst.append(dst)

    def fwd_drop(src: int, dst: int) -> None:
        lst = ov_fwd.get(src)
        if lst is not None and dst in lst:
            lst.remove(dst)
            if not lst:
                del ov_fwd[src]

    for src, dst in new_edges:
        if in_base_csr(src, dst):
            key = (src << 32) | dst
            if key in removed:
                # re-insert of a tombstoned base edge: restore in place
                removed.discard(key)
                if src < ni and dst < na:
                    slot = ell_slot(src, dst)
                    if slot is None:
                        return None  # base layout disagrees — be safe
                    ell_patch.append(slot + (src,))
                    lab_dirty.update((src, dst))
                if src < sb and dst < sb:
                    lst_tombstone(src, dst, restore=True)
            continue
        if nl <= dst < nb:
            return None  # base static node gains an in-edge
        src_bitmap = src < ni
        # host-propagated sources: peeled interior, base static, overlay
        # static — their new out-edges extend the host propagation
        # adjacency (pack_chunk walks them), whatever the destination
        src_hostprop = (
            (ni <= src < sb)
            or (nl <= src < nb)
            or (src >= nb and ov_class.get(src) == "static")
        )
        if src_bitmap:
            if dst < ni:
                if dst >= na:
                    return None  # passive bitmap row: the BFS loop never
                    # updates it, so a new in-edge from a bitmap source
                    # needs a relayout
                ell.append((src, dst))
                lab_dirty.update((src, dst))
            elif ni <= dst < sb:
                return None  # peeled row gains a device-dependent in-edge:
                # its init-constant property breaks — relayout
            else:  # sink-class dst (base sink or overlay sink node)
                add_sink_in.setdefault(dst, []).append(src)
        elif src_hostprop:
            add_out.setdefault(src, []).append(dst)
        else:
            return None  # sink source would need class change
        fwd_add(src, dst)
        # interior-class endpoints join the list kernels' overlay stage
        # (covers both overlay-ELL edges and peeled-source host edges —
        # the list layouts iterate ALL interior-class rows, peel included)
        if src < sb and dst < sb and (src, dst) not in lst_edge_set:
            lst_edge_set.add((src, dst))
            lst_edges.append((src, dst))

    # deletes: resolve each key's endpoints (no creation) and remove the
    # edge wherever it lives — overlay structures for delta-added edges,
    # the tombstone set (plus a device sentinel patch for iterated edges)
    # for base edges. Unresolvable endpoints or absent edges are no-ops:
    # deleting a tuple that isn't there changes nothing (the store's
    # delete log only records effective deletes anyway).
    ell_members = set(ell)
    dropped_ell: set[tuple[int, int]] = set()
    for k in del_keys:
        ns_id, obj, rel, sub_id, sns, sobj, srel = k
        lhs_dev, lhs_missing = resolve_or_new_set(ns_id, obj, rel)
        if lhs_missing:
            continue
        if sub_id is not None:
            sub_dev, sub_missing = resolve_or_new_leaf(sub_id)
        else:
            sub_dev, sub_missing = resolve_or_new_set(sns, sobj, srel)
        if sub_missing:
            continue
        edge = (lhs_dev, sub_dev)
        if edge in ell_members:
            ell_members.discard(edge)
            dropped_ell.add(edge)
            fwd_drop(lhs_dev, sub_dev)
            lst_drop(lhs_dev, sub_dev)
            continue
        out_arr = ov_out.get(lhs_dev)
        if out_arr is not None and bool(np.any(out_arr == sub_dev)):
            rest = out_arr[out_arr != sub_dev]
            if rest.size:
                ov_out[lhs_dev] = rest
            else:
                del ov_out[lhs_dev]
            fwd_drop(lhs_dev, sub_dev)
            lst_drop(lhs_dev, sub_dev)
            continue
        in_arr = ov_sink_in.get(sub_dev)
        if in_arr is not None and bool(np.any(in_arr == lhs_dev)):
            rest = in_arr[in_arr != lhs_dev]
            if rest.size:
                ov_sink_in[sub_dev] = rest
            else:
                del ov_sink_in[sub_dev]
            fwd_drop(lhs_dev, sub_dev)
            continue
        key = (lhs_dev << 32) | sub_dev
        if key in removed or not in_base_csr(lhs_dev, sub_dev):
            continue  # already tombstoned / edge never existed
        removed.add(key)
        if lhs_dev < ni and sub_dev < na:
            slot = ell_slot(lhs_dev, sub_dev)
            if slot is None:
                return None  # base layout disagrees — be safe
            # num_int is the bitmap's all-zero row: the gather contributes
            # nothing, exactly like bucket padding
            ell_patch.append(slot + (ni,))
            lab_dirty.update((lhs_dev, sub_dev))
        elif lhs_dev < ni and not (sb <= sub_dev < nl):
            # interior source into anything but a sink has no host-side
            # mask to hide behind — only the two handled classes exist in
            # a consistent layout (ELL above, sink gathers below), so an
            # unclassifiable edge means the layout and the store disagree
            return None
        # peeled/static sources and interior→sink edges are masked by the
        # ov_removed filters in out_neighbors_bulk / sink_in_rows_bulk
        if lhs_dev < sb and sub_dev < sb:
            # interior-class on both ends: the list layouts iterate this
            # edge on device — sentinel-patch it out of both orientations
            lst_tombstone(lhs_dev, sub_dev, restore=False)
    if dropped_ell:
        ell = [e for e in ell if e not in dropped_ell]

    for src, dsts in add_out.items():
        old = ov_out.get(src)
        merged = np.asarray(dsts, np.int64) if old is None else np.concatenate(
            [old, np.asarray(dsts, np.int64)]
        )
        ov_out[src] = np.unique(merged)
    for dst, srcs in add_sink_in.items():
        old = ov_sink_in.get(dst)
        merged = np.asarray(srcs, np.int32) if old is None else np.concatenate(
            [old, np.asarray(srcs, np.int32)]
        )
        ov_sink_in[dst] = np.unique(merged)

    ell_arr = None
    if ell:
        ell_arr = np.unique(np.asarray(ell, np.int64), axis=0)

    # per-delta ELL change record: what this delta added to / dropped from
    # the overlay gather matrix, so the engine can scatter-patch the
    # device-resident [K, C] overlay in place instead of re-packing and
    # re-uploading the whole matrix on every group commit
    base_ell_set = set(
        (int(e[0]), int(e[1]))
        for e in (() if base.ov_ell is None else base.ov_ell)
    )
    final_ell_set = set((int(a), int(b)) for a, b in ell)
    ov_ell_delta = (
        int(base.snapshot_id),
        tuple(sorted(final_ell_set - base_ell_set)),
        tuple(sorted(base_ell_set - final_ell_set)),
    )

    removed_arr = None
    if removed:
        removed_arr = np.sort(np.fromiter(removed, np.int64, len(removed)))

    return dataclasses.replace(
        base,
        snapshot_id=new_watermark,
        ov_set_ids=ov_set,
        ov_leaf_ids=ov_leaf,
        ov_class=ov_class,
        ov_next=nxt,
        ov_out=ov_out,
        ov_sink_in=ov_sink_in,
        ov_fwd=ov_fwd or None,
        ov_ell=ell_arr,
        ov_removed=removed_arr,
        ov_ell_delta=ov_ell_delta,
        ell_patch=ell_patch or None,
        lst_ov_edges=lst_edges or None,
        lst_patch=lst_patch or None,
        lst_dirty=lst_dirty,
        lab_dirty=lab_dirty or None,
        device_overlay=None,  # engine re-uploads (cheap: overlay is small)
        device_shard_overlay=None,  # same contract for the sharded route

        _pattern_cache={},
        _cache_lock=__import__("threading").Lock(),
    )


def _ceil_pow2(x: int) -> int:
    return 1 if x <= 1 else 1 << (int(x) - 1).bit_length()


def overlay_device_bytes(snap: GraphSnapshot) -> int:
    """Device footprint the engine's overlay upload will place: the
    pow2-padded [K, C] gather matrix plus its destination vector, sized
    exactly the way ``TpuCheckEngine._upload_overlay`` lays them out —
    the number the HBM governor (keto_tpu/driver/hbm.py) plans against
    BEFORE the ``jax.device_put``."""
    if snap.ov_ell is None or snap.ov_ell.shape[0] == 0:
        return 0
    dst = snap.ov_ell[:, 1]
    uniq, counts = np.unique(dst, return_counts=True)
    K = _ceil_pow2(uniq.shape[0])
    C = _ceil_pow2(int(counts.max()))
    return K * C * 4 + K * 4
