"""Pruned-landmark 2-hop reachability labels: O(1)-step checks at any depth.

The BFS check kernel (keto_tpu/check/tpu_engine.py) pays one TPU step per
frontier hop, so deep grant chains (team forests, org hierarchies) tax
every check with their depth: BENCH_r04's depth-8 config runs ~60k
checks/s against ~215k on the shallow graph. This module precomputes a
**2-hop label index** over the interior subgraph at snapshot-build time
("Simple, Fast, and Scalable Reachability Oracle", PAPERS.md) so a
reachability probe becomes ONE sorted-label intersection — a single
gather + compare on device, independent of graph depth.

Scope — the label universe is exactly the BFS kernel's bitmap universe:

- labels cover **interior rows** (device ids < ``num_int``) and the
  **iterated (ELL) edges** between them — the only part of the graph the
  BFS loop walks. Static/peeled starts are host-propagated to interior
  seeds by ``pack_chunk`` (unchanged), and sink targets are answered
  through their interior in-neighbor gathers (unchanged), so the label
  path reuses the engine's existing host resolution end to end and only
  replaces the iterated device loop;
- ``reach0(a, b)`` below means "b reachable from a via ≥ 0 ELL edges"
  (``a == b`` counts). The engine's router maps the check semantics
  ("reached via ≥ 1 real edge") onto reach0 probes exactly — see
  ``TpuCheckEngine._label_route``.

Construction is **pruned landmark labeling** (PLL): process interior
nodes in degree rank order; for node v, a forward pruned BFS appends v to
``IN(u)`` of every node u it reaches (skipping u when an earlier-ranked
hub already certifies v→u), and a backward pruned BFS appends v to
``OUT(u)``. Processing ALL nodes yields an exact oracle; the
``landmarks`` knob caps processing for build-time budgets, and
``max_width`` caps per-row label width for the device layout. Both caps
degrade COVERAGE, never correctness:

- every stored entry witnesses a real path, so a label **hit is always a
  sound grant**;
- a **miss certifies a deny** only for pairs ``(a, b)`` where
  ``out_ok[a] and in_ok[b] and (processed[a] or processed[b])`` — the
  PLL completeness argument needs one endpoint's own BFS to have run,
  and neither endpoint's label truncated. Uncertifiable pairs fall back
  to the BFS kernel, bit-identically.

The index is immutable and shared across snapshots like every other
snapshot array; the mutation path keeps it honest:

- ``overlay.apply_delta`` records inserted/tombstoned ELL edges in
  ``lab_dirty`` — while any are pending, the engine disables the label
  path (every other overlay class — new sinks, sink in-edges, host-walk
  adjacency, host-masked tombstones — leaves the interior subgraph
  untouched, so labels stay EXACT through those);
- ``compaction`` patches labels incrementally for folded ELL inserts
  (``patch_labels`` — resumed pruned BFS per edge, the standard
  incremental-PLL insertion) and falls back to a full label rebuild past
  a visit budget or for folded deletions (2-hop deletion is a rebuild in
  the literature too);
- ``snapcache`` persists the arrays (crc-covered segments) so cold
  starts skip construction.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

#: padding values for the device rows: the two sides pad differently so a
#: padded slot can never witness an intersection
OUT_PAD = np.int32(-1)
IN_PAD = np.int32(-2)


def _ceil_pow2(x: int) -> int:
    return 1 if x <= 1 else 1 << (int(x) - 1).bit_length()


def interior_adjacency(snap):
    """The iterated (ELL) edge set of ``snap`` as forward + reverse CSRs
    over interior device ids — (out_indptr, out_indices, in_indptr,
    in_indices), all int64/int32. Derived from the bucket matrices (the
    kernel's own edge source), so labels and BFS walk the SAME graph by
    construction."""
    ni = snap.num_int
    srcs: list[np.ndarray] = []
    dsts: list[np.ndarray] = []
    sentinel = np.int32(ni)
    for b in snap.buckets:
        nbrs = np.asarray(b.nbrs[: b.n])
        rows, cols = np.nonzero(nbrs != sentinel)
        if rows.size:
            srcs.append(nbrs[rows, cols].astype(np.int64))
            dsts.append((rows + b.offset).astype(np.int64))
    if srcs:
        src = np.concatenate(srcs)
        dst = np.concatenate(dsts)
    else:
        src = np.zeros(0, np.int64)
        dst = np.zeros(0, np.int64)
    o = np.argsort(src, kind="stable")
    out_indptr = np.searchsorted(src[o], np.arange(ni + 1))
    out_indices = dst[o].astype(np.int32)
    i = np.argsort(dst, kind="stable")
    in_indptr = np.searchsorted(dst[i], np.arange(ni + 1))
    in_indices = src[i].astype(np.int32)
    return out_indptr, out_indices, in_indptr, in_indices


@dataclass
class LabelIndex:
    """Immutable 2-hop label arrays over ``n`` interior rows.

    ``out_lab``/``in_lab`` are padded-ELL int32 ``[n + 1, W]`` matrices
    (row ``n`` is all-padding — the engine's pair padding gathers it);
    valid entries per row are sorted ascending. ``processed[u]`` means
    u's own pruned BFS ran (u was a landmark); ``out_ok``/``in_ok`` mean
    the row never hit the width cap. See the module docstring for the
    exactness rules these flags carry."""

    n: int
    out_lab: np.ndarray  # int32 [n+1, Wo], OUT_PAD-padded
    in_lab: np.ndarray  # int32 [n+1, Wi], IN_PAD-padded
    processed: np.ndarray  # bool [n]
    out_ok: np.ndarray  # bool [n]
    in_ok: np.ndarray  # bool [n]
    max_width: int
    n_landmarks: int
    build_ms: float = 0.0
    #: total stored entries (both sides) — operators size budgets off this
    n_entries: int = 0
    #: which construction path produced the index: "host" (this module's
    #: per-landmark Python BFS) or "device" (the batched frontier sweeps
    #: of keto_tpu/graph/label_build.py — entry-identical by contract)
    backend: str = "host"
    device: object = field(default=None, compare=False)  # jnp arrays, engine-set

    @property
    def coverage(self) -> float:
        """Fraction of interior rows fully certifiable on BOTH sides —
        the ``keto_label_coverage_ratio`` gauge."""
        if self.n == 0:
            return 1.0
        return float(
            np.count_nonzero(self.processed & self.out_ok & self.in_ok) / self.n
        )

    def device_bytes(self) -> int:
        """Device footprint of the uploaded label arrays — what the HBM
        governor (keto_tpu/driver/hbm.py) plans and registers under the
        ``labels`` ledger tag before the engine uploads them."""
        return int(self.out_lab.nbytes + self.in_lab.nbytes)

    def certifiable(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        """bool[len(a)] — True where a MISS on pair (a[i], b[i]) is a
        sound deny (see module docstring). Rows == n (the padding row)
        certify trivially: they witness no path and assert none."""
        a = np.asarray(a)
        b = np.asarray(b)
        pad_a = a >= self.n
        pad_b = b >= self.n
        ac = np.where(pad_a, 0, a)
        bc = np.where(pad_b, 0, b)
        out = (
            self.out_ok[ac]
            & self.in_ok[bc]
            & (self.processed[ac] | self.processed[bc])
        )
        return out | pad_a | pad_b

    def query(self, a: int, b: int) -> bool:
        """Host-side reach0 probe (tests, compaction pruning): does
        OUT(a) intersect IN(b)?"""
        if a >= self.n or b >= self.n:
            return False
        oa = self.out_lab[a]
        ib = self.in_lab[b]
        oa = oa[oa != OUT_PAD]
        ib = ib[ib != IN_PAD]
        if not oa.size or not ib.size:
            return False
        return bool(np.isin(oa, ib, assume_unique=True).any())

    def witness_landmark(self, a: int, b: int) -> Optional[int]:
        """The winning entry of the reach0 intersection for ``(a, b)``:
        the minimum common landmark id, or None on miss. Every stored
        entry witnesses a real path, so a returned landmark sits on a
        genuine a→…→landmark→…→b chain — the 2-hop witness the explain
        subsystem surfaces. The device path
        (tpu_engine.label_step_witness) is argmin over the same compare."""
        if a >= self.n or b >= self.n:
            return None
        oa = self.out_lab[a]
        ib = self.in_lab[b]
        oa = oa[oa != OUT_PAD]
        ib = ib[ib != IN_PAD]
        if not oa.size or not ib.size:
            return None
        common = oa[np.isin(oa, ib, assume_unique=True)]
        return int(common.min()) if common.size else None


def _finalize(
    n: int,
    out_sets: list,
    in_sets: list,
    processed: np.ndarray,
    out_ok: np.ndarray,
    in_ok: np.ndarray,
    max_width: int,
    n_landmarks: int,
) -> LabelIndex:
    """Pack per-node label sets into the padded, sorted device layout."""
    wo = max((len(s) for s in out_sets), default=0)
    wi = max((len(s) for s in in_sets), default=0)
    Wo = _ceil_pow2(max(1, wo))
    Wi = _ceil_pow2(max(1, wi))
    out_lab = np.full((n + 1, Wo), OUT_PAD, np.int32)
    in_lab = np.full((n + 1, Wi), IN_PAD, np.int32)
    entries = 0
    for u in range(n):
        s = sorted(out_sets[u])
        if s:
            out_lab[u, : len(s)] = s
            entries += len(s)
        s = sorted(in_sets[u])
        if s:
            in_lab[u, : len(s)] = s
            entries += len(s)
    return LabelIndex(
        n=n,
        out_lab=out_lab,
        in_lab=in_lab,
        processed=processed,
        out_ok=out_ok,
        in_ok=in_ok,
        max_width=max_width,
        n_landmarks=n_landmarks,
        n_entries=entries,
    )


def landmark_order(
    out_indptr: np.ndarray, in_indptr: np.ndarray, n: int
) -> np.ndarray:
    """THE landmark processing order: degree descending, device id
    ascending on ties — deterministic across hosts (the multi-controller
    lockstep contract). Shared by ``build_labels`` and the device
    builder (keto_tpu/graph/label_build.py) so their entry-identity
    contract starts from the identical rank list."""
    out_deg = np.diff(out_indptr)
    in_deg = np.diff(in_indptr)
    return np.lexsort((np.arange(n), -(out_deg + in_deg)))


def _csr_row(indptr, indices, u: int) -> np.ndarray:
    return indices[indptr[u] : indptr[u + 1]]


def _pruned_bfs(
    v: int,
    frontier_adj,  # (indptr, indices) to EXPAND along
    own_label: set,  # OUT(v) for forward, IN(v) for backward
    write_labels: list,  # IN sets for forward, OUT sets for backward
    ok_flags: np.ndarray,
    max_width: int,
    start: Optional[int] = None,
    prune_expansion: bool = True,
    budget: Optional[list] = None,
) -> None:
    """One pruned BFS for landmark ``v``: visit u; when an earlier-ranked
    hub already certifies the pair (``own_label ∩ write_labels[u]`` —
    both sides hold only earlier ranks plus v itself), skip storing v at
    u, else record it (a width-cap overflow trips ``ok_flags[u]``
    instead of lying).

    ``prune_expansion=True`` is static PLL: a certified node's subtree
    is skipped entirely (sound because every label in the index
    witnesses the SAME immutable graph). Incremental patches pass False:
    a hub certificate minted before an edge insertion does not extend to
    the node's new descendants, so the patch must keep expanding and
    apply the hub test per node for storage only. ``start`` resumes the
    walk mid-graph (patching edge a→b resumes at b); ``budget`` (mutable
    ``[remaining_visits]``) makes patches abortable — the caller
    rebuilds when it runs dry."""
    indptr, indices = frontier_adj
    s = v if start is None else start
    visited = {s}
    frontier = [s]
    while frontier:
        nxt: list = []
        for u in frontier:
            for w in _csr_row(indptr, indices, u):
                w = int(w)
                if w in visited:
                    continue
                visited.add(w)
                if budget is not None:
                    budget[0] -= 1
                    if budget[0] < 0:
                        raise _BudgetExceeded
                certified = bool(own_label & write_labels[w])
                if not certified:
                    lab = write_labels[w]
                    if len(lab) < max_width:
                        lab.add(v)
                    else:
                        ok_flags[w] = False
                if certified and prune_expansion:
                    continue
                nxt.append(w)
        frontier = nxt


class _BudgetExceeded(Exception):
    pass


def build_labels(snap, max_width: int = 64, landmarks: int = 0) -> LabelIndex:
    """Construct the index for ``snap`` (see module docstring).
    ``landmarks == 0`` processes every interior node (exact oracle);
    a positive cap processes only the top-ranked ones (coverage shrinks,
    soundness holds). Deterministic: rank ties break on device id, BFS
    label content is visit-order independent — the multi-controller
    lockstep contract holds for label-path decisions too."""
    import time

    t0 = time.monotonic()
    n = snap.num_int
    out_indptr, out_indices, in_indptr, in_indices = interior_adjacency(snap)
    # rank: degree descending, id ascending (deterministic across hosts)
    order = landmark_order(out_indptr, in_indptr, n)
    K = n if landmarks <= 0 else min(int(landmarks), n)

    out_sets: list = [set() for _ in range(n)]
    in_sets: list = [set() for _ in range(n)]
    processed = np.zeros(n, bool)
    out_ok = np.ones(n, bool)
    in_ok = np.ones(n, bool)

    for v in order[:K].tolist():
        # self entries first: reach0(v, v) must hit, and the prune tests
        # below rely on v ∈ own label
        if len(out_sets[v]) < max_width:
            out_sets[v].add(v)
        else:
            out_ok[v] = False
        if len(in_sets[v]) < max_width:
            in_sets[v].add(v)
        else:
            in_ok[v] = False
        _pruned_bfs(
            v, (out_indptr, out_indices), out_sets[v], in_sets, in_ok,
            max_width,
        )
        _pruned_bfs(
            v, (in_indptr, in_indices), in_sets[v], out_sets, out_ok,
            max_width,
        )
        processed[v] = True

    idx = _finalize(
        n, out_sets, in_sets, processed, out_ok, in_ok, max_width, K
    )
    idx.build_ms = (time.monotonic() - t0) * 1e3
    return idx


def patch_labels(
    idx: LabelIndex,
    snap,
    added_edges,
    visit_budget: int = 65536,
) -> Optional[LabelIndex]:
    """Incremental-PLL edge insertion: for each folded ELL edge (a, b),
    every landmark recorded as reaching ``a`` resumes its forward pruned
    BFS from ``b`` (and symmetrically from ``b``'s OUT entries backward
    through ``a``) over the COMPACTED adjacency. Returns the patched
    index, or None when the caller must rebuild: endpoint labels are
    truncated (the resume set is incomplete), the visit budget runs dry,
    or the index universe doesn't match the snapshot."""
    import time

    t0 = time.monotonic()
    n = snap.num_int
    if idx.n != n:
        return None
    added = [(int(a), int(b)) for a, b in added_edges]
    for a, b in added:
        if not (0 <= a < n and 0 <= b < n):
            return None
        if not (idx.in_ok[a] and idx.out_ok[b]):
            # the resume sets IN(a)/OUT(b) are incomplete — a resumed
            # patch would silently miss landmarks
            return None

    out_indptr, out_indices, in_indptr, in_indices = interior_adjacency(snap)
    out_sets = [
        set(int(x) for x in row[row != OUT_PAD]) for row in idx.out_lab[:n]
    ]
    in_sets = [
        set(int(x) for x in row[row != IN_PAD]) for row in idx.in_lab[:n]
    ]
    out_ok = idx.out_ok.copy()
    in_ok = idx.in_ok.copy()
    budget = [int(visit_budget)]

    def _store(lm: int, u: int, own: set, write: list, ok: np.ndarray) -> None:
        if not (own & write[u]):
            lab = write[u]
            if len(lab) < idx.max_width:
                lab.add(lm)
            else:
                ok[u] = False

    try:
        # edges apply one at a time in ascending-rank landmark order: the
        # per-edge invariant restoration ("every landmark's pair set is
        # exact again") is what makes the next edge's resume sound
        for a, b in added:
            # landmarks recorded as reaching a now also reach b's tail:
            # resume each one's forward walk AT b over the new adjacency
            for lm in sorted(in_sets[a]):
                _store(lm, b, out_sets[lm], in_sets, in_ok)
                _pruned_bfs(
                    lm, (out_indptr, out_indices), out_sets[lm], in_sets,
                    in_ok, idx.max_width, start=b, prune_expansion=False,
                    budget=budget,
                )
            # symmetric: landmarks reachable from b now label a's sources
            for lm in sorted(out_sets[b]):
                _store(lm, a, in_sets[lm], out_sets, out_ok)
                _pruned_bfs(
                    lm, (in_indptr, in_indices), in_sets[lm], out_sets,
                    out_ok, idx.max_width, start=a, prune_expansion=False,
                    budget=budget,
                )
    except _BudgetExceeded:
        return None

    new = _finalize(
        n, out_sets, in_sets, idx.processed.copy(), out_ok, in_ok,
        idx.max_width, idx.n_landmarks,
    )
    new.build_ms = (time.monotonic() - t0) * 1e3
    return new
