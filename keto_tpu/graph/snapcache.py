"""Persistent snapshot cache: versioned on-disk layout, mmap reload.

Cold start previously meant re-ingesting every tuple and rebuilding the
device layout — minutes at 50M tuples — before the first check could be
answered. This module serializes a built ``GraphSnapshot`` (CSR arrays,
bucket matrices, interner tables, pattern-key columns) into a directory of
raw ``.npy``/blob files keyed by ``(format_version, watermark)`` and
reloads it with ``numpy`` memory-mapping: the arrays page in lazily, so a
50M-tuple snapshot serves its first query in seconds. The engine then
catches up from the cached watermark through the ordinary delta path
(keto_tpu/graph/overlay.py) — the watermark doubles as the snaptoken, so
the cache key IS the consistency token (docs/concepts/snaptokens.md).

Layout (one directory per cached snapshot, written to a temp dir and
renamed into place — a crashed save can never leave a half-readable
cache):

    <cache_dir>/v<FORMAT>-w<watermark>/
        meta.json            scalars, bucket geometry, wild_ns_ids
        raw2dev.npy fwd_indptr.npy fwd_indices.npy
        sink_indptr.npy sink_indices.npy bucket_<i>.npy ...
        key_ns.npy key_obj.npy key_rel.npy key_wild.npy
        set_order.npy set_nsobj.npy set_rel.npy     (sorted set-key index)
        {obj,rel,leaf}_blob.bin {obj,rel,leaf}_off.npy
        {obj,rel,leaf}_hash.npy {obj,rel,leaf}_hord.npy

The interner reloads as a ``CachedInterned``: string→code resolution runs
as a crc32 probe into the sorted hash column (verified against the blob —
collisions are handled, not assumed away), set-key resolution as two
binary searches over the lexsorted ``(ns<<32|obj_code, rel_code)``
columns. No dict is ever materialized, which is what keeps reload
O(mmap) instead of O(rows). The native bulk-resolution entry point is
absent on a cached interner; the check engine detects that and resolves
through its host path.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import threading
import zlib
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path
from typing import Optional

import numpy as np

from keto_tpu.graph.snapshot import Bucket, GraphSnapshot

#: bump when the on-disk layout or the snapshot's array semantics change —
#: the version is part of the directory key, so old caches are simply
#: never matched (and pruned as newer saves land — retention is
#: format-version-aware, see ``_prune``). v2: per-segment checksums in
#: meta.json + fsync-before-rename durability. v3: 2-hop reachability
#: label arrays (keto_tpu/graph/labels.py) ride along, so a cold start
#: skips label construction too. v4: reverse-query orientations
#: (transposed CSR). v5: SEGMENTED layout — segments are grouped by the
#: pipeline stage that produces them (``meta.json`` ``groups``), written
#: in stage order at save time and verified+mapped in parallel at load,
#: so a mesh cold-starts shards concurrently and a single process's
#: reload is bounded by the slowest group, not the sum. v6: PER-SHARD
#: bucket segments — a sharded engine (keto_tpu/parallel/sharded.py)
#: saves each bucket matrix striped by the serve-time shard row ranges
#: (``graph/device_build.shard_row_ranges`` — the same assignment the
#: upload partitions by), one ``bucket_<i>_s<j>.npy`` per shard in its
#: own ``shard<j>`` segment group, so a mesh cold start verifies and
#: loads every shard's stripe in parallel and reassembles the exact
#: single-device byte layout. Single-shard saves keep whole-file
#: buckets (and their lazy mmap reload).
FORMAT_VERSION = 6

#: caches kept per format version within a directory (newest watermarks
#: win). Retention never reaches across versions: a v(N-1) cache written
#: by the previous binary survives a vN upgrade until ITS version
#: accumulates KEEP newer caches — so a rollback (or a not-yet-upgraded
#: replica sharing the directory) always finds a loadable cache.
KEEP = 2

#: quarantined (corrupt) caches kept for forensics; older ones drop
QUARANTINE_KEEP = 2

#: segment-group of each segment file, by the pipeline stage that
#: produces it: "core" lands with the device build (CSRs, buckets,
#: renumbering), "interner" with the string tables, "reverse" with the
#: transposed orientation, "labels" with the 2-hop index. The loader
#: verifies and maps groups concurrently.
_SHARD_SEG_RE = re.compile(r"^bucket_\d+_s(\d+)\.npy$")


def _group_of(name: str) -> str:
    m = _SHARD_SEG_RE.match(name)
    if m is not None:
        return f"shard{int(m.group(1))}"
    if name.startswith(("rev_",)):
        return "reverse"
    if name.startswith("lab_"):
        return "labels"
    if name.startswith(("key_", "set_", "obj_", "rel_", "leaf_")):
        return "interner"
    return "core"


class CacheCorrupt(ValueError):
    """A cached snapshot failed its integrity verification (size or
    checksum mismatch, torn meta.json). The loader quarantines the
    directory and rebuilds — a corrupt cache must never serve wrong
    decisions, and must never crash the server either."""


def _fsync_file(path: Path) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: Path) -> None:
    """Flush directory metadata (the rename itself) to disk; best-effort
    on filesystems that refuse O_RDONLY fsync on directories."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _file_crc(path: Path, chunk: int = 1 << 22) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            buf = f.read(chunk)
            if not buf:
                return crc
            crc = zlib.crc32(buf, crc)


def _string_table(strings: list) -> Optional[tuple]:
    """(utf-8 blob, offsets int64[n+1], sorted crc32 hashes uint32[n],
    argsort order int64[n]) for a code-indexed string column."""
    encoded = [s.encode() for s in strings]
    n = len(encoded)
    off = np.zeros(n + 1, np.int64)
    if n:
        off[1:] = np.cumsum([len(b) for b in encoded])
    blob = b"".join(encoded)
    hashes = np.fromiter((zlib.crc32(b) for b in encoded), np.uint32, n)
    order = np.argsort(hashes, kind="stable")
    return blob, off, hashes[order], order.astype(np.int64)


def _obj_strings(interned, n: int) -> list:
    """Code-indexed object-string column for any interner flavor."""
    from keto_tpu.graph.interner import ExtendedInterned, InternedGraph

    if isinstance(interned, InternedGraph):
        out = [""] * n
        for s, c in interned.obj_codes.items():
            out[c] = s
        return out
    if isinstance(interned, ExtendedInterned):
        base = _obj_strings(interned._base, interned._obj_floor)
        return base + [
            interned._ext_obj_strs[c]
            for c in range(interned._obj_floor, n)
        ]
    if isinstance(interned, CachedInterned):
        return [interned._obj_str(c) for c in range(n)]
    return [interned._str_at("graph_obj_str", c) for c in range(n)]


def _rel_strings(interned, n: int) -> list:
    from keto_tpu.graph.interner import ExtendedInterned, InternedGraph

    if isinstance(interned, InternedGraph):
        out = [""] * n
        for s, c in interned.rel_codes.items():
            out[c] = s
        return out
    if isinstance(interned, ExtendedInterned):
        base = _rel_strings(interned._base, interned._rel_floor)
        return base + [
            interned._ext_rel_strs[c]
            for c in range(interned._rel_floor, n)
        ]
    if isinstance(interned, CachedInterned):
        return [interned._rel_str(c) for c in range(n)]
    return [interned._str_at("graph_rel_str", c) for c in range(n)]


class CachedInterned:
    """InternedGraph-compatible resolution over the mmapped cache arrays.

    Implements the same interface the snapshot and engines consume
    (resolve_set/resolve_leaf/obj_code/rel_code, key arrays, reverse
    lookups) without materializing any dict — the whole point of the
    cache is an O(mmap) cold start. Lacks the native bulk
    ``resolve_queries`` entry point on purpose; the engine's host
    resolution path covers it.
    """

    def __init__(self, d: Path, meta: dict):
        self.num_sets = int(meta["num_sets"])
        self.num_leaves = int(meta["num_leaves"])
        self._n_obj = int(meta["n_obj"])
        self._n_rel = int(meta["n_rel"])
        mm = lambda name: np.load(d / name, mmap_mode="r")  # noqa: E731
        self.key_ns = mm("key_ns.npy")
        self.key_obj = mm("key_obj.npy")
        self.key_rel = mm("key_rel.npy")
        self.key_wild = np.asarray(mm("key_wild.npy")).astype(bool)
        self._set_order = mm("set_order.npy")
        self._set_nsobj = mm("set_nsobj.npy")
        self._set_rel = mm("set_rel.npy")
        self._tables = {}
        for kind in ("obj", "rel", "leaf"):
            blob = np.memmap(d / f"{kind}_blob.bin", dtype=np.uint8, mode="r") \
                if (d / f"{kind}_blob.bin").stat().st_size else np.zeros(0, np.uint8)
            self._tables[kind] = (
                blob,
                mm(f"{kind}_off.npy"),
                mm(f"{kind}_hash.npy"),
                mm(f"{kind}_hord.npy"),
            )

    @property
    def num_nodes(self) -> int:
        return self.num_sets + self.num_leaves

    def num_obj_codes(self) -> int:
        return self._n_obj

    def num_rel_codes(self) -> int:
        return self._n_rel

    # -- string tables -------------------------------------------------------

    def _str_of(self, kind: str, idx: int) -> str:
        blob, off, _, _ = self._tables[kind]
        return bytes(blob[int(off[idx]) : int(off[idx + 1])]).decode()

    def _code_of(self, kind: str, s: str) -> int:
        blob, off, hashes, order = self._tables[kind]
        b = s.encode()
        h = np.uint32(zlib.crc32(b))
        lo = int(np.searchsorted(hashes, h, "left"))
        hi = int(np.searchsorted(hashes, h, "right"))
        for k in range(lo, hi):
            i = int(order[k])
            if bytes(blob[int(off[i]) : int(off[i + 1])]) == b:
                return i
        return -1

    def _obj_str(self, code: int) -> str:
        return self._str_of("obj", code)

    def _rel_str(self, code: int) -> str:
        return self._str_of("rel", code)

    # -- resolution ----------------------------------------------------------

    def obj_code(self, s: str) -> int:
        return self._code_of("obj", s)

    def rel_code(self, s: str) -> int:
        return self._code_of("rel", s)

    def resolve_set(self, ns_id: int, obj: str, rel: str) -> int:
        oc = self.obj_code(obj)
        if oc < 0:
            return -1
        rc = self.rel_code(rel)
        if rc < 0:
            return -1
        key = (int(ns_id) << 32) | oc
        lo = int(np.searchsorted(self._set_nsobj, key, "left"))
        hi = int(np.searchsorted(self._set_nsobj, key, "right"))
        seg = self._set_rel[lo:hi]
        j = int(np.searchsorted(seg, rc, "left"))
        if j < seg.shape[0] and int(seg[j]) == rc:
            return int(self._set_order[lo + j])
        return -1

    def resolve_leaf(self, subject_id: str) -> int:
        return self._code_of("leaf", subject_id)

    # -- reverse lookups -----------------------------------------------------

    def set_key_of(self, raw_id: int):
        return (
            int(self.key_ns[raw_id]),
            self._str_of("obj", int(self.key_obj[raw_id])),
            self._str_of("rel", int(self.key_rel[raw_id])),
        )

    def leaf_str(self, idx: int) -> str:
        return self._str_of("leaf", idx)


def save_snapshot(
    snap: GraphSnapshot, cache_dir: str, shards: int = 1, labels_wait=None
) -> Optional[str]:
    """Serialize ``snap`` under ``cache_dir``; returns the cache path, or
    None when the snapshot isn't cacheable (pending overlay, an interner
    without code-table sizes, or key codes outside the packed-index
    range). Atomic: written to a temp dir and renamed into place.

    ``shards > 1`` (the sharded engine passes its graph-axis count)
    stripes each bucket matrix into per-shard row segments along the
    serve-time shard assignment, so a mesh cold start loads shards in
    parallel; reassembly is byte-identical to the single-file layout.

    ``labels_wait`` is called right before the label segments are read:
    the engine overlaps its label build with this save and passes a join
    so an in-flight index still lands in the cache instead of being
    silently dropped (a warm reload would otherwise rebuild it)."""
    if snap.has_overlay:
        return None
    shards = max(1, int(shards))
    interned = snap.interned
    n_obj = getattr(interned, "num_obj_codes", lambda: None)()
    n_rel = getattr(interned, "num_rel_codes", lambda: None)()
    if n_obj is None or n_rel is None:
        return None
    key_ns = np.asarray(interned.key_ns, np.int64)
    key_obj = np.asarray(interned.key_obj, np.int64)
    key_rel = np.asarray(interned.key_rel, np.int64)
    if key_ns.size and (
        int(key_ns.min()) < 0
        or int(key_ns.max()) >= 1 << 31
        or int(key_obj.max()) >= 1 << 32
    ):
        return None  # outside the (ns<<32|obj) packed-index range

    base = Path(cache_dir)
    tag = f"v{FORMAT_VERSION}-w{snap.snapshot_id}"
    final = base / tag
    if final.exists():
        return str(final)
    base.mkdir(parents=True, exist_ok=True)
    tmp = base / f".tmp-{tag}-{os.getpid()}-{threading.get_ident()}"
    if tmp.exists():
        shutil.rmtree(tmp)
    tmp.mkdir(parents=True)
    try:
        sv = lambda name, arr: np.save(tmp / name, np.ascontiguousarray(arr))  # noqa: E731
        sv("raw2dev", snap.raw2dev)
        sv("fwd_indptr", snap.fwd_indptr)
        sv("fwd_indices", snap.fwd_indices)
        sv("sink_indptr", snap.sink_indptr)
        sv("sink_indices", snap.sink_indices)
        # both reverse-query orientations persist (FORMAT_VERSION 4): the
        # transposed CSR reloads mmap'd; the bucketed list layouts are
        # re-derived from the forward CSR at load (cheap, deterministic)
        if snap.rev_indptr is not None:
            sv("rev_indptr", snap.rev_indptr)
            sv("rev_indices", snap.rev_indices)
        if shards > 1:
            # per-shard bucket stripes: rows split by the SERVE-TIME
            # shard ownership (graph/device_build.shard_row_ranges over
            # the global bitmap rows — bucket rows are contiguous bitmap
            # rows starting at the bucket offset); the last stripe also
            # carries the bucket's pow2 padding rows so concatenating
            # stripes in shard order reproduces the exact matrix
            from keto_tpu.graph.device_build import shard_row_ranges

            ranges = shard_row_ranges(snap.num_int + 1, shards)
            rps = max(1, ranges[0][1] - ranges[0][0])
            for i, b in enumerate(snap.buckets):
                nbrs = np.asarray(b.nbrs)
                n_pad = nbrs.shape[0]
                cuts = [0]
                for s in range(shards - 1):
                    cuts.append(
                        int(np.clip((s + 1) * rps - b.offset, 0, b.n))
                    )
                cuts.append(n_pad)
                for s in range(shards):
                    sv(f"bucket_{i}_s{s}", nbrs[cuts[s] : cuts[s + 1]])
        else:
            for i, b in enumerate(snap.buckets):
                sv(f"bucket_{i}", b.nbrs)
        sv("key_ns", key_ns)
        sv("key_obj", key_obj)
        sv("key_rel", key_rel)
        sv("key_wild", np.asarray(interned.key_wild).astype(np.uint8))
        # lexsorted set-key index: (ns<<32|obj_code) with rel_code minor
        order = np.lexsort((key_rel, key_obj, key_ns))
        sv("set_order", order.astype(np.int64))
        sv("set_nsobj", (key_ns[order] << 32) | key_obj[order])
        sv("set_rel", key_rel[order])
        # 2-hop label arrays (overlay-free snapshots only reach a save, so
        # a present index is exactly the base graph's): the segment
        # manifest below covers them like every other array, and a
        # corrupted label segment quarantines the whole cache
        if labels_wait is not None:
            labels_wait()  # join an overlapped label build before reading
        lab_meta = None
        idx = snap.labels
        if idx is not None:
            sv("lab_out", idx.out_lab)
            sv("lab_in", idx.in_lab)
            sv("lab_processed", idx.processed.astype(np.uint8))
            sv("lab_out_ok", idx.out_ok.astype(np.uint8))
            sv("lab_in_ok", idx.in_ok.astype(np.uint8))
            lab_meta = {
                "n": int(idx.n),
                "max_width": int(idx.max_width),
                "n_landmarks": int(idx.n_landmarks),
                "n_entries": int(idx.n_entries),
                "backend": str(idx.backend),
            }
        for kind, strings in (
            ("obj", _obj_strings(interned, n_obj)),
            ("rel", _rel_strings(interned, n_rel)),
            ("leaf", [interned.leaf_str(i) for i in range(interned.num_leaves)]),
        ):
            blob, off, hashes, order = _string_table(strings)
            (tmp / f"{kind}_blob.bin").write_bytes(blob)
            sv(f"{kind}_off", off)
            sv(f"{kind}_hash", hashes)
            sv(f"{kind}_hord", order)
        # per-segment integrity manifest: size + crc32 of every data file,
        # read back from disk so the checksum covers what actually landed.
        # The loader verifies before serving — a torn write (crash or
        # power loss mid-save that somehow survived the atomic-rename
        # protocol, bit rot, a truncating copy) is DETECTED and the cache
        # quarantined instead of silently yielding wrong decisions.
        segments = {}
        groups: dict[str, list] = {}
        for f in sorted(tmp.iterdir()):
            _fsync_file(f)  # durable before the rename publishes them
            segments[f.name] = {
                "size": f.stat().st_size,
                "crc32": _file_crc(f),
            }
            groups.setdefault(_group_of(f.name), []).append(f.name)
        meta = {
            "format": FORMAT_VERSION,
            "watermark": int(snap.snapshot_id),
            "wild_ns_ids": sorted(int(i) for i in snap.wild_ns_ids),
            "num_sets": int(interned.num_sets),
            "num_leaves": int(interned.num_leaves),
            "num_active": int(snap.num_active),
            "num_int": int(snap.num_int),
            "num_live": int(snap.num_live),
            "n_peeled": int(snap.n_peeled),
            "buckets": [{"offset": int(b.offset), "n": int(b.n)} for b in snap.buckets],
            "shards": shards,
            "n_obj": int(n_obj),
            "n_rel": int(n_rel),
            "labels": lab_meta,
            "segments": segments,
            "groups": groups,
        }
        (tmp / "meta.json").write_text(json.dumps(meta))
        _fsync_file(tmp / "meta.json")
        _fsync_dir(tmp)
        try:
            os.replace(tmp, final)
        except OSError:
            if not final.exists():
                raise
            # a concurrent saver landed the same watermark first — theirs
            # is identical; drop ours
            shutil.rmtree(tmp, ignore_errors=True)
        else:
            # the rename is only durable once the parent directory is —
            # an acknowledged cache must survive the machine dying now
            _fsync_dir(base)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    _prune(base, keep=KEEP)
    return str(final)


def export_manifest(cache_dir: str, max_watermark: Optional[int] = None) -> Optional[dict]:
    """Segment listing of the newest CURRENT-format cache under
    ``cache_dir`` with watermark ≤ ``max_watermark`` — the
    ``GET /snapshot/export`` manifest a replica mirrors segments from
    (keto_tpu/replica/controller.py). Returns ``{"tag", "watermark",
    "format_version", "segments": [{"name", "size", "crc32"}, …]}`` with
    ``meta.json`` itself included (crc32 null — its integrity is the
    loader's JSON parse + the per-segment checksums it declares), or
    None when no loadable-by-this-binary cache exists."""
    base = Path(cache_dir)
    if not cache_dir or not base.is_dir():
        return None
    candidates = []
    for d in base.iterdir():
        wm = _parse_tag(d.name) if d.is_dir() else None
        if wm is None:
            continue
        if max_watermark is not None and wm > max_watermark:
            continue
        candidates.append((wm, d))
    for wm, d in sorted(candidates, reverse=True):
        try:
            meta = json.loads((d / "meta.json").read_text())
        except Exception:
            continue  # torn/in-flight save: try the next-newest
        if meta.get("format") != FORMAT_VERSION:
            continue
        segments = [
            {"name": name, "size": int(info["size"]), "crc32": int(info["crc32"])}
            for name, info in sorted(meta.get("segments", {}).items())
        ]
        segments.append(
            {
                "name": "meta.json",
                "size": (d / "meta.json").stat().st_size,
                "crc32": None,
            }
        )
        return {
            "tag": d.name,
            "watermark": int(wm),
            "format_version": FORMAT_VERSION,
            "segments": segments,
        }
    return None


def _prune(base: Path, keep: int) -> None:
    """Drop all but the ``keep`` newest caches PER FORMAT VERSION.

    Retention is format-version-aware on purpose: pruning by mtime/
    watermark across versions would let the first post-upgrade v5 save
    evict the only v4 cache — and a rollback (or a replica still running
    the previous binary against the same directory) would cold-start
    from a full rebuild. Caches of other recognized versions age out
    only against caches of their OWN version; directories that are not
    ``v<N>-w<M>``-shaped at all are junk and removed. Dot-prefixed
    entries (in-flight ``.tmp-`` saves, ``.quarantine-`` forensics) are
    managed by their own lifecycles and skipped here."""
    by_fmt: dict[int, list] = {}
    for d in base.iterdir():
        if not d.is_dir() or d.name.startswith("."):
            continue
        parsed = _parse_any_tag(d.name)
        if parsed is None:
            shutil.rmtree(d, ignore_errors=True)  # not a cache dir at all
        else:
            fmt, wm = parsed
            by_fmt.setdefault(fmt, []).append((wm, d))
    for entries in by_fmt.values():
        entries.sort(reverse=True)
        for _, d in entries[keep:]:
            shutil.rmtree(d, ignore_errors=True)


def _quarantine(d: Path, stats=None) -> None:
    """Move a corrupt/unreadable cache aside instead of deleting it (an
    operator can post-mortem the torn segment) and never serve from it
    again. Bounded: only the newest QUARANTINE_KEEP quarantines are
    kept."""
    base = d.parent
    target = base / f".quarantine-{d.name}-{os.getpid()}"
    try:
        if target.exists():
            shutil.rmtree(target, ignore_errors=True)
        os.replace(d, target)
    except OSError:
        shutil.rmtree(d, ignore_errors=True)  # rename refused — just drop it
    if stats is not None:
        stats.incr("cache_quarantined")
    quarantines = sorted(
        (q for q in base.iterdir() if q.name.startswith(".quarantine-")),
        key=lambda q: q.stat().st_mtime,
        reverse=True,
    )
    for q in quarantines[QUARANTINE_KEEP:]:
        shutil.rmtree(q, ignore_errors=True)


#: verification workers: crc32 releases the GIL on large buffers, so a
#: cold-start verify is parallel real I/O + checksum work, bounded by
#: the slowest segment group instead of the byte sum
VERIFY_WORKERS = 4


def _verify_one(d: Path, name: str, want: dict) -> None:
    f = d / name
    if not f.is_file():
        raise CacheCorrupt(f"{d.name}/{name}: segment missing")
    size = f.stat().st_size
    if size != want.get("size"):
        raise CacheCorrupt(
            f"{d.name}/{name}: size {size} != recorded {want.get('size')}"
            " (torn write?)"
        )
    crc = _file_crc(f)
    if crc != want.get("crc32"):
        raise CacheCorrupt(
            f"{d.name}/{name}: crc32 {crc:#x} != recorded "
            f"{int(want.get('crc32', 0)):#x} (corrupt segment)"
        )


def _verify_segments(d: Path, meta: dict) -> None:
    """Integrity gate: every data file must match the manifest recorded
    at save time, and no manifest entry may be missing. Segments verify
    CONCURRENTLY (the v5 segmented layout's load-side win — zlib.crc32
    releases the GIL, so checksum throughput scales with workers).
    Raises CacheCorrupt on any mismatch."""
    segments = meta.get("segments")
    if not isinstance(segments, dict):
        raise CacheCorrupt(f"{d.name}: meta.json has no segment manifest")
    items = list(segments.items())
    if len(items) <= 2:
        for name, want in items:
            _verify_one(d, name, want)
        return
    with ThreadPoolExecutor(max_workers=VERIFY_WORKERS) as pool:
        futures = [pool.submit(_verify_one, d, name, want) for name, want in items]
        for fut in futures:
            fut.result()  # first corrupt segment propagates CacheCorrupt


def _parse_tag(name: str) -> Optional[int]:
    prefix = f"v{FORMAT_VERSION}-w"
    if not name.startswith(prefix):
        return None
    try:
        return int(name[len(prefix):])
    except ValueError:
        return None


_ANY_TAG_RE = re.compile(r"^v(\d+)-w(\d+)$")


def _parse_any_tag(name: str) -> Optional[tuple[int, int]]:
    """``(format, watermark)`` for ANY version's cache directory, or
    None for non-cache junk — retention (``_prune``) must recognize
    other versions' caches without being able to load them."""
    m = _ANY_TAG_RE.match(name)
    if m is None:
        return None
    return int(m.group(1)), int(m.group(2))


def load_snapshot(path: str, verify: bool = True, sorter=None) -> GraphSnapshot:
    """Reload one cached snapshot directory (mmap — arrays page lazily).
    ``sorter`` rides into the list-layout re-derivation (the one
    compute-bound step of a reload) so a cold start can run its sorts on
    the device (keto_tpu/graph/device_build.py).

    ``verify`` checks every segment's size and crc32 against the manifest
    recorded at save time before anything is served from the cache —
    sequential reads at crc32 throughput, still orders of magnitude
    cheaper than the ingest+build it replaces. Raises CacheCorrupt on any
    mismatch (including a torn meta.json, surfaced as the JSON error)."""
    d = Path(path)
    try:
        meta = json.loads((d / "meta.json").read_text())
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise CacheCorrupt(f"{d.name}/meta.json unreadable: {e}") from None
    if meta.get("format") != FORMAT_VERSION:
        raise ValueError(f"cache format {meta.get('format')} != {FORMAT_VERSION}")
    if verify:
        _verify_segments(d, meta)
    interned = CachedInterned(d, meta)
    mm = lambda name: np.load(d / name, mmap_mode="r")  # noqa: E731
    n_shards = int(meta.get("shards", 1))
    if n_shards > 1:
        # per-shard stripes reassemble concurrently — the mesh cold
        # start's parallel-shard load; concatenation in shard order is
        # byte-identical to the single-file layout by construction
        def load_bucket(i):
            stripes = [mm(f"bucket_{i}_s{s}.npy") for s in range(n_shards)]
            return np.concatenate([np.asarray(a) for a in stripes], axis=0)

        with ThreadPoolExecutor(max_workers=VERIFY_WORKERS) as pool:
            nbrs_list = list(pool.map(load_bucket, range(len(meta["buckets"]))))
        buckets = [
            Bucket(offset=int(b["offset"]), n=int(b["n"]), nbrs=nbrs_list[i])
            for i, b in enumerate(meta["buckets"])
        ]
    else:
        buckets = [
            Bucket(offset=int(b["offset"]), n=int(b["n"]), nbrs=mm(f"bucket_{i}.npy"))
            for i, b in enumerate(meta["buckets"])
        ]
    labels = None
    lm = meta.get("labels")
    if lm is not None:
        from keto_tpu.graph.labels import LabelIndex

        labels = LabelIndex(
            n=int(lm["n"]),
            out_lab=mm("lab_out.npy"),
            in_lab=mm("lab_in.npy"),
            processed=np.asarray(mm("lab_processed.npy")).astype(bool),
            out_ok=np.asarray(mm("lab_out_ok.npy")).astype(bool),
            in_ok=np.asarray(mm("lab_in_ok.npy")).astype(bool),
            max_width=int(lm["max_width"]),
            n_landmarks=int(lm["n_landmarks"]),
            n_entries=int(lm.get("n_entries", 0)),
            backend=str(lm.get("backend", "host")),
        )
    snap = GraphSnapshot(
        snapshot_id=int(meta["watermark"]),
        num_sets=int(meta["num_sets"]),
        num_leaves=int(meta["num_leaves"]),
        num_active=int(meta["num_active"]),
        num_int=int(meta["num_int"]),
        num_live=int(meta["num_live"]),
        n_peeled=int(meta["n_peeled"]),
        buckets=buckets,
        interned=interned,
        raw2dev=mm("raw2dev.npy"),
        wild_ns_ids=frozenset(meta["wild_ns_ids"]),
        fwd_indptr=mm("fwd_indptr.npy"),
        fwd_indices=mm("fwd_indices.npy"),
        sink_indptr=mm("sink_indptr.npy"),
        sink_indices=mm("sink_indices.npy"),
        labels=labels,
    )
    # reverse-query orientations: the persisted transposed CSR mmaps;
    # the bucketed list layouts re-derive from the forward CSR (shared
    # builder — identical to a from-scratch build)
    from keto_tpu.graph.snapshot import build_list_layouts

    snap.rev_indptr = mm("rev_indptr.npy")
    snap.rev_indices = mm("rev_indices.npy")
    fi = np.asarray(snap.fwd_indptr)
    snap.lay_fwd, snap.lay_rev = build_list_layouts(
        fi, np.asarray(snap.fwd_indices), fi.shape[0] - 1, snap.sink_base,
        sorter=sorter,
    )
    return snap


def load_latest(
    cache_dir: str, max_watermark: Optional[int] = None, stats=None, sorter=None
) -> Optional[GraphSnapshot]:
    """Newest loadable cache under ``cache_dir`` with watermark ≤
    ``max_watermark`` (the store's current watermark — a cache AHEAD of
    the store belongs to other data and must never serve), or None.

    A cache that fails its integrity verification is QUARANTINED (moved
    aside, counted into ``stats`` as ``cache_quarantined`` when a
    MaintenanceStats-like sink is given) and the next-newest candidate is
    tried — the recovery contract is "loads clean or is rejected", never
    wrong decisions and never a crash."""
    base = Path(cache_dir)
    if not base.is_dir():
        return None
    candidates = []
    for d in base.iterdir():
        wm = _parse_tag(d.name) if d.is_dir() else None
        if wm is None:
            continue
        if max_watermark is not None and wm > max_watermark:
            continue
        candidates.append((wm, d))
    for _, d in sorted(candidates, reverse=True):
        try:
            snap = load_snapshot(str(d), sorter=sorter)
            # the cold-start upload the HBM governor is about to plan
            # (keto_tpu/driver/hbm.py): surface its size at load time.
            # Counter-only stats sinks simply skip the gauge.
            set_gauge = getattr(stats, "set_gauge", None)
            if set_gauge is not None:
                set_gauge("cache_loaded_bytes", snap.bucket_device_bytes())
            return snap
        except CacheCorrupt:
            _quarantine(d, stats=stats)  # rejected; rebuild path takes over
        except Exception:
            continue  # unreadable for other reasons → try the next
    return None
