"""Streaming, overlapped snapshot construction: scan → intern → layout.

The legacy cold start was strictly serial: a blocking full-table read
(``snapshot_rows``) materialized every row, THEN interning ran, THEN the
host lay out the device arrays — at 50M tuples, minutes in which the
store connection, the CPU interner, and the accelerator each sat idle
two-thirds of the time. This module runs the stages as a pipeline:

1. **Streaming scan** — the persister's chunked-cursor seam
   (``snapshot_scan`` on keto_tpu/persistence/sql_base.py and
   memory.py) hands over row chunks as they arrive, in the store's
   ORDER BY order;
2. **Overlapped intern** — each chunk feeds the native streaming
   builder (native/ingest.cpp ``stream_build_*``): a worker pool
   interns chunk *k* while the scan fetches chunk *k+1*, and the
   deterministic chunk-order merge reproduces the serial
   first-occurrence ids bit-identically. Without the native library the
   chunks intern through ``IncrementalInterner`` — same ids, no
   thread-level overlap;
3. **Device-side layout** — ``layout_snapshot`` with a
   ``DeviceSorter`` (keto_tpu/graph/device_build.py) runs the edge-scale
   stable sorts on the accelerator.

``BuildProgress`` is the observability spine of the pipeline: the
engine exposes it through ``health()`` (a STARTING boot reports
``{phase, pct}`` instead of a silent wait — keto_tpu/driver/health.py)
and the ``keto_build_*`` metric families bridge it into /metrics
(keto_tpu/driver/registry.py).

A transient store failure mid-scan aborts the in-flight builder and the
caller's retry policy (the engine's ``_read_store`` → x/retry seam)
re-runs the whole attempt with a fresh builder — chunks are never
replayed into a half-fed interner.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Callable, Optional

from keto_tpu.graph.interner import IncrementalInterner
from keto_tpu.graph.snapshot import GraphSnapshot, build_snapshot, layout_snapshot

#: default rows per scan chunk: large enough that per-chunk overheads
#: (pack, enqueue, shard tables) amortize, small enough that the intern
#: pool stays busy while the cursor fetches the next chunk
DEFAULT_CHUNK_ROWS = 262144

#: build phases in pipeline order; "idle" means no build in flight
PHASES = ("scan", "intern", "device_build", "labels", "cache_save")

#: per-phase weight of the pct estimate (scan/intern dominate at scale;
#: labels/cache_save land after the snapshot already serves)
_PCT_WEIGHTS = {
    "scan": 0.35, "intern": 0.25, "device_build": 0.30,
    "labels": 0.10, "cache_save": 0.0,
}


class BuildProgress:
    """Thread-safe phase/progress tracker for snapshot builds.

    Counters (rows/edges ingested) are cumulative across builds — they
    bridge to monotone ``keto_build_*_total`` families — while phase and
    per-phase durations describe the in-flight (or most recent) build.
    ``attach_histogram`` mirrors phase durations into a labeled
    /metrics histogram the same way DurationStats mirrors slice times.
    """

    def __init__(self, stats=None):
        self._lock = threading.Lock()
        self._phase = "idle"
        self._rows = 0
        self._edges = 0
        self._durations: dict[str, float] = {}
        self._done: set[str] = set()
        self._hist = None
        self._stats = stats  # MaintenanceStats or None

    def attach_histogram(self, histogram) -> None:
        """Mirror phase durations into ``histogram`` (anything with
        ``observe((phase,), seconds)``)."""
        self._hist = histogram

    # -- build lifecycle -----------------------------------------------------

    def start(self) -> None:
        """A new full build begins: reset the per-build view (cumulative
        counters keep counting)."""
        with self._lock:
            self._durations = {}
            self._done = set()
            self._phase = "scan"

    def finish(self) -> None:
        with self._lock:
            self._phase = "idle"

    @contextlib.contextmanager
    def phase(self, name: str):
        """Run one pipeline phase: sets the live phase gauge, records
        the duration on exit (into the build view, the maintenance
        stats, and the attached histogram)."""
        with self._lock:
            self._phase = name
        t0 = time.monotonic()
        try:
            yield self
        finally:
            self.observe(name, time.monotonic() - t0)
            with self._lock:
                self._phase = "idle"

    def set_phase(self, name: str) -> None:
        with self._lock:
            self._phase = name

    def observe(self, name: str, seconds: float) -> None:
        """Record ``seconds`` spent in phase ``name`` (additive — the
        streaming scan attributes fetch time and intern time separately
        out of one interleaved loop)."""
        s = max(0.0, float(seconds))
        with self._lock:
            self._durations[name] = self._durations.get(name, 0.0) + s
            self._done.add(name)
        hist = self._hist
        if hist is not None:
            hist.observe((name,), s)
        if self._stats is not None:
            self._stats.observe_ms(f"build_{name}", s * 1e3)

    def add_rows(self, n: int) -> None:
        with self._lock:
            self._rows += int(n)

    def add_edges(self, n: int) -> None:
        with self._lock:
            self._edges += int(n)

    # -- read side -----------------------------------------------------------

    @property
    def rows_ingested(self) -> int:
        with self._lock:
            return self._rows

    @property
    def edges_ingested(self) -> int:
        with self._lock:
            return self._edges

    @property
    def current_phase(self) -> str:
        with self._lock:
            return self._phase

    def pct(self) -> float:
        """Coarse completion estimate of the in-flight build: completed
        phases count their full weight, the live phase half of its —
        honest about being an estimate (the scan does not know the table
        size), monotone enough for a progress probe."""
        with self._lock:
            phase = self._phase
            done = set(self._done)
        if phase == "idle":
            return 1.0 if done else 0.0
        got = sum(_PCT_WEIGHTS.get(p, 0.0) for p in done if p != phase)
        got += 0.5 * _PCT_WEIGHTS.get(phase, 0.0)
        return round(min(0.99, got), 3)

    def durations(self) -> dict:
        """Per-phase seconds of the current/most recent build."""
        with self._lock:
            return dict(self._durations)

    def snapshot(self) -> dict:
        pct = self.pct()
        with self._lock:
            return {
                "phase": self._phase,
                "pct": pct,
                "rows_ingested": self._rows,
                "edges_ingested": self._edges,
                "durations_s": {k: round(v, 3) for k, v in self._durations.items()},
            }


def _scan_and_intern(store, wild_ns_ids, progress, chunk_rows):
    """One streaming scan+intern attempt: returns ``(interned, wm)``.
    Raises on store failure with the in-flight native builder aborted —
    the caller's retry policy re-runs with fresh state."""
    from keto_tpu.graph.native import NativeStreamBuilder

    state = {
        "native": NativeStreamBuilder.create(wild_ns_ids),
        "py": None,
        "rows": [],  # chunk refs: fallback insurance while native feeds
        "intern_s": 0.0,
    }
    if state["native"] is None:
        state["py"] = IncrementalInterner(wild_ns_ids)

    def on_chunk(chunk):
        t0 = time.monotonic()
        nb = state["native"]
        if nb is not None:
            state["rows"].append(chunk)
            if not nb.feed(chunk):
                # native stream died (framing rejection): replay the
                # accumulated chunks through the Python interner —
                # identical ids, the stream just loses its overlap
                state["native"] = None
                it = IncrementalInterner(wild_ns_ids)
                for c in state["rows"]:
                    it.add_rows(c)
                state["rows"] = []
                state["py"] = it
        else:
            state["py"].add_rows(chunk)
        state["intern_s"] += time.monotonic() - t0
        progress.add_rows(len(chunk))

    progress.set_phase("scan")
    t_scan = time.monotonic()
    try:
        wm = store.snapshot_scan(on_chunk, chunk_rows=chunk_rows)
    except BaseException:
        if state["native"] is not None:
            state["native"].abort()
        raise
    scan_wall = time.monotonic() - t_scan

    progress.set_phase("intern")
    t0 = time.monotonic()
    if state["native"] is not None:
        g = state["native"].finish()
        if g is None:
            it = IncrementalInterner(wild_ns_ids)
            for c in state["rows"]:
                it.add_rows(c)
            g = it.finish()
    else:
        g = state["py"].finish()
    state["intern_s"] += time.monotonic() - t0

    # attribute the interleaved loop honestly: fetch time is the scan
    # wall minus the time on_chunk spent packing/feeding; the intern
    # phase is that packing/feeding plus the merge tail. With the native
    # pool the worker time overlaps the fetches entirely — which is the
    # point — so scan_s + intern_s may exceed the pipeline wall.
    in_scan_intern = min(state["intern_s"], scan_wall)
    progress.observe("scan", scan_wall - in_scan_intern)
    progress.observe("intern", state["intern_s"])
    return g, wm


def full_build(
    store,
    wild_ns_ids=frozenset(),
    *,
    peel_seed_cap: float = 4.0,
    sorter=None,
    progress: Optional[BuildProgress] = None,
    read_retry: Optional[Callable] = None,
    chunk_rows: int = DEFAULT_CHUNK_ROWS,
) -> GraphSnapshot:
    """Build a full snapshot from ``store`` at its current watermark via
    the fastest available path, in preference order:

    1. the store's sorted **column bundle** (``snapshot_columns`` right
       after a bulk load) through the native zero-copy interner — no row
       objects at all;
    2. the **streaming scan+intern pipeline** (``snapshot_scan``) when
       the store prefers it (SQL persisters: I/O overlaps interning);
    3. the legacy ``snapshot_rows`` one-shot.

    All three produce bit-identical snapshots; ``read_retry`` (the
    engine's ``_read_store`` — x/retry with backoff) wraps each store
    read so a transient failure mid-scan retries with fresh state.
    """
    prog = progress if progress is not None else BuildProgress()
    read_retry = read_retry or (lambda fn, *a: fn(*a))
    prog.start()
    try:
        # -- 1) column-bundle fast path (native interner required) -----------
        cols_fn = getattr(store, "snapshot_columns", None)
        if cols_fn is not None:
            wm = store.watermark()
            columns = cols_fn(wm)
            if columns is not None:
                from keto_tpu.graph import native as native_mod

                lib = native_mod.load_library()
                if lib is not None:
                    with prog.phase("intern"):
                        g = native_mod.native_intern_columns(
                            lib, columns, wild_ns_ids
                        )
                    if g is not None:
                        prog.add_rows(int(columns["ns"].shape[0]))
                        return layout_snapshot(
                            g, wm, wild_ns_ids, peel_seed_cap=peel_seed_cap,
                            sorter=sorter, progress=prog,
                        )

        # -- 2) streaming scan+intern ----------------------------------------
        scan_fn = getattr(store, "snapshot_scan", None)
        if scan_fn is not None and getattr(store, "scan_chunks_preferred", True):
            g, wm = read_retry(
                lambda: _scan_and_intern(store, wild_ns_ids, prog, chunk_rows)
            )
            return layout_snapshot(
                g, wm, wild_ns_ids, peel_seed_cap=peel_seed_cap,
                sorter=sorter, progress=prog,
            )

        # -- 3) legacy one-shot ----------------------------------------------
        with prog.phase("scan"):
            rows, wm = read_retry(store.snapshot_rows)
        cols = cols_fn(wm) if cols_fn is not None else None
        return build_snapshot(
            rows, wm, wild_ns_ids, peel_seed_cap=peel_seed_cap,
            columns=cols, sorter=sorter, progress=prog,
        )
    finally:
        prog.finish()
