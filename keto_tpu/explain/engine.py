"""The explain engine: decision + witness + verification + audit record.

``ExplainEngine.explain`` answers "why" for one Check:

1. **Decide** through the serving engine itself — the TPU engine's streaming
   path with ``with_info=True`` (so the genuine route that decided —
   label / hybrid / bfs / host / cpu — is reported, not re-derived), or the
   reference engine when that is what serves the scope (tenants, fallback).
2. **Reconstruct** the witness: device routes back-trace the subject-set
   closure against the Manager (``build_witness`` — BFS with parent
   pointers, shortest path); the cpu route threads the reference engine's
   own traversal (``oracle_witness``). Denies carry the BFS's
   frontier-exhaustion certificate.
3. **Verify** edge-by-edge against the Manager before returning. A witness
   that fails verification is a bug: counted
   (``keto_witness_verify_failures_total``), recorded for the flight
   recorder, and the response falls back to the CPU oracle's witness.
4. **Enrich** label-route grants with the intersection's winning landmark
   (``TpuCheckEngine.label_witness_info`` — the argmin the device kernel
   extracts), naming the hub node the 2-hop proof went through.
5. **Record** the decision in the durable decision log when one is
   configured, witness included, so the audit trail carries provenance.

The engine is scope-shaped: the default tenant's instance wraps the TPU
engine + root Manager; tenant instances wrap that tenant's fault-in engine +
store view (keto_tpu/driver/tenants.py). None of this ever runs on the check
hot path — explain is its own endpoint, and hot-path decision-log sampling
is a separate, witness-free record (servers/rest.py).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Callable, Optional

import numpy as np

from keto_tpu.explain.decision_log import DecisionLog
from keto_tpu.explain.witness import (
    DEFAULT_MAX_HEADS,
    build_witness,
    oracle_witness,
    verify_witness,
)
from keto_tpu.relationtuple.manager import Manager
from keto_tpu.relationtuple.model import RelationTuple


class ExplainEngine:
    def __init__(
        self,
        engine: Any,
        manager: Manager,
        *,
        decision_log: Optional[DecisionLog] = None,
        page_size: int = 0,
        max_heads: int = DEFAULT_MAX_HEADS,
        on_verify_failure: Optional[Callable[[dict[str, Any]], None]] = None,
        decide: Optional[Callable[..., tuple[bool, str, Optional[int]]]] = None,
    ):
        self._engine = engine
        self._manager = manager
        #: optional decide override — tenant contexts route the decision
        #: through their dispatch guard so engine eviction can never leave
        #: an explain call holding a closed engine
        self._decide_fn = decide
        self._decision_log = decision_log
        self._page_size = page_size
        self._max_heads = max_heads
        self._on_verify_failure = on_verify_failure
        self._lock = threading.Lock()
        #: per-route request counts — keto_explain_requests_total{route}
        self.requests_by_route: dict[str, int] = {}
        #: witnesses that failed edge-by-edge verification — each one is a
        #: bug in the producing route; keto_witness_verify_failures_total
        self.verify_failures = 0
        #: recent verify failures, flight-recorder section material
        self.recent_failures: deque = deque(maxlen=8)

    # -- decision -------------------------------------------------------------

    def _decide(self, rt: RelationTuple, at_least) -> tuple[bool, str, Optional[int]]:
        if self._decide_fn is not None:
            return self._decide_fn(rt, at_least)
        return self.decide_with(self._engine, self._manager, rt, at_least)

    @staticmethod
    def decide_with(
        eng: Any, manager: Manager, rt: RelationTuple, at_least
    ) -> tuple[bool, str, Optional[int]]:
        """One check through ``eng``, returning ``(allowed, route,
        snaptoken)`` with the route that actually decided it (the
        stream's with_info route label; "cpu" for the reference engine)."""
        if hasattr(eng, "batch_check_stream_with_token"):
            allowed = False
            route = "host"
            if getattr(eng, "STREAM_INFO", False):
                gen, token = eng.batch_check_stream_with_token(
                    [rt], at_least=at_least, ordered=False, with_info=True
                )
                for _off, out, info in gen:
                    allowed = bool(np.asarray(out).reshape(-1)[0])
                    route = str(info.get("route", route))
            else:
                gen, token = eng.batch_check_stream_with_token(
                    [rt], at_least=at_least, ordered=False
                )
                for _off, out in gen:
                    allowed = bool(np.asarray(out).reshape(-1)[0])
            return allowed, route, token
        allowed = bool(eng.subject_is_allowed(rt))
        token = None
        wm = getattr(manager, "watermark", None)
        if callable(wm):
            try:
                token = int(wm())
            except Exception:
                token = None
        return allowed, "cpu", token

    # -- explain --------------------------------------------------------------

    def explain(
        self,
        requested: RelationTuple,
        *,
        at_least=None,
        trace_id: str = "",
        tenant: str = "default",
    ) -> dict[str, Any]:
        """Decide + reconstruct + verify + record; returns the response
        body for ``GET /check/explain`` (docs/concepts/explain.md)."""
        allowed, route, token = self._decide(requested, at_least)
        with self._lock:
            self.requests_by_route[route] = self.requests_by_route.get(route, 0) + 1

        path = None
        certificate = None
        witness_source = ""
        divergence = False

        if route == "cpu":
            # the oracle decided; its own traversal IS the witness
            path = oracle_witness(self._manager, requested, page_size=self._page_size)
            witness_source = "oracle"
            if allowed != (path is not None):
                divergence = True
            if path is None and not allowed:
                _, _, certificate = build_witness(
                    self._manager,
                    requested,
                    page_size=self._page_size,
                    max_heads=self._max_heads,
                )
        else:
            found, path, certificate = build_witness(
                self._manager,
                requested,
                page_size=self._page_size,
                max_heads=self._max_heads,
            )
            witness_source = "backtrace"
            if found != allowed:
                # the device route and the store-closure back-trace disagree
                # — a real bug (or an injected one); surface it loudly
                divergence = True

        verified = False
        if allowed:
            ok, reason = (
                verify_witness(self._manager, requested, path)
                if path
                else (False, "no witness path found for an allowed decision")
            )
            if not ok:
                self._note_failure(requested, route, tenant, path, reason)
                path = oracle_witness(
                    self._manager, requested, page_size=self._page_size
                )
                witness_source = "oracle-fallback"
                if path:
                    ok, _ = verify_witness(self._manager, requested, path)
            verified = bool(ok and path)
        elif divergence:
            # denied by the engine but the closure holds a path: count it
            # like a verify failure — it is the same class of bug
            self._note_failure(
                requested, route, tenant, path, "engine denied but closure grants"
            )
            certificate = None

        witness = [t.to_json() for t in path] if path else None
        resp: dict[str, Any] = {
            "allowed": allowed,
            "route": route,
            "snaptoken": str(token) if token is not None else "",
            "tuple": requested.to_json(),
            "witness": witness,
            "certificate": certificate,
            "verified": verified,
            "witness_source": witness_source if path else "",
        }
        if divergence:
            resp["decision_divergence"] = True
        if allowed and route in ("label", "hybrid"):
            lw = getattr(self._engine, "label_witness_info", None)
            if lw is not None:
                try:
                    landmark = lw(requested, at_least=at_least)
                except Exception:
                    landmark = None
                if landmark:
                    resp["landmark"] = landmark

        dl = self._decision_log
        if dl is not None:
            # explain calls are explicit audit actions: always recorded
            # (the 1-in-N sampling applies to hot-path checks only)
            dl.record(
                tenant,
                {
                    "kind": "explain",
                    "tuple": requested.to_json(),
                    "decision": allowed,
                    "route": route,
                    "witness": witness,
                    "certificate": certificate,
                    "snaptoken": resp["snaptoken"],
                    "trace_id": trace_id,
                },
            )
        return resp

    def _note_failure(
        self,
        requested: RelationTuple,
        route: str,
        tenant: str,
        path,
        reason: str,
    ) -> None:
        with self._lock:
            self.verify_failures += 1
            note = {
                "tuple": str(requested),
                "route": route,
                "tenant": tenant,
                "reason": reason,
                "witness": [str(t) for t in path] if path else None,
            }
            self.recent_failures.append(note)
        cb = self._on_verify_failure
        if cb is not None:
            try:
                cb(note)
            except Exception:  # keto-analyze: ignore[KTA401] the callback is the flight recorder; a recorder fault must not mask the verify-failure accounting above
                pass
